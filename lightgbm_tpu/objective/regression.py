"""Regression objectives.

TPU-native equivalents of the reference's regression family
(reference: src/objective/regression_objective.hpp; CUDA mirrors under
src/objective/cuda/). Each objective's (grad, hess) is a pure jitted
elementwise function over device arrays — XLA fuses it into one kernel,
the analogue of the reference's CUDA objective kernels writing into
device-resident gradient buffers.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import compile as obs_compile
from ..utils import log
from .base import ObjectiveFunction, weighted_percentile


def _apply_weight(grad, hess, weights):
    if weights is None:
        return grad, hess
    return grad * weights, hess * weights


class RegressionL2(ObjectiveFunction):
    """L2 loss (reference: RegressionL2loss,
    src/objective/regression_objective.hpp:127-139: grad = score - label,
    hess = 1; optional sqrt label transform)."""

    name = "regression"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)
        self._raw_label: Optional[np.ndarray] = None

    def _jit_key(self):
        # the L2/L1/MAPE gradient bodies read nothing off self — every
        # config-identical instance shares one compile per score shape
        return ()

    @property
    def is_constant_hessian(self) -> bool:
        return self.weights is None

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if self.sqrt:
            raw = np.asarray(metadata.label, dtype=np.float64)
            self._raw_label = raw
            trans = np.sign(raw) * np.sqrt(np.abs(raw))
            self.label = jax.device_put(trans.astype(np.float32))

    @obs_compile.instrument_jit_method("obj.regression_l2.grads")
    def _grads(self, score, label, weights):
        grad = score - label
        hess = jnp.ones_like(score)
        return _apply_weight(grad, hess, weights)

    def get_gradients(self, score):
        return self._grads(score, self.label, self.weights)

    def boost_from_score(self, class_id: int = 0) -> float:
        label = np.asarray(self.label, dtype=np.float64)
        if self.weights is not None:
            w = np.asarray(self.weights, dtype=np.float64)
            return float((label * w).sum() / w.sum())
        return float(label.mean())

    def convert_output(self, score):
        if self.sqrt:
            return np.sign(score) * score * score
        return score

    def to_string(self) -> str:
        return self.name + (" sqrt" if self.sqrt else "")


class RegressionL1(RegressionL2):
    """L1 loss (reference: RegressionL1loss,
    src/objective/regression_objective.hpp:217-236): grad = sign(diff),
    hess = 1; leaf outputs renewed to the weighted median of residuals
    (RenewTreeOutput at :253)."""

    name = "regression_l1"
    _renew_alpha = 0.5

    @property
    def is_constant_hessian(self) -> bool:
        return self.weights is None

    @obs_compile.instrument_jit_method("obj.regression_l1.grads")
    def _grads(self, score, label, weights):
        grad = jnp.sign(score - label)
        hess = jnp.ones_like(score)
        return _apply_weight(grad, hess, weights)

    def boost_from_score(self, class_id: int = 0) -> float:
        label = np.asarray(self.label, dtype=np.float64)
        w = (None if self.weights is None
             else np.asarray(self.weights, dtype=np.float64))
        return weighted_percentile(label, w, self._renew_alpha)

    @property
    def is_renew_tree_output(self) -> bool:
        return True

    def _renew_weights(self) -> Optional[np.ndarray]:
        return (None if self.weights is None
                else np.asarray(self.weights, dtype=np.float64))

    def renew_tree_output(self, tree, score, leaf_of_row, row_mask=None):
        label = np.asarray(self.label, dtype=np.float64)
        residual = label - score
        w = self._renew_weights()
        for leaf in range(tree.num_leaves):
            rows = leaf_of_row == leaf
            if row_mask is not None:
                rows &= row_mask
            if not rows.any():
                continue
            out = weighted_percentile(
                residual[rows], None if w is None else w[rows],
                self._renew_alpha)
            tree.set_leaf_output(leaf, out)


class RegressionHuber(RegressionL2):
    """Huber loss (reference: RegressionHuberLoss,
    src/objective/regression_objective.hpp:292+): grad = diff clipped to
    +-alpha, hess = 1; sqrt transform disabled."""

    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        if self.sqrt:
            log.warning("Cannot use sqrt transform in %s Regression, "
                        "will auto disable it", self.name)
            self.sqrt = False
        self.alpha = float(config.alpha)
        if self.alpha <= 0.0:
            log.fatal("alpha should be greater than 0")

    def _jit_key(self):
        return (self.alpha,)  # baked into the clip constants

    @obs_compile.instrument_jit_method("obj.huber.grads")
    def _grads(self, score, label, weights):
        diff = score - label
        grad = jnp.clip(diff, -self.alpha, self.alpha)
        hess = jnp.ones_like(score)
        return _apply_weight(grad, hess, weights)


class RegressionFair(RegressionL2):
    """Fair loss (reference: RegressionFairLoss,
    src/objective/regression_objective.hpp:352+): grad = c*x/(|x|+c),
    hess = c^2/(|x|+c)^2."""

    name = "fair"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.c = float(config.fair_c)

    def _jit_key(self):
        return (self.c,)

    @property
    def is_constant_hessian(self) -> bool:
        return False

    @obs_compile.instrument_jit_method("obj.fair.grads")
    def _grads(self, score, label, weights):
        x = score - label
        denom = jnp.abs(x) + self.c
        grad = self.c * x / denom
        hess = self.c * self.c / (denom * denom)
        return _apply_weight(grad, hess, weights)

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0


class RegressionPoisson(RegressionL2):
    """Poisson regression (reference: RegressionPoissonLoss,
    src/objective/regression_objective.hpp:407+): scores are log-scale;
    grad = exp(s) - label, hess = exp(s + poisson_max_delta_step);
    BoostFromScore = log(weighted mean label)."""

    name = "poisson"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.max_delta_step = float(config.poisson_max_delta_step)
        if self.max_delta_step <= 0.0:
            log.fatal("poisson_max_delta_step should be greater than 0")

    def _jit_key(self):
        # covers Gamma too (its body reads nothing; keying the shared
        # scalar is merely conservative)
        return (self.max_delta_step,)

    def _check_label(self, label: np.ndarray) -> None:
        if (label < 0).any():
            log.fatal("[%s]: at least one target label is negative" % self.name)
        if label.sum() <= 0.0:
            log.fatal("[%s]: sum of labels is zero" % self.name)

    @property
    def is_constant_hessian(self) -> bool:
        return False

    @obs_compile.instrument_jit_method("obj.poisson.grads")
    def _grads(self, score, label, weights):
        exp_score = jnp.exp(score)
        grad = exp_score - label
        hess = exp_score * np.exp(self.max_delta_step)
        return _apply_weight(grad, hess, weights)

    def boost_from_score(self, class_id: int = 0) -> float:
        mean = super().boost_from_score(class_id)
        return float(np.log(max(mean, 1e-20)))

    def convert_output(self, score):
        return np.exp(score)


class RegressionQuantile(RegressionL2):
    """Quantile regression (reference: RegressionQuantileloss,
    src/objective/regression_objective.hpp:478+): grad = (1-alpha) if
    score > label else -alpha, hess = 1; leaf renewal at the alpha
    percentile of residuals."""

    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)
        if not (0.0 < self.alpha < 1.0):
            log.fatal("alpha should be in (0, 1) for quantile objective")

    def _jit_key(self):
        return (self.alpha,)

    @property
    def is_constant_hessian(self) -> bool:
        return self.weights is None

    @obs_compile.instrument_jit_method("obj.quantile.grads")
    def _grads(self, score, label, weights):
        grad = jnp.where(score > label, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return _apply_weight(grad, hess, weights)

    def boost_from_score(self, class_id: int = 0) -> float:
        label = np.asarray(self.label, dtype=np.float64)
        w = (None if self.weights is None
             else np.asarray(self.weights, dtype=np.float64))
        return weighted_percentile(label, w, self.alpha)

    @property
    def is_renew_tree_output(self) -> bool:
        return True

    def renew_tree_output(self, tree, score, leaf_of_row, row_mask=None):
        label = np.asarray(self.label, dtype=np.float64)
        residual = label - score
        w = (None if self.weights is None
             else np.asarray(self.weights, dtype=np.float64))
        for leaf in range(tree.num_leaves):
            rows = leaf_of_row == leaf
            if row_mask is not None:
                rows &= row_mask
            if not rows.any():
                continue
            out = weighted_percentile(
                residual[rows], None if w is None else w[rows],
                self.alpha)
            tree.set_leaf_output(leaf, out)


class RegressionMAPE(RegressionL1):
    """MAPE (reference: RegressionMAPELOSS,
    src/objective/regression_objective.hpp:579+): per-row label weight
    1/max(1,|label|); grad = sign(diff)*label_weight, hess = label_weight
    (or user weight); renewal weighted by label_weight."""

    name = "mape"

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        raw = np.asarray(metadata.label, dtype=np.float64)
        lw = 1.0 / np.maximum(1.0, np.abs(raw))
        if metadata.weights is not None:
            lw = lw * np.asarray(metadata.weights, dtype=np.float64)
        if (np.abs(raw) < 1).any():
            log.warning(
                "Some label values are < 1 in absolute value. MAPE is "
                "unstable with such values, so LightGBM rounds them to "
                "1.0 when computing MAPE.")
        self.label_weight = jax.device_put(lw.astype(np.float32))
        self._label_weight_np = lw

    @property
    def is_constant_hessian(self) -> bool:
        return self.weights is None

    def get_gradients(self, score):
        return self._grads_mape(score, self.label, self.label_weight,
                                self.weights)

    @obs_compile.instrument_jit_method("obj.mape.grads")
    def _grads_mape(self, score, label, label_weight, weights):
        grad = jnp.sign(score - label) * label_weight
        hess = (jnp.ones_like(score) if weights is None else weights)
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        label = np.asarray(self.label, dtype=np.float64)
        return weighted_percentile(label, self._label_weight_np, 0.5)

    def _renew_weights(self) -> Optional[np.ndarray]:
        return self._label_weight_np


class RegressionGamma(RegressionPoisson):
    """Gamma regression (reference: RegressionGammaLoss,
    src/objective/regression_objective.hpp:679+): grad = 1 - label*exp(-s),
    hess = label*exp(-s)."""

    name = "gamma"

    @obs_compile.instrument_jit_method("obj.gamma.grads")
    def _grads(self, score, label, weights):
        exp_ns = jnp.exp(-score)
        grad = 1.0 - label * exp_ns
        hess = label * exp_ns
        return _apply_weight(grad, hess, weights)


class RegressionTweedie(RegressionPoisson):
    """Tweedie regression (reference: RegressionTweedieLoss,
    src/objective/regression_objective.hpp:716+): with rho =
    tweedie_variance_power, grad = -label*exp((1-rho)s) + exp((2-rho)s)."""

    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def _jit_key(self):
        return (self.max_delta_step, self.rho)

    def _check_label(self, label: np.ndarray) -> None:
        if (label < 0).any():
            log.fatal("[%s]: at least one target label is negative" % self.name)

    @obs_compile.instrument_jit_method("obj.tweedie.grads")
    def _grads(self, score, label, weights):
        exp_1 = jnp.exp((1.0 - self.rho) * score)
        exp_2 = jnp.exp((2.0 - self.rho) * score)
        grad = -label * exp_1 + exp_2
        hess = (-label * (1.0 - self.rho) * exp_1
                + (2.0 - self.rho) * exp_2)
        return _apply_weight(grad, hess, weights)
