"""Cross-entropy objectives over [0,1]-valued labels.

TPU-native equivalents of the reference's CrossEntropy /
CrossEntropyLambda (reference: src/objective/xentropy_objective.hpp:21,148).
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log
from ..obs import compile as obs_compile
from .base import ObjectiveFunction

_EPS = 1e-12


class CrossEntropy(ObjectiveFunction):
    """loss(y, p, w) = (-(1-y) log(1-p) - y log p) * w, p = sigmoid(score)
    (reference: xentropy_objective.hpp:82-92): grad = z - y,
    hess = z(1-z), scaled by weight."""

    name = "cross_entropy"

    def _check_label(self, label: np.ndarray) -> None:
        if label.min() < 0.0 or label.max() > 1.0:
            log.fatal("[%s]: label must be in [0, 1]" % self.name)
        log.info("[%s]: (objective) labels passed interval [0, 1] check"
                 % self.name)

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.weights is not None:
            w = np.asarray(metadata.weights)
            if (w < 0).any():
                log.fatal("[%s]: at least one weight is negative" % self.name)
            if w.sum() == 0.0:
                log.fatal("[%s]: sum of weights is zero" % self.name)

    def _jit_key(self):
        return ()  # the body reads nothing off self

    @obs_compile.instrument_jit_method("obj.xentropy.grads")
    def _grads(self, score, label, weights):
        z = jax.nn.sigmoid(score)
        grad = z - label
        hess = z * (1.0 - z)
        if weights is not None:
            grad = grad * weights
            hess = hess * weights
        return grad, hess

    def get_gradients(self, score):
        return self._grads(score, self.label, self.weights)

    def boost_from_score(self, class_id: int = 0) -> float:
        label = np.asarray(self.label, dtype=np.float64)
        if self.weights is not None:
            w = np.asarray(self.weights, dtype=np.float64)
            pavg = (label * w).sum() / w.sum()
        else:
            pavg = label.mean()
        pavg = min(max(pavg, _EPS), 1.0 - _EPS)
        initscore = float(np.log(pavg / (1.0 - pavg)))
        log.info("[%s:BoostFromScore]: pavg = %f -> initscore = %f"
                 % (self.name, pavg, initscore))
        return initscore

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-score))


class CrossEntropyLambda(ObjectiveFunction):
    """Alternative parameterization with p = 1 - exp(-lambda * w),
    lambda = log(1 + exp(f)) (reference: xentropy_objective.hpp:148-216).
    Unweighted it reduces to CrossEntropy."""

    name = "cross_entropy_lambda"

    def _check_label(self, label: np.ndarray) -> None:
        if label.min() < 0.0 or label.max() > 1.0:
            log.fatal("[%s]: label must be in [0, 1]" % self.name)

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.weights is not None:
            w = np.asarray(metadata.weights)
            if (w <= 0).any():
                log.fatal("[%s]: at least one weight is non-positive"
                          % self.name)

    def _jit_key(self):
        return ()  # the body reads nothing off self

    @obs_compile.instrument_jit_method("obj.xentropy_lambda.grads")
    def _grads(self, score, label, weights):
        if weights is None:
            z = jax.nn.sigmoid(score)
            return z - label, z * (1.0 - z)
        w, y = weights, label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        grad = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        hess = a * (1.0 + y * b)
        return grad, hess

    def get_gradients(self, score):
        return self._grads(score, self.label, self.weights)

    def boost_from_score(self, class_id: int = 0) -> float:
        label = np.asarray(self.label, dtype=np.float64)
        if self.weights is not None:
            w = np.asarray(self.weights, dtype=np.float64)
            havg = (label * w).sum() / w.sum()
        else:
            havg = label.mean()
        initscore = float(np.log(max(np.expm1(havg), _EPS)))
        log.info("[%s:BoostFromScore]: havg = %f -> initscore = %f"
                 % (self.name, havg, initscore))
        return initscore

    def convert_output(self, score):
        return np.log1p(np.exp(score))
