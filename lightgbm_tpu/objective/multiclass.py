"""Multiclass objectives: softmax and one-vs-all.

TPU-native equivalents of the reference's MulticlassSoftmax /
MulticlassOVA (reference: src/objective/multiclass_objective.hpp:22,176).
Scores and gradients are [N, K] device arrays; the softmax gradient is a
single fused XLA kernel over the class axis (the reference loops classes
with a rescaling factor K/(K-1), :31,101-105).
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log
from ..obs import compile as obs_compile
from .base import ObjectiveFunction
from .binary import BinaryLogloss


class MulticlassSoftmax(ObjectiveFunction):
    """Softmax objective (reference: multiclass_objective.hpp:22):
    p = softmax(score_row); grad_k = p_k - 1{y=k};
    hess_k = factor * p_k * (1 - p_k), factor = K/(K-1)."""

    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            log.fatal("num_class should be >= 2 for multiclass")
        self.factor = self.num_class / (self.num_class - 1.0)

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_class

    def _check_label(self, label: np.ndarray) -> None:
        li = label.astype(np.int32)
        if not np.allclose(li, label):
            log.fatal("Label must be int type for multiclass")
        if li.min() < 0 or li.max() >= self.num_class:
            log.fatal("Label must be in [0, %d) for multiclass, but found "
                      "%d" % (self.num_class, int(li.max())))

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        li = np.asarray(metadata.label).astype(np.int32)
        self.label_onehot = jax.device_put(
            np.eye(self.num_class, dtype=np.float32)[li])

    def _jit_key(self):
        return (self.num_class,)  # the body bakes self.factor = K/(K-1)

    @obs_compile.instrument_jit_method("obj.multiclass.grads")
    def _grads(self, score, label_onehot, weights):
        p = jax.nn.softmax(score, axis=1)
        grad = p - label_onehot
        hess = self.factor * p * (1.0 - p)
        if weights is not None:
            grad = grad * weights[:, None]
            hess = hess * weights[:, None]
        return grad, hess

    def get_gradients(self, score):
        return self._grads(score, self.label_onehot, self.weights)

    def convert_output(self, score):
        e = np.exp(score - score.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    def to_string(self) -> str:
        return "%s num_class:%d" % (self.name, self.num_class)


class MulticlassOVA(ObjectiveFunction):
    """One-vs-all (reference: MulticlassOVA,
    multiclass_objective.hpp:176): K independent BinaryLogloss objectives,
    one per class column."""

    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        if self.num_class < 2:
            log.fatal("num_class should be >= 2 for multiclassova")
        self.sigmoid = float(config.sigmoid)
        self._binary: List[BinaryLogloss] = [
            BinaryLogloss(config, is_pos=_make_is_pos(k))
            for k in range(self.num_class)]

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_class

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        for b in self._binary:
            b.init(metadata, num_data)

    def get_gradients(self, score):
        grads, hesss = [], []
        for k, b in enumerate(self._binary):
            g, h = b.get_gradients(score[:, k])
            grads.append(g)
            hesss.append(h)
        return jnp.stack(grads, axis=1), jnp.stack(hesss, axis=1)

    def boost_from_score(self, class_id: int = 0) -> float:
        return self._binary[class_id].boost_from_score(0)

    def class_need_train(self, class_id: int) -> bool:
        return self._binary[class_id].class_need_train(0)

    def convert_output(self, score):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * score))

    def to_string(self) -> str:
        return "%s num_class:%d sigmoid:%g" % (
            self.name, self.num_class, self.sigmoid)


def _make_is_pos(k: int):
    return lambda y: np.asarray(y).astype(np.int32) == k
