"""DCG/NDCG math shared by the lambdarank objective and the ndcg metric.

Equivalent of the reference's ``DCGCalculator``
(reference: include/LightGBM/metric.h:68, src/metric/dcg_calculator.cpp):
label gain table (default 2^l - 1), position discounts 1/log2(2 + rank),
and max-DCG@k over a label multiset.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..utils import log

kMaxPosition = 10000


def default_label_gain(num: int = 31) -> np.ndarray:
    """2^i - 1 (reference: DCGCalculator::DefaultLabelGain,
    src/metric/dcg_calculator.cpp:33)."""
    return (2.0 ** np.arange(num)) - 1.0


def resolve_label_gain(config_label_gain: Sequence[float]) -> np.ndarray:
    if config_label_gain:
        return np.asarray(config_label_gain, dtype=np.float64)
    return default_label_gain()


def discounts(n: int) -> np.ndarray:
    """1/log2(2 + i) for rank i (reference: DCGCalculator::Init)."""
    return 1.0 / np.log2(2.0 + np.arange(n))


def check_label(label: np.ndarray, num_gains: int) -> None:
    li = label.astype(np.int64)
    if not np.allclose(li, label):
        log.fatal("label should be int type (met %f) for ranking task"
                  % float(label[np.argmax(li != label)]))
    if li.min() < 0:
        log.fatal("Label should be >= 0 in ranking task")
    if li.max() >= num_gains:
        log.fatal("Label %d is not less than the number of label mappings "
                  "(%d)" % (int(li.max()), num_gains))


def max_dcg_at_k(k: int, label: np.ndarray, label_gain: np.ndarray) -> float:
    """Max achievable DCG@k: labels sorted descending (reference:
    DCGCalculator::CalMaxDCGAtK, src/metric/dcg_calculator.cpp:54)."""
    n = len(label)
    k = min(k, n)
    if k <= 0:
        return 0.0
    top = np.sort(label.astype(np.int64))[::-1][:k]
    return float((discounts(k) * label_gain[top]).sum())


def dcg_at_k(k: int, label: np.ndarray, score: np.ndarray,
             label_gain: np.ndarray) -> float:
    """DCG@k of a scored ranking (reference: DCGCalculator::CalDCGAtK).
    Ties broken by stable argsort of descending score, matching the
    reference's stable partial sort."""
    n = len(label)
    k = min(k, n)
    if k <= 0:
        return 0.0
    order = np.argsort(-score, kind="stable")[:k]
    top = label.astype(np.int64)[order]
    return float((discounts(k) * label_gain[top]).sum())
