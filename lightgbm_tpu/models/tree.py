"""Decision tree model — flat-array representation, host-side.

Equivalent of the reference's ``Tree`` (include/LightGBM/tree.h:25,
src/io/tree.cpp). The tree is *built* by the device learner; this class is
the host mirror used for model storage, prediction over raw feature values,
and LightGBM-v3-compatible text serialization (src/io/tree.cpp:339
``ToString``, :682 parse ctor) so models interchange with the reference.

Conventions (same as reference):
- internal nodes are numbered 0..num_leaves-2 in creation order; a child
  pointer >= 0 is an internal node, < 0 encodes leaf ``~index``
- splitting leaf L creates internal node ``num_leaves-1``; the left child
  keeps leaf index L, the right child becomes leaf ``num_leaves``
- ``decision_type`` bit flags: 1 = categorical, 2 = default_left,
  bits 2-3 = missing type (none/zero/nan) (include/LightGBM/tree.h:19-20)
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..io.binning import MissingType, kZeroThreshold

kCategoricalMask = 1
kDefaultLeftMask = 2


def _fmt(x: float) -> str:
    """Shortest round-trip decimal, matching the reference's
    Common::DoubleToStr output closely enough to round-trip."""
    return np.format_float_positional(
        np.float64(x), unique=True, trim="0") if np.isfinite(x) else repr(x)


def _arr_to_str(a, is_float: bool) -> str:
    if is_float:
        return " ".join(_fmt(v) for v in a)
    return " ".join(str(int(v)) for v in a)


class Tree:
    def __init__(self, max_leaves: int):
        self.max_leaves = max_leaves
        self.num_leaves = 1
        n = max(max_leaves - 1, 1)
        self.split_feature = np.zeros(n, dtype=np.int32)      # real feature idx
        self.split_feature_inner = np.zeros(n, dtype=np.int32)
        self.threshold_in_bin = np.zeros(n, dtype=np.int32)
        self.threshold = np.zeros(n, dtype=np.float64)
        self.decision_type = np.zeros(n, dtype=np.int8)
        self.split_gain = np.zeros(n, dtype=np.float64)
        self.left_child = np.zeros(n, dtype=np.int32)
        self.right_child = np.zeros(n, dtype=np.int32)
        self.internal_value = np.zeros(n, dtype=np.float64)
        self.internal_weight = np.zeros(n, dtype=np.float64)
        self.internal_count = np.zeros(n, dtype=np.int64)
        self.leaf_value = np.zeros(max_leaves, dtype=np.float64)
        self.leaf_weight = np.zeros(max_leaves, dtype=np.float64)
        self.leaf_count = np.zeros(max_leaves, dtype=np.int64)
        self.leaf_parent = np.full(max_leaves, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(max_leaves, dtype=np.int32)
        self.shrinkage = 1.0
        # categorical splits (reference: tree.h cat_boundaries_/
        # cat_threshold_ bitsets; num_cat counter)
        self.num_cat = 0
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []  # uint32 bitset words
        # session-only: per-node bool mask over BIN ids for fast binned
        # traversal (not serialized; rebuilt models predict on raw values)
        self.cat_bin_masks: dict = {}
        # linear trees (reference: tree.h is_linear_/leaf_const_/
        # leaf_features_/leaf_coeff_)
        self.is_linear = False
        self.leaf_const = np.zeros(0)
        self.leaf_features: List[List[int]] = []
        self.leaf_coeff: List[List[float]] = []

    # ------------------------------------------------------------------
    def split(self, leaf: int, feature: int, feature_inner: int,
              threshold_bin: int, threshold_real: float,
              left_value: float, right_value: float,
              left_count: int, right_count: int,
              left_weight: float, right_weight: float,
              gain: float, missing_type: int, default_left: bool) -> int:
        """Split ``leaf``; returns the new (right-child) leaf index
        (reference: Tree::Split, include/LightGBM/tree.h:62)."""
        node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = node
            else:
                self.right_child[parent] = node
        self.split_feature[node] = feature
        self.split_feature_inner[node] = feature_inner
        self.threshold_in_bin[node] = threshold_bin
        self.threshold[node] = threshold_real
        dt = (missing_type & 3) << 2
        if default_left:
            dt |= kDefaultLeftMask
        self.decision_type[node] = dt
        self.split_gain[node] = gain
        self.left_child[node] = ~leaf
        self.right_child[node] = ~self.num_leaves
        self.internal_value[node] = self.leaf_value[leaf]
        self.internal_weight[node] = left_weight + right_weight
        self.internal_count[node] = left_count + right_count
        new_leaf = self.num_leaves
        self.leaf_parent[leaf] = node
        self.leaf_parent[new_leaf] = node
        self.leaf_value[leaf] = _sane(left_value)
        self.leaf_value[new_leaf] = _sane(right_value)
        self.leaf_weight[leaf] = left_weight
        self.leaf_weight[new_leaf] = right_weight
        self.leaf_count[leaf] = left_count
        self.leaf_count[new_leaf] = right_count
        self.leaf_depth[new_leaf] = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] += 1
        self.num_leaves += 1
        return new_leaf

    # ------------------------------------------------------------------
    def split_categorical(self, leaf: int, feature: int, feature_inner: int,
                          cat_values, bin_mask,
                          left_value: float, right_value: float,
                          left_count: int, right_count: int,
                          left_weight: float, right_weight: float,
                          gain: float) -> int:
        """Categorical split: the given category VALUES go left
        (reference: Tree::SplitCategorical, include/LightGBM/tree.h:85 —
        bitset words appended to cat_threshold_, node threshold = index
        into cat_boundaries_)."""
        node = self.num_leaves - 1
        new_leaf = self.split(
            leaf=leaf, feature=feature, feature_inner=feature_inner,
            threshold_bin=self.num_cat, threshold_real=float(self.num_cat),
            left_value=left_value, right_value=right_value,
            left_count=left_count, right_count=right_count,
            left_weight=left_weight, right_weight=right_weight,
            gain=gain, missing_type=MissingType.NONE, default_left=False)
        self.decision_type[node] = kCategoricalMask
        max_cat = max([int(v) for v in cat_values], default=0)
        n_words = max_cat // 32 + 1
        words = [0] * n_words
        for v in cat_values:
            v = int(v)
            if v >= 0:
                words[v // 32] |= (1 << (v % 32))
        self.cat_threshold.extend(words)
        self.cat_boundaries.append(len(self.cat_threshold))
        self.num_cat += 1
        self.cat_bin_masks[node] = np.asarray(bin_mask, dtype=bool)
        return new_leaf

    def _cat_contains(self, cat_idx: int, values: np.ndarray) -> np.ndarray:
        """Vectorized FindInBitset (reference:
        include/LightGBM/utils/common.h ``FindInBitset``)."""
        lo = self.cat_boundaries[cat_idx]
        hi = self.cat_boundaries[cat_idx + 1]
        words = np.asarray(self.cat_threshold[lo:hi], dtype=np.uint64)
        iv = values.astype(np.int64)
        word_idx = iv // 32
        ok = (iv >= 0) & (word_idx < len(words))
        wi = np.clip(word_idx, 0, max(len(words) - 1, 0))
        bits = (words[wi] >> (iv % 32).astype(np.uint64)) & 1
        return ok & (bits > 0)

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float) -> None:
        """reference: Tree::Shrinkage (tree.h:113)."""
        self.leaf_value[:self.num_leaves] *= rate
        self.internal_value[:max(self.num_leaves - 1, 0)] *= rate
        if self.is_linear:
            self.leaf_const[:self.num_leaves] *= rate
            self.leaf_coeff = [[c * rate for c in cs]
                               for cs in self.leaf_coeff]
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        """reference: Tree::AddBias — used by boost_from_average refit."""
        self.leaf_value[:self.num_leaves] += val
        self.internal_value[:max(self.num_leaves - 1, 0)] += val

    def set_leaf_output(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = _sane(value)

    # ------------------------------------------------------------------
    def _decide(self, fval: np.ndarray, node: int) -> np.ndarray:
        """Vectorized Numerical/CategoricalDecision (reference: tree.h:133
        Predict → NumericalDecision / CategoricalDecision). True = left."""
        dt = int(self.decision_type[node])
        if dt & kCategoricalMask:
            iv = np.where(np.isnan(fval), -1.0, fval)
            return self._cat_contains(int(self.threshold_in_bin[node]), iv)
        missing = (dt >> 2) & 3
        default_left = bool(dt & kDefaultLeftMask)
        thr = self.threshold[node]
        isnan = np.isnan(fval)
        v = np.where(isnan & (missing != MissingType.NAN), 0.0, fval)
        go_left = v <= thr
        if missing == MissingType.ZERO:
            is_default = np.abs(v) <= kZeroThreshold
            go_left = np.where(is_default, default_left, go_left)
        elif missing == MissingType.NAN:
            go_left = np.where(isnan, default_left, go_left)
        return go_left

    def predict(self, X: np.ndarray) -> np.ndarray:
        leaf = self.predict_leaf_index(X)
        if self.is_linear:
            from .linear import linear_predict
            return linear_predict(self, X, leaf)
        return self.leaf_value[leaf]

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        """Lockstep vectorized traversal: all rows advance one level per
        pass, all node types decided at once (reference: tree.h:133
        Predict over NumericalDecision/CategoricalDecision — here the
        per-row branch walk becomes array ops over the flat node
        arrays)."""
        n = X.shape[0]
        if self.num_leaves == 1:
            return np.zeros(n, dtype=np.int32)
        ni = self.num_internal
        feat = self.split_feature[:ni]
        thr = self.threshold[:ni]
        dt = self.decision_type[:ni].astype(np.int64)
        is_cat = (dt & kCategoricalMask) != 0
        default_left = (dt & kDefaultLeftMask) != 0
        missing = (dt >> 2) & 3
        left, right = self.left_child[:ni], self.right_child[:ni]
        has_cat = bool(is_cat.any())
        if has_cat:
            boundaries = np.asarray(self.cat_boundaries, dtype=np.int64)
            words = np.asarray(self.cat_threshold, dtype=np.uint64)
            cat_idx = self.threshold_in_bin[:ni].astype(np.int64)
        node = np.zeros(n, dtype=np.int32)   # >=0 internal, <0 = ~leaf
        for _ in range(ni):
            act = np.nonzero(node >= 0)[0]
            if len(act) == 0:
                break
            nd = node[act]
            fv = X[act, feat[nd]]
            m = missing[nd]
            isnan = np.isnan(fv)
            v = np.where(isnan & (m != MissingType.NAN), 0.0, fv)
            gl = v <= thr[nd]
            gl = np.where((m == MissingType.ZERO)
                          & (np.abs(v) <= kZeroThreshold),
                          default_left[nd], gl)
            gl = np.where((m == MissingType.NAN) & isnan,
                          default_left[nd], gl)
            if has_cat:
                cn = is_cat[nd]
                if cn.any():
                    iv = np.where(isnan, -1.0, fv).astype(np.int64)
                    # non-cat nodes carry numeric bins in threshold_in_bin;
                    # clamp them out of the boundaries lookup
                    ci = np.clip(np.where(cn, cat_idx[nd], 0), 0,
                                 len(boundaries) - 2)
                    n_words = boundaries[ci + 1] - boundaries[ci]
                    ok = (iv >= 0) & (iv // 32 < n_words)
                    pos = np.clip(boundaries[ci] + iv // 32, 0,
                                  max(len(words) - 1, 0))
                    bits = (words[pos] >> (iv % 32).astype(np.uint64)) & 1
                    gl = np.where(cn, ok & (bits > 0), gl)
            node[act] = np.where(gl, left[nd], right[nd])
        return (~node).astype(np.int32)

    def predict_by_bin(self, bins: np.ndarray,
                       nan_bins: np.ndarray,
                       zero_bins: np.ndarray,
                       missing_types: np.ndarray) -> np.ndarray:
        """Lockstep vectorized traversal over pre-binned rows. ``bins`` is
        [n, F_inner]; per-inner-feature metadata arrays resolve missing bins."""
        n = bins.shape[0]
        if self.num_leaves == 1:
            return np.zeros(n, dtype=np.int32)
        ni = self.num_internal
        feat = self.split_feature_inner[:ni]
        tbin = self.threshold_in_bin[:ni]
        dt = self.decision_type[:ni].astype(np.int64)
        is_cat = (dt & kCategoricalMask) != 0
        default_left = (dt & kDefaultLeftMask) != 0
        left, right = self.left_child[:ni], self.right_child[:ni]
        # per-node missing-bin ids (-1 disables the compare)
        node_nan = np.where(missing_types[feat] == MissingType.NAN,
                            nan_bins[feat], -1)
        node_zero = np.where(missing_types[feat] == MissingType.ZERO,
                             zero_bins[feat], -1)
        has_cat = bool(is_cat.any())
        if has_cat:
            max_b = max((len(m) for m in self.cat_bin_masks.values()),
                        default=1)
            cat_tbl = np.zeros((ni, max_b), dtype=bool)
            for nd_i, mask in self.cat_bin_masks.items():
                if nd_i < ni:
                    m = np.asarray(mask, dtype=bool)
                    cat_tbl[nd_i, :len(m)] = m[:max_b]
        node = np.zeros(n, dtype=np.int32)
        for _ in range(ni):
            act = np.nonzero(node >= 0)[0]
            if len(act) == 0:
                break
            nd = node[act]
            b = bins[act, feat[nd]].astype(np.int64)
            gl = b <= tbin[nd]
            gl = np.where(b == node_nan[nd], default_left[nd], gl)
            gl = np.where(b == node_zero[nd], default_left[nd], gl)
            if has_cat:
                cn = is_cat[nd]
                if cn.any():
                    gl = np.where(cn,
                                  cat_tbl[nd, np.minimum(b, max_b - 1)],
                                  gl)
            node[act] = np.where(gl, left[nd], right[nd])
        return (~node).astype(np.int32)

    # ------------------------------------------------------------------
    def to_string(self) -> str:
        """Serialize in the reference's model text format
        (src/io/tree.cpp:339-410)."""
        nl = self.num_leaves
        ni = max(nl - 1, 0)
        lines = [f"num_leaves={nl}", f"num_cat={self.num_cat}"]
        if nl == 1:
            lines += [f"leaf_value={_fmt(self.leaf_value[0])}"]
        else:
            lines += [
                "split_feature=" + _arr_to_str(self.split_feature[:ni], False),
                "split_gain=" + _arr_to_str(self.split_gain[:ni], True),
                "threshold=" + _arr_to_str(self.threshold[:ni], True),
                "decision_type=" + _arr_to_str(self.decision_type[:ni], False),
                "left_child=" + _arr_to_str(self.left_child[:ni], False),
                "right_child=" + _arr_to_str(self.right_child[:ni], False),
                "leaf_value=" + _arr_to_str(self.leaf_value[:nl], True),
                "leaf_weight=" + _arr_to_str(self.leaf_weight[:nl], True),
                "leaf_count=" + _arr_to_str(self.leaf_count[:nl], False),
                "internal_value=" + _arr_to_str(self.internal_value[:ni], True),
                "internal_weight=" + _arr_to_str(self.internal_weight[:ni], True),
                "internal_count=" + _arr_to_str(self.internal_count[:ni], False),
            ]
            if self.num_cat > 0:
                lines += [
                    "cat_boundaries=" + " ".join(
                        str(v) for v in self.cat_boundaries),
                    "cat_threshold=" + " ".join(
                        str(v) for v in self.cat_threshold),
                ]
        if self.is_linear:
            nfeat = [len(self.leaf_features[i]) for i in range(nl)]
            flat_feats = [f for i in range(nl)
                          for f in self.leaf_features[i]]
            flat_coef = [c for i in range(nl) for c in self.leaf_coeff[i]]
            lines += [
                "is_linear=1",
                "leaf_const=" + _arr_to_str(self.leaf_const[:nl], True),
                "num_features=" + " ".join(str(v) for v in nfeat),
                "leaf_features=" + " ".join(str(v) for v in flat_feats),
                "leaf_coeff=" + " ".join(_fmt(v) for v in flat_coef),
            ]
        else:
            lines += ["is_linear=0"]
        lines += [f"shrinkage={_fmt(self.shrinkage)}", ""]
        return "\n".join(lines)

    @classmethod
    def from_string(cls, s: str) -> "Tree":
        """Parse the text format (reference: Tree::Tree(const char*, ...),
        src/io/tree.cpp:682)."""
        kv = {}
        for line in s.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        nl = int(kv["num_leaves"])
        t = cls(max(nl, 1))
        t.num_leaves = nl
        t.shrinkage = float(kv.get("shrinkage", 1.0))
        if nl == 1:
            t.leaf_value[0] = float(kv.get("leaf_value", 0.0))
            return t
        ni = nl - 1

        def farr(key, n, dtype=np.float64):
            return np.array(kv[key].split(), dtype=dtype)[:n]

        t.split_feature[:ni] = farr("split_feature", ni, np.int32)
        t.split_feature_inner[:ni] = t.split_feature[:ni]
        if "split_gain" in kv:
            t.split_gain[:ni] = farr("split_gain", ni)
        t.threshold[:ni] = farr("threshold", ni)
        t.decision_type[:ni] = farr("decision_type", ni, np.int64).astype(np.int8)
        t.left_child[:ni] = farr("left_child", ni, np.int32)
        t.right_child[:ni] = farr("right_child", ni, np.int32)
        t.leaf_value[:nl] = farr("leaf_value", nl)
        # leaf_depth is a train-time field the text format does not
        # carry; rebuild it from the structure — device traversal trip
        # counts (ops/predict.py build_device_tree) and depth reporting
        # on resumed/loaded trees read it
        stack = [(0, 0)]
        while stack:
            idx, d = stack.pop()
            if idx < 0:
                t.leaf_depth[~idx] = d
            else:
                stack.append((int(t.left_child[idx]), d + 1))
                stack.append((int(t.right_child[idx]), d + 1))
        if "leaf_weight" in kv:
            t.leaf_weight[:nl] = farr("leaf_weight", nl)
        if "leaf_count" in kv:
            t.leaf_count[:nl] = farr("leaf_count", nl, np.int64)
        if "internal_value" in kv:
            t.internal_value[:ni] = farr("internal_value", ni)
        if "internal_weight" in kv:
            t.internal_weight[:ni] = farr("internal_weight", ni)
        if "internal_count" in kv:
            t.internal_count[:ni] = farr("internal_count", ni, np.int64)
        if int(kv.get("is_linear", 0)):
            t.is_linear = True
            t.leaf_const = np.zeros(max(nl, 1))
            t.leaf_const[:nl] = farr("leaf_const", nl)
            nfeat = [int(v) for v in kv.get("num_features", "").split()]
            flat_feats = [int(v)
                          for v in kv.get("leaf_features", "").split()]
            flat_coef = [float(v)
                         for v in kv.get("leaf_coeff", "").split()]
            t.leaf_features = []
            t.leaf_coeff = []
            pos = 0
            for c in nfeat:
                t.leaf_features.append(flat_feats[pos:pos + c])
                t.leaf_coeff.append(flat_coef[pos:pos + c])
                pos += c
            while len(t.leaf_features) < t.max_leaves:
                t.leaf_features.append([])
                t.leaf_coeff.append([])
        t.num_cat = int(kv.get("num_cat", 0))
        if t.num_cat > 0:
            t.cat_boundaries = [int(v)
                                for v in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(v) for v in kv["cat_threshold"].split()]
            # categorical nodes store the cat-split index in `threshold`;
            # cast only those (numeric nodes may hold NaN thresholds,
            # which trip a RuntimeWarning on int cast)
            cat_nodes = (t.decision_type[:ni] & kCategoricalMask) != 0
            t.threshold_in_bin[:ni] = np.where(
                cat_nodes,
                np.where(cat_nodes, t.threshold[:ni], 0).astype(np.int32),
                t.threshold_in_bin[:ni])
        return t

    # ------------------------------------------------------------------
    def cat_value_words(self, cat_idx: int) -> int:
        """Bitset word count of one categorical split — bounds the
        largest category value the node can send left."""
        return self.cat_boundaries[cat_idx + 1] - self.cat_boundaries[cat_idx]

    def cat_value_mask(self, cat_idx: int, max_value: int) -> np.ndarray:
        """[max_value+1] bool: membership of category values 0..max_value
        in the split's bitset (vectorized FindInBitset). Works on
        text-loaded trees — only cat_boundaries/cat_threshold needed."""
        vals = np.arange(max_value + 1, dtype=np.float64)
        return self._cat_contains(cat_idx, vals)

    def structure_depth(self) -> int:
        """Max root→leaf hop count derived from the child arrays alone.
        ``leaf_depth`` is a train-time field that text-loaded trees leave
        zeroed, so device traversal trip counts must come from here."""
        if self.num_leaves <= 1:
            return 0
        best = 0
        stack: List[tuple] = [(0, 0)]
        while stack:
            idx, d = stack.pop()
            if idx < 0:
                best = max(best, d)
                continue
            stack.append((int(self.left_child[idx]), d + 1))
            stack.append((int(self.right_child[idx]), d + 1))
        return best

    # ------------------------------------------------------------------
    def _cats_of(self, cat_idx: int) -> List[int]:
        """Expand a stored bitset back to category values (reference:
        Tree::NodeToJSON's FindInBitset loop, src/io/tree.cpp:466-477)."""
        lo = self.cat_boundaries[cat_idx]
        hi = self.cat_boundaries[cat_idx + 1]
        out = []
        for w in range(hi - lo):
            word = int(self.cat_threshold[lo + w])
            for j in range(32):
                if (word >> j) & 1:
                    out.append(w * 32 + j)
        return out

    def _linear_json(self, leaf: int) -> dict:
        return {
            "leaf_const": float(self.leaf_const[leaf]),
            "leaf_features": list(self.leaf_features[leaf]),
            "leaf_coeff": [float(c) for c in self.leaf_coeff[leaf]],
        }

    def _node_to_json(self, index: int) -> dict:
        """reference: Tree::NodeToJSON (src/io/tree.cpp:455-520).
        Iterative (explicit post-order) — chain-shaped trees can be
        num_leaves-1 deep, past Python's recursion limit."""
        order: List[int] = []
        stack = [index]
        while stack:
            idx = stack.pop()
            order.append(idx)
            if idx >= 0:
                stack.append(int(self.left_child[idx]))
                stack.append(int(self.right_child[idx]))
        memo: dict = {}
        for idx in reversed(order):
            if idx < 0:
                leaf = ~idx
                d = {
                    "leaf_index": int(leaf),
                    "leaf_value": float(self.leaf_value[leaf]),
                    "leaf_weight": float(self.leaf_weight[leaf]),
                    "leaf_count": int(self.leaf_count[leaf]),
                }
                if self.is_linear:
                    d.update(self._linear_json(leaf))
                memo[idx] = d
                continue
            dt = int(self.decision_type[idx])
            if dt & kCategoricalMask:
                cat_idx = int(self.threshold_in_bin[idx])
                threshold = "||".join(str(c) for c in self._cats_of(cat_idx))
                decision = "=="
            else:
                threshold = float(self.threshold[idx])
                decision = "<="
            missing = (dt >> 2) & 3
            missing_name = ("None", "Zero", "NaN", "NaN")[missing]
            memo[idx] = {
                "split_index": int(idx),
                "split_feature": int(self.split_feature[idx]),
                "split_gain": float(self.split_gain[idx]),
                "threshold": threshold,
                "decision_type": decision,
                "default_left": bool(dt & kDefaultLeftMask),
                "missing_type": missing_name,
                "internal_value": float(self.internal_value[idx]),
                "internal_weight": float(self.internal_weight[idx]),
                "internal_count": int(self.internal_count[idx]),
                "left_child": memo[int(self.left_child[idx])],
                "right_child": memo[int(self.right_child[idx])],
            }
        return memo[index]

    def to_json(self) -> dict:
        """JSON-dump structure (reference: Tree::ToJSON,
        src/io/tree.cpp:411-429)."""
        d = {
            "num_leaves": int(self.num_leaves),
            "num_cat": int(self.num_cat),
            "shrinkage": float(self.shrinkage),
        }
        if self.num_leaves == 1:
            root = {"leaf_value": float(self.leaf_value[0])}
            if self.is_linear:
                root.update(self._linear_json(0))
            d["tree_structure"] = root
        else:
            d["tree_structure"] = self._node_to_json(0)
        return d

    # ------------------------------------------------------------------
    @property
    def num_internal(self) -> int:
        return max(self.num_leaves - 1, 0)

    def features_used(self) -> np.ndarray:
        return np.unique(self.split_feature[:self.num_internal])


def _sane(v: float) -> float:
    """reference: Tree::Split guards leaf outputs against NaN/Inf
    (kMaxTreeOutput clamp in feature_histogram)."""
    if not np.isfinite(v):
        return 0.0
    return float(v)


def parse_tree_blocks(s: str) -> List["Tree"]:
    """Parse the ``Tree=<i>`` ... ``end of trees`` section of a v3
    model text into Tree objects — THE tree-framing parser, shared by
    ``GBDT.load_model_from_string`` and checkpoint resume
    (ft/checkpoint.py) so the block grammar cannot drift between the
    two loaders. Lines before the first ``Tree=`` are ignored, so the
    full model text (or just its tree section) both work."""
    models: List[Tree] = []
    cur: List[str] = []
    in_tree = False
    for line in s.splitlines():
        if line.startswith("Tree="):
            if cur:
                models.append(Tree.from_string("\n".join(cur)))
            cur = []
            in_tree = True
        elif line.strip() == "end of trees":
            if cur:
                models.append(Tree.from_string("\n".join(cur)))
            cur = []
            in_tree = False
        elif in_tree:
            cur.append(line)
    return models
