"""Piecewise-linear trees: ridge fits in each leaf.

Equivalent of the reference's ``LinearTreeLearner``
(reference: src/treelearner/linear_tree_learner.cpp:173
``CalculateLinear``): after the tree structure is grown, each leaf gets a
linear model over the features used on its path, solved from the
gradient/hessian normal equations with ``linear_lambda`` ridge
regularization (config.h:400); leaves with too few rows or singular
systems keep their constant output. Rows with NaN in any leaf feature fall
back to the constant (reference: linear prediction NaN handling).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..utils import log
from .tree import Tree


def fit_linear_leaves(tree: Tree, X: np.ndarray, grad: np.ndarray,
                      hess: np.ndarray, leaf_of_row: np.ndarray,
                      linear_lambda: float,
                      row_mask: Optional[np.ndarray] = None,
                      min_rows: int = 10) -> None:
    """Fit ``f(x) = const + coef·x_path`` per leaf minimizing
    sum_i [g_i f + 0.5 h_i f^2] + 0.5*linear_lambda*|coef|^2
    (the second-order objective the reference solves with Eigen,
    linear_tree_learner.cpp:290-360)."""
    X = np.asarray(X, dtype=np.float64)
    grad = np.asarray(grad, dtype=np.float64)
    hess = np.asarray(hess, dtype=np.float64)
    tree.is_linear = True
    tree.leaf_const = tree.leaf_value.copy()
    tree.leaf_features = [[] for _ in range(tree.max_leaves)]
    tree.leaf_coeff = [[] for _ in range(tree.max_leaves)]

    # features on the path to each leaf
    path_feats = {0: []}
    for leaf in range(tree.num_leaves):
        path_feats.setdefault(leaf, [])
    paths = _leaf_paths(tree)

    for leaf in range(tree.num_leaves):
        feats = paths.get(leaf, [])
        if not feats:
            continue
        rows = leaf_of_row == leaf
        if row_mask is not None:
            rows &= row_mask
        Xl = X[np.ix_(rows, feats)]
        ok = ~np.isnan(Xl).any(axis=1)
        if ok.sum() < max(min_rows, len(feats) + 1):
            continue
        Xl = Xl[ok]
        gl = grad[rows][ok]
        hl = hess[rows][ok]
        n, k = Xl.shape
        A = np.concatenate([Xl, np.ones((n, 1))], axis=1)
        H = A.T @ (A * hl[:, None])
        reg = np.eye(k + 1) * linear_lambda
        reg[-1, -1] = 0.0  # constant not regularized
        b = -A.T @ gl
        try:
            beta = np.linalg.solve(H + reg, b)
        except np.linalg.LinAlgError:
            continue
        if not np.isfinite(beta).all():
            continue
        tree.leaf_features[leaf] = [int(f) for f in feats]
        tree.leaf_coeff[leaf] = [float(v) for v in beta[:-1]]
        tree.leaf_const[leaf] = float(beta[-1])


def _leaf_paths(tree: Tree) -> dict:
    """Map leaf -> ordered unique feature list on its root path."""
    out = {}
    if tree.num_leaves <= 1:
        return {0: []}

    def walk(node, feats):
        if node < 0:
            out[~node] = list(dict.fromkeys(feats))
            return
        f = int(tree.split_feature[node])
        walk(int(tree.left_child[node]), feats + [f])
        walk(int(tree.right_child[node]), feats + [f])

    walk(0, [])
    return out


def linear_predict(tree: Tree, X: np.ndarray,
                   leaf_idx: Optional[np.ndarray] = None) -> np.ndarray:
    """Linear-leaf prediction over raw features (reference:
    Tree::Predict linear branch, src/io/tree.cpp)."""
    X = np.asarray(X, dtype=np.float64)
    if leaf_idx is None:
        leaf_idx = tree.predict_leaf_index(X)
    out = tree.leaf_value[leaf_idx].copy()
    for leaf in range(tree.num_leaves):
        feats = tree.leaf_features[leaf]
        if not feats:
            continue
        rows = leaf_idx == leaf
        if not rows.any():
            continue
        Xl = X[np.ix_(rows, feats)]
        ok = ~np.isnan(Xl).any(axis=1)
        vals = tree.leaf_const[leaf] + Xl @ np.asarray(tree.leaf_coeff[leaf])
        res = out[rows]
        res[ok] = vals[ok]
        out[rows] = res
    return out
