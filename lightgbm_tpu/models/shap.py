"""SHAP feature contributions (TreeSHAP).

Equivalent of the reference's ``Tree::PredictContrib`` path
(reference: include/LightGBM/tree.h:139 PredictContrib, the TreeSHAP
recursion in src/io/tree.cpp ``TreeSHAP``/``ExtendPath``/``UnwindPath``,
after Lundberg & Lee's exact polynomial-time algorithm). Per-node covers
come from the training-time ``internal_count``/``leaf_count`` just like
the reference.
"""
from __future__ import annotations

import numpy as np

from .tree import Tree, kCategoricalMask


class _Path:
    __slots__ = ("feature", "zero", "one", "pweight")

    def __init__(self, depth: int):
        self.feature = np.full(depth, -1, dtype=np.int64)
        self.zero = np.zeros(depth)
        self.one = np.zeros(depth)
        self.pweight = np.zeros(depth)

    def copy_from(self, other: "_Path", n: int) -> None:
        self.feature[:n] = other.feature[:n]
        self.zero[:n] = other.zero[:n]
        self.one[:n] = other.one[:n]
        self.pweight[:n] = other.pweight[:n]


def _extend(path: _Path, depth: int, zero: float, one: float,
            feature: int) -> None:
    path.feature[depth] = feature
    path.zero[depth] = zero
    path.one[depth] = one
    path.pweight[depth] = 1.0 if depth == 0 else 0.0
    for i in range(depth - 1, -1, -1):
        path.pweight[i + 1] += one * path.pweight[i] * (i + 1) / (depth + 1)
        path.pweight[i] = zero * path.pweight[i] * (depth - i) / (depth + 1)


def _unwind(path: _Path, depth: int, index: int) -> None:
    one = path.one[index]
    zero = path.zero[index]
    next_one = path.pweight[depth]
    for i in range(depth - 1, -1, -1):
        if one != 0.0:
            tmp = path.pweight[i]
            path.pweight[i] = next_one * (depth + 1) / ((i + 1) * one)
            next_one = tmp - path.pweight[i] * zero * (depth - i) / (depth + 1)
        else:
            path.pweight[i] = (path.pweight[i] * (depth + 1)) \
                / (zero * (depth - i))
    for i in range(index, depth):
        path.feature[i] = path.feature[i + 1]
        path.zero[i] = path.zero[i + 1]
        path.one[i] = path.one[i + 1]


def _unwound_sum(path: _Path, depth: int, index: int) -> float:
    one = path.one[index]
    zero = path.zero[index]
    next_one = path.pweight[depth]
    total = 0.0
    for i in range(depth - 1, -1, -1):
        if one != 0.0:
            tmp = next_one * (depth + 1) / ((i + 1) * one)
            total += tmp
            next_one = path.pweight[i] - tmp * zero * (depth - i) / (depth + 1)
        else:
            total += (path.pweight[i] / zero) * (depth + 1) / (depth - i)
    return total


def _node_count(tree: Tree, node: int) -> float:
    if node < 0:
        return float(max(tree.leaf_count[~node], 1))
    return float(max(tree.internal_count[node], 1))


def _decision(tree: Tree, node: int, x: np.ndarray) -> bool:
    return bool(tree._decide(np.array([x[tree.split_feature[node]]]),
                             node)[0])


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray, node: int,
               unique_depth: int, parent_path: _Path,
               parent_zero: float, parent_one: float,
               parent_feature: int) -> None:
    path = _Path(unique_depth + 2)
    path.copy_from(parent_path, unique_depth + 1)
    _extend(path, unique_depth, parent_zero, parent_one, parent_feature)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_sum(path, unique_depth, i)
            phi[path.feature[i]] += (w * (path.one[i] - path.zero[i])
                                     * tree.leaf_value[leaf])
        return

    hot = tree.left_child[node] if _decision(tree, node, x) \
        else tree.right_child[node]
    cold = tree.right_child[node] if hot == tree.left_child[node] \
        else tree.left_child[node]
    node_cnt = _node_count(tree, node)
    hot_zero = _node_count(tree, hot) / node_cnt
    cold_zero = _node_count(tree, cold) / node_cnt
    incoming_zero, incoming_one = 1.0, 1.0
    path_index = 0
    feat = tree.split_feature[node]
    while path_index <= unique_depth:
        if path.feature[path_index] == feat:
            break
        path_index += 1
    if path_index != unique_depth + 1:
        incoming_zero = path.zero[path_index]
        incoming_one = path.one[path_index]
        _unwind(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(tree, x, phi, hot, unique_depth + 1, path,
               hot_zero * incoming_zero, incoming_one, feat)
    _tree_shap(tree, x, phi, cold, unique_depth + 1, path,
               cold_zero * incoming_zero, 0.0, feat)


def _expected_value(tree: Tree) -> float:
    """Cover-weighted mean output (reference: Tree::ExpectedValue)."""
    total = tree.leaf_count[:tree.num_leaves].sum()
    if total <= 0:
        return float(tree.leaf_value[:tree.num_leaves].mean())
    return float((tree.leaf_value[:tree.num_leaves]
                  * tree.leaf_count[:tree.num_leaves]).sum() / total)


def tree_predict_contrib(tree: Tree, X: np.ndarray,
                         num_features: int) -> np.ndarray:
    """Per-row SHAP values [n, num_features + 1]; last column is the
    expected value (reference: PredictContrib appends the bias term)."""
    n = X.shape[0]
    out = np.zeros((n, num_features + 1))
    expected = _expected_value(tree)
    out[:, -1] = expected
    if tree.num_leaves == 1:
        return out
    for r in range(n):
        phi = out[r]
        _tree_shap(tree, X[r], phi, 0, 0, _Path(1), 1.0, 1.0, -1)
    return out


def predict_contrib(models, X: np.ndarray, num_features: int,
                    num_tree_per_iteration: int) -> np.ndarray:
    """Sum of per-tree SHAP values. Returns [n, (F+1)] for single-class
    or [n, K*(F+1)] multiclass (reference: c_api predict_contrib
    layout)."""
    X = np.asarray(X, dtype=np.float64)
    n = X.shape[0]
    K = num_tree_per_iteration
    out = np.zeros((n, K, num_features + 1))
    for i, tree in enumerate(models):
        out[:, i % K, :] += tree_predict_contrib(tree, X, num_features)
    if K == 1:
        return out[:, 0, :]
    return out.reshape(n, K * (num_features + 1))
