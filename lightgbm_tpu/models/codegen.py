"""Model → standalone C++ code generation (``convert_model``).

Equivalent of the reference's if-else codegen (``GBDT::SaveModelToIfElse``
/ ``Tree::ToIfElse``, src/boosting/gbdt_model_text.cpp:286,
src/io/tree.cpp:548-648), re-designed to emit a *self-contained*
translation unit: the reference's output plugs into its own C++ codebase,
whereas ours compiles standalone with only the C++ standard library and
exposes a C ABI (``Predict``/``PredictRaw``/``PredictLeafIndex``) so any
engine — or our own test-suite via ctypes — can load it.

Unlike the reference's ``NumericalDecisionIfElse`` (src/io/tree.cpp:520),
which drops the threshold comparison on Zero/NaN-missing nodes, the
emitted decision here reproduces ``Tree::NumericalDecision``
(include/LightGBM/tree.h:335) exactly, so compiled predictions match the
in-framework predictor bit-for-bit on finite inputs.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..io.binning import MissingType, kZeroThreshold
from .tree import Tree, kCategoricalMask, kDefaultLeftMask


def _f(x: float) -> str:
    """C++ double literal with round-trip precision."""
    if np.isnan(x):
        return "std::numeric_limits<double>::quiet_NaN()"
    if np.isinf(x):
        return ("std::numeric_limits<double>::infinity()" if x > 0
                else "-std::numeric_limits<double>::infinity()")
    return repr(float(x))


def _numerical_cond(tree: Tree, node: int) -> str:
    """True ⇔ go left; mirrors Tree._decide / reference
    NumericalDecision (include/LightGBM/tree.h:335)."""
    dt = int(tree.decision_type[node])
    missing = (dt >> 2) & 3
    default_left = "true" if dt & kDefaultLeftMask else "false"
    thr = _f(float(tree.threshold[node]))
    if missing == MissingType.NAN:
        return ("(std::isnan(fval) ? %s : (fval <= %s))"
                % (default_left, thr))
    # NaN is remapped to 0 first (missing None/Zero)
    v = "(std::isnan(fval) ? 0.0 : fval)"
    if missing == MissingType.ZERO:
        return ("(std::fabs(%s) <= kZeroThreshold ? %s : (%s <= %s))"
                % (v, default_left, v, thr))
    return "(%s <= %s)" % (v, thr)


def _categorical_cond(tree: Tree, node: int, tree_id: int) -> str:
    """True ⇔ category bit set ⇒ go left (reference:
    CategoricalDecisionIfElse, src/io/tree.cpp:548; CategoricalDecision,
    tree.h:395)."""
    cat_idx = int(tree.threshold_in_bin[node])
    lo = tree.cat_boundaries[cat_idx]
    n_words = tree.cat_boundaries[cat_idx + 1] - lo
    return ("CatDecision(fval, kCatWords%d + %d, %d)"
            % (tree_id, lo, n_words))


def _emit_node(tree: Tree, index: int, tree_id: int, leaf_index: bool,
               out: List[str], depth: int) -> None:
    """Iterative emission with an explicit work stack — chain-shaped
    trees can be num_leaves-1 deep, past Python's recursion limit."""
    stack = [("node", index, depth)]
    while stack:
        kind, arg, d = stack.pop()
        pad = "  " * min(d, 40)
        if kind == "else":
            out.append("%s} else {" % pad)
            continue
        if kind == "close":
            out.append("%s}" % pad)
            continue
        if arg < 0:
            leaf = ~arg
            if leaf_index:
                out.append("%sreturn %d;" % (pad, leaf))
            elif tree.is_linear:
                terms = ["%s" % _f(float(tree.leaf_const[leaf]))]
                for f, c in zip(tree.leaf_features[leaf],
                                tree.leaf_coeff[leaf]):
                    terms.append("%s * NanToZero(arr[%d])"
                                 % (_f(float(c)), f))
                out.append("%sreturn %s;" % (pad, " + ".join(terms)))
            else:
                out.append("%sreturn %s;"
                           % (pad, _f(float(tree.leaf_value[leaf]))))
            continue
        dt = int(tree.decision_type[arg])
        out.append("%sfval = arr[%d];" % (pad, int(tree.split_feature[arg])))
        if dt & kCategoricalMask:
            cond = _categorical_cond(tree, arg, tree_id)
        else:
            cond = _numerical_cond(tree, arg)
        out.append("%sif (%s) {" % (pad, cond))
        stack.append(("close", 0, d))
        stack.append(("node", int(tree.right_child[arg]), d + 1))
        stack.append(("else", 0, d))
        stack.append(("node", int(tree.left_child[arg]), d + 1))


def _tree_fn(tree: Tree, tree_id: int, leaf_index: bool) -> str:
    name = "PredictTree%d%s" % (tree_id, "Leaf" if leaf_index else "")
    lines = ["static double %s(const double* arr) {" % name]
    if tree.num_leaves <= 1:
        lines.append("  (void)arr; return %s;"
                     % ("0" if leaf_index
                        else _f(float(tree.leaf_value[0]))))
    else:
        lines.append("  double fval = 0.0;")
        _emit_node(tree, 0, tree_id, leaf_index, lines, 1)
        lines.append("  return 0.0;  // unreachable")
    lines.append("}")
    return "\n".join(lines)


def _convert_output_code(objective_str: str, num_class: int,
                         sigmoid: float) -> str:
    """ConvertOutput body per objective family (reference: each
    objective's ConvertOutput, e.g. binary_objective.hpp sigmoid,
    multiclass_objective.hpp softmax, regression poisson/gamma/tweedie
    exp)."""
    name = objective_str.split(" ")[0]
    if name in ("binary", "cross_entropy", "cross_entropy_lambda"):
        return ("  output[0] = 1.0 / (1.0 + std::exp(-%s * output[0]));"
                % _f(sigmoid if name == "binary" else 1.0))
    if name == "multiclass":
        return ("  Softmax(output, %d);" % num_class)
    if name == "multiclassova":
        return ("  for (int k = 0; k < %d; ++k) output[k] = "
                "1.0 / (1.0 + std::exp(-%s * output[k]));"
                % (num_class, _f(sigmoid)))
    if name in ("poisson", "gamma", "tweedie"):
        return "  output[0] = std::exp(output[0]);"
    return "  // identity"


def model_to_cpp(gbdt) -> str:
    """Emit the standalone C++ translation unit for ``gbdt``
    (reference: GBDT::ModelToIfElse, gbdt_model_text.cpp:76-286)."""
    models = gbdt.models
    num_tree_per_iter = gbdt.num_tree_per_iteration
    num_class = max(gbdt.num_class, 1)
    sigmoid = float(getattr(gbdt.config, "sigmoid", 1.0))
    obj_str = (gbdt.objective.to_string()
               if gbdt.objective is not None else "custom")

    parts = [
        "// Generated by lightgbm_tpu convert_model; standalone predictor.",
        "// Compile: g++ -O2 -shared -fPIC -o model.so model.cpp",
        "#include <cmath>",
        "#include <cstdint>",
        "#include <cstring>",
        "#include <limits>",
        "",
        "namespace {",
        "const double kZeroThreshold = %s;" % repr(kZeroThreshold),
        "inline double NanToZero(double v) "
        "{ return std::isnan(v) ? 0.0 : v; }",
        "inline bool CatDecision(double fval, const uint32_t* words, "
        "int n_words) {",
        "  if (std::isnan(fval)) return false;",
        "  int iv = static_cast<int>(fval);",
        "  if (iv < 0 || iv >= 32 * n_words) return false;",
        "  return (words[iv / 32] >> (iv & 31)) & 1;",
        "}",
        "inline void Softmax(double* rec, int n) {",
        "  double wmax = rec[0];",
        "  for (int k = 1; k < n; ++k) "
        "wmax = rec[k] > wmax ? rec[k] : wmax;",
        "  double wsum = 0.0;",
        "  for (int k = 0; k < n; ++k) "
        "{ rec[k] = std::exp(rec[k] - wmax); wsum += rec[k]; }",
        "  for (int k = 0; k < n; ++k) rec[k] /= wsum;",
        "}",
    ]

    for i, tree in enumerate(models):
        if tree.num_cat > 0:
            words = ",".join(str(int(w) & 0xFFFFFFFF)
                             for w in tree.cat_threshold)
            parts.append("const uint32_t kCatWords%d[] = {%s};"
                         % (i, words))
    for i, tree in enumerate(models):
        parts.append(_tree_fn(tree, i, leaf_index=False))
    for i, tree in enumerate(models):
        parts.append(_tree_fn(tree, i, leaf_index=True))

    fn_ptrs = ", ".join("PredictTree%d" % i for i in range(len(models)))
    leaf_ptrs = ", ".join("PredictTree%dLeaf" % i
                          for i in range(len(models)))
    parts += [
        "typedef double (*TreeFn)(const double*);",
        "const TreeFn kTreeFns[] = {%s};" % (fn_ptrs or "nullptr"),
        "const TreeFn kTreeLeafFns[] = {%s};" % (leaf_ptrs or "nullptr"),
        "const int kNumModels = %d;" % len(models),
        "const int kNumTreePerIter = %d;" % num_tree_per_iter,
        "const bool kAverageOutput = %s;"
        % ("true" if gbdt.average_output else "false"),
        "}  // namespace",
        "",
        'extern "C" void PredictRaw(const double* features, '
        "double* output) {",
        "  std::memset(output, 0, sizeof(double) * kNumTreePerIter);",
        "  for (int i = 0; i < kNumModels; ++i)",
        "    output[i % kNumTreePerIter] += kTreeFns[i](features);",
        "  if (kAverageOutput && kNumModels > 0)",
        "    for (int k = 0; k < kNumTreePerIter; ++k)",
        "      output[k] /= (kNumModels / kNumTreePerIter);",
        "}",
        "",
        'extern "C" void Predict(const double* features, double* output) {',
        "  PredictRaw(features, output);",
        _convert_output_code(obj_str, num_class, sigmoid),
        "}",
        "",
        'extern "C" void PredictLeafIndex(const double* features, '
        "double* output) {",
        "  for (int i = 0; i < kNumModels; ++i)",
        "    output[i] = kTreeLeafFns[i](features);",
        "}",
        "",
        'extern "C" int GetNumModels() { return kNumModels; }',
        'extern "C" int GetNumTreePerIteration() '
        "{ return kNumTreePerIter; }",
        "",
    ]
    return "\n".join(parts)
