"""Configuration for lightgbm_tpu.

TPU-native equivalent of the reference's ``struct Config``
(reference: include/LightGBM/config.h:34, parser src/io/config.cpp, alias table
src/io/config_auto.cpp:10-120). One typed dataclass carries the full
user-facing parameter surface; :func:`Config.from_params` resolves aliases,
coerces types, and validates ranges like ``Config::Set``.

TPU-specific additions (the analogue of the reference's device section,
config.h:1056-1070): ``device_type`` accepts ``'tpu'``, ``tpu_use_f64_hist``
mirrors ``gpu_use_dp`` (double-precision histogram accumulation), and
``hist_backend`` selects the histogram kernel implementation.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from .utils import log

# ---------------------------------------------------------------------------
# Alias table (reference: src/io/config_auto.cpp:10-120, ~117 aliases)
# ---------------------------------------------------------------------------
_ALIASES: Dict[str, str] = {}


def _alias(canonical: str, *names: str) -> None:
    for n in names:
        _ALIASES[n] = canonical


_alias("config", "config_file")
_alias("task", "task_type")
_alias("objective", "objective_type", "app", "application", "loss")
_alias("boosting", "boosting_type", "boost")
_alias("data_sample_strategy", "sample_strategy")
_alias("data", "train", "train_data", "train_data_file", "data_filename")
_alias("valid", "test", "valid_data", "valid_data_file", "test_data",
       "test_data_file", "valid_filenames")
_alias("num_iterations", "num_iteration", "n_iter", "num_tree", "num_trees",
       "num_round", "num_rounds", "nrounds", "num_boost_round", "n_estimators",
       "max_iter")
_alias("learning_rate", "shrinkage_rate", "eta")
_alias("num_leaves", "num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes")
_alias("tree_learner", "tree", "tree_type", "tree_learner_type")
_alias("num_threads", "num_thread", "nthread", "nthreads", "n_jobs")
_alias("device_type", "device")
_alias("seed", "random_seed", "random_state")
_alias("histogram_pool_size", "hist_pool_size")
_alias("min_data_in_leaf", "min_data_per_leaf", "min_data",
       "min_child_samples", "min_samples_leaf")
_alias("min_sum_hessian_in_leaf", "min_sum_hessian_per_leaf",
       "min_sum_hessian", "min_hessian", "min_child_weight")
_alias("bagging_fraction", "sub_row", "subsample", "bagging")
_alias("pos_bagging_fraction", "pos_sub_row", "pos_subsample", "pos_bagging")
_alias("neg_bagging_fraction", "neg_sub_row", "neg_subsample", "neg_bagging")
_alias("bagging_freq", "subsample_freq")
_alias("bagging_seed", "bagging_fraction_seed")
_alias("feature_fraction", "sub_feature", "colsample_bytree")
_alias("feature_fraction_bynode", "sub_feature_bynode", "colsample_bynode")
_alias("extra_trees", "extra_tree")
_alias("early_stopping_round", "early_stopping_rounds", "early_stopping",
       "n_iter_no_change")
_alias("max_delta_step", "max_tree_output", "max_leaf_output")
_alias("lambda_l1", "reg_alpha", "l1_regularization")
_alias("lambda_l2", "reg_lambda", "lambda", "l2_regularization")
_alias("min_gain_to_split", "min_split_gain")
_alias("drop_rate", "rate_drop")
_alias("top_k", "topk")
_alias("monotone_constraints", "mc", "monotone_constraint", "monotonic_cst")
_alias("monotone_constraints_method", "monotone_constraining_method",
       "mc_method")
_alias("monotone_penalty", "monotone_splits_penalty", "ms_penalty",
       "mc_penalty")
_alias("feature_contri", "feature_contrib", "fc", "fp", "feature_penalty")
_alias("forcedsplits_filename", "fs", "forced_splits_filename",
       "forced_splits_file", "forced_splits")
_alias("verbosity", "verbose")
_alias("input_model", "model_input", "model_in")
_alias("output_model", "model_output", "model_out")
_alias("snapshot_freq", "save_period")
_alias("linear_tree", "linear_trees")
_alias("max_bin", "max_bins")
_alias("bin_construct_sample_cnt", "subsample_for_bin")
_alias("data_random_seed", "data_seed")
_alias("is_enable_sparse", "is_sparse", "enable_sparse", "sparse")
_alias("enable_bundle", "is_enable_bundle", "bundle")
_alias("pre_partition", "is_pre_partition")
_alias("two_round", "two_round_loading", "use_two_round_loading")
_alias("header", "has_header")
_alias("label_column", "label")
_alias("weight_column", "weight")
_alias("group_column", "group", "group_id", "query_column", "query",
       "query_id")
_alias("ignore_column", "ignore_feature", "blacklist")
_alias("categorical_feature", "cat_feature", "categorical_column",
       "cat_column", "categorical_features")
_alias("save_binary", "is_save_binary", "is_save_binary_file")
_alias("predict_raw_score", "is_predict_raw_score", "predict_rawscore",
       "raw_score")
_alias("predict_leaf_index", "is_predict_leaf_index", "leaf_index")
_alias("predict_contrib", "contrib")
_alias("output_result", "predict_result", "prediction_result", "predict_name",
       "pred_name", "name_pred")
_alias("is_unbalance", "unbalance", "unbalanced_sets")
_alias("metric", "metrics", "metric_types")
_alias("metric_freq", "output_freq")
_alias("is_provide_training_metric", "training_metric", "is_training_metric",
       "train_metric")
_alias("eval_at", "ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at")
_alias("num_class", "num_classes")
_alias("use_quantized_grad", "use_quantized_gradients", "quantized_grad")
_alias("quant_grad_bits", "num_grad_quant_bins_bits", "grad_quant_bits")
_alias("num_machines", "num_machine")
_alias("local_listen_port", "local_port", "port")
_alias("machine_list_filename", "machine_list_file", "machine_list", "mlist")
_alias("machines", "workers", "nodes")


_OBJECTIVE_ALIASES = {
    # reference: ObjectiveFunction::CreateObjectiveFunction name handling +
    # config.h:151 objective docs (aliases listed per objective).
    "regression": "regression", "regression_l2": "regression",
    "l2": "regression", "mean_squared_error": "regression",
    "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg", "xendcg": "rank_xendcg",
    "xe_ndcg": "rank_xendcg", "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "none": "custom", "null": "custom", "custom": "custom", "na": "custom",
}

_METRIC_ALIASES = {
    # reference: src/metric/metric.cpp:19 factory names.
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1",
    "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression": "l2",
    "regression_l2": "l2",
    "rmse": "rmse", "root_mean_squared_error": "rmse", "l2_root": "rmse",
    "quantile": "quantile", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc": "auc", "average_precision": "average_precision",
    "auc_mu": "auc_mu",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    "xendcg": "ndcg", "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg",
    "xendcg_mart": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss",
    "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv", "kldiv": "kldiv",
    "none": "custom", "null": "custom", "custom": "custom", "na": "custom",
}


@dataclass
class Config:
    """Full parameter surface (reference: include/LightGBM/config.h field list,
    cited per-field in SURVEY.md §2.8). Defaults match the reference."""

    # --- Core (config.h:105-251) ---
    config: str = ""
    task: str = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data_sample_strategy: str = "bagging"
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"
    num_threads: int = 0
    device_type: str = "tpu"
    seed: int = 0
    deterministic: bool = False

    # --- Learning control (config.h:267-615) ---
    force_col_wise: bool = False
    force_row_wise: bool = False
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    feature_fraction_seed: int = 2
    extra_trees: bool = False
    extra_seed: int = 6
    early_stopping_round: int = 0
    first_metric_only: bool = False
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    linear_lambda: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20
    monotone_constraints: List[int] = field(default_factory=list)
    monotone_constraints_method: str = "basic"
    monotone_penalty: float = 0.0
    feature_contri: List[float] = field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: List[float] = field(default_factory=list)
    cegb_penalty_feature_coupled: List[float] = field(default_factory=list)
    path_smooth: float = 0.0
    interaction_constraints: Union[str, List[List[int]]] = ""
    verbosity: int = 1
    input_model: str = ""
    output_model: str = "LightGBM_model.txt"
    saved_feature_importance_type: int = 0
    snapshot_freq: int = -1
    linear_tree: bool = False

    # --- Dataset (config.h:622-756) ---
    max_bin: int = 255
    max_bin_by_feature: List[int] = field(default_factory=list)
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    data_random_seed: int = 1
    is_enable_sparse: bool = True
    enable_bundle: bool = True
    use_missing: bool = True
    zero_as_missing: bool = False
    feature_pre_filter: bool = True
    pre_partition: bool = False
    two_round: bool = False
    # progress-log interval for text loading (config.h:679); accepted
    # for conf compatibility — the numpy/native-parser loaders finish
    # in one pass without incremental progress logging
    file_load_progress_interval_bytes: int = 10 * 1024 * 1024 * 1024
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: Union[str, List[int]] = ""
    forcedbins_filename: str = ""
    save_binary: bool = False
    precise_float_parser: bool = False
    parser_config_file: str = ""

    # --- Predict / convert (config.h:768-850) ---
    start_iteration_predict: int = 0
    num_iteration_predict: int = -1
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    predict_disable_shape_check: bool = False
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    # TPU addition: allow Booster.predict to route large batches through
    # the stacked-forest device path (serve/) when it can reproduce the
    # host walk bit-for-bit; per-call override via the
    # ``predict_on_device`` predict kwarg
    predict_on_device: bool = True
    output_result: str = "LightGBM_predict_result.txt"
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"

    # --- Objective (config.h:862-936) ---
    objective_seed: int = 5
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    lambdarank_truncation_level: int = 30
    lambdarank_norm: bool = True
    label_gain: List[float] = field(default_factory=list)

    # --- Metric (config.h:975-1012) ---
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])
    multi_error_top_k: int = 1
    auc_mu_weights: List[float] = field(default_factory=list)

    # --- Network (config.h:1024-1045) ---
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""

    # --- Device (config.h:1056-1070; TPU-native replacements) ---
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    num_gpu: int = 1
    # TPU additions:
    tpu_use_f64_hist: bool = False   # analogue of gpu_use_dp (f64 hist accum)
    # quantized-gradient training (reference: use_quantized_grad +
    # num_grad_quant_bins, config.h / gradient_discretizer.cpp):
    # per-iteration (grad, hess) discretization to int8/int16 rows with
    # stochastic rounding; histograms accumulate in int32/int64 (exact
    # subtraction), split gain dequantizes once per scan. 4x fewer
    # bandwidth bytes through the histogram hot op, int-MXU matmuls on
    # TPU, and half the psum bytes on data-parallel meshes.
    use_quantized_grad: bool = False
    quant_grad_bits: int = 8         # 8 or 16
    # run N boosting iterations per device dispatch when nothing needs
    # per-iteration host work (boosting/gbdt.py train_batch); amortizes
    # remote-chip dispatch latency. 0/1 = per-iteration training.
    tpu_batch_iterations: int = 0
    # eval hoisting (pipelined boosting): run metric evaluation — and
    # the after-iteration callbacks it feeds, incl. the early-stopping
    # check — only when the iteration count crosses a multiple of k
    # (absolute grid, resume-invariant), plus always at the final /
    # stopping iteration. The early-stopping patience window still
    # counts in iterations; k only coarsens where the check can fire.
    # 0/1 = evaluate every iteration (every batch boundary when
    # tpu_batch_iterations is on).
    tpu_eval_iterations: int = 0
    # fused whole-tree growth (treelearner/serial.py): histogram →
    # split scan → partition for the entire tree runs as ONE jitted
    # while_loop dispatch with a device-resident frontier, reading back
    # only the finished [L-1] split-record buffer (bit-identical to the
    # stepped host loop). False keeps the legacy per-batch host loop.
    tpu_fused_tree: bool = True
    # out-of-core frontier batching (treelearner/sharded.py): speculate
    # up to K pending best-split candidates per shard sweep — each
    # staging applies K partition updates and histograms K children —
    # cutting shard staging traffic up to K× per tree while the
    # device-validated finish keeps trees bit-identical to serial
    # growth. 0/1 = legacy one-split-per-sweep.
    tpu_frontier_splits: int = 8
    hist_backend: str = "auto"       # auto | scatter | onehot | pallas
    mesh_shape: str = ""             # e.g. "data=8" or "data=4,feature=2"

    # raw params as given by the user (for model "parameters:" section)
    raw_params: Dict[str, Any] = field(default_factory=dict, repr=False)

    # ------------------------------------------------------------------
    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]]) -> "Config":
        """Resolve aliases, coerce types, validate — reference Config::Set
        (src/io/config.cpp) + alias transform (application.cpp:50-86)."""
        params = dict(params or {})
        # apply verbosity first so it governs parse-time warnings
        for vkey in ("verbosity", "verbose"):
            if vkey in params:
                try:
                    log.set_verbosity(int(params[vkey]))
                except (TypeError, ValueError):
                    pass
                break
        cfg = cls()
        cfg.raw_params = dict(params)
        resolved: Dict[str, Any] = {}
        fields = {f.name: f for f in dataclasses.fields(cls)}
        # Canonical-name-wins alias transform (reference:
        # ParameterAlias::KeyAliasTransform, include/LightGBM/config.h:1159 —
        # a key spelled with the canonical name always overrides aliases;
        # among multiple aliases the first-sorted one wins).
        resolved_from: Dict[str, str] = {}
        for key in sorted(params):
            value = params[key]
            name = _ALIASES.get(key, key)
            if name not in fields:
                log.warning("Unknown parameter: %s", key)
                continue
            if name in resolved:
                is_canonical = key == name
                prev_canonical = resolved_from[name] == name
                if prev_canonical or not is_canonical:
                    log.warning("%s is set=%s, %s=%s will be ignored. "
                                "Current value: %s=%s", name, resolved[name],
                                key, value, name, resolved[name])
                    continue
            resolved[name] = value
            resolved_from[name] = key
        for name, value in resolved.items():
            setattr(cfg, name, _coerce(fields[name], value))
        cfg._post_process()
        return cfg

    # ------------------------------------------------------------------
    def _post_process(self) -> None:
        obj = str(self.objective).strip().lower()
        if obj not in _OBJECTIVE_ALIASES:
            log.fatal("Unknown objective: %s" % self.objective)
        self.objective = _OBJECTIVE_ALIASES[obj]
        self.metric = self._resolve_metrics(self.metric)
        self.boosting = {
            "gbdt": "gbdt", "gbrt": "gbdt", "dart": "dart", "rf": "rf",
            "random_forest": "rf", "goss": "goss",
        }.get(str(self.boosting).lower(), None) or log.fatal(
            "Unknown boosting type: %s" % self.boosting)
        # 'goss' as boosting is the deprecated spelling of
        # data_sample_strategy=goss (reference: config.cpp GetBoostingType)
        if self.boosting == "goss":
            self.boosting = "gbdt"
            self.data_sample_strategy = "goss"
        if self.tree_learner not in ("serial", "feature", "data", "voting"):
            log.fatal("Unknown tree learner: %s" % self.tree_learner)
        if self.device_type not in ("cpu", "gpu", "cuda", "tpu"):
            log.fatal("Unknown device type: %s" % self.device_type)
        # validations (reference: Config::Set CHECK calls)
        if self.num_leaves < 2:
            log.fatal("num_leaves must be >= 2")
        if not (0.0 < self.bagging_fraction <= 1.0):
            log.fatal("bagging_fraction should be in (0.0, 1.0]")
        if not (0.0 < self.feature_fraction <= 1.0):
            log.fatal("feature_fraction should be in (0.0, 1.0]")
        if self.max_bin < 2:
            log.fatal("max_bin should be >= 2")
        if self.objective in ("multiclass", "multiclassova") and self.num_class < 2:
            log.fatal("num_class should be >= 2 for multiclass objectives")
        if self.objective not in ("multiclass", "multiclassova", "custom") \
                and self.num_class != 1:
            log.fatal("num_class must be 1 for non-multiclass objectives")
        if self.top_rate + self.other_rate > 1.0:
            log.fatal("top_rate + other_rate cannot be larger than 1.0")
        if self.quant_grad_bits not in (8, 16):
            log.fatal("quant_grad_bits must be 8 or 16")
        self._warn_unimplemented()
        log.set_verbosity(self.verbosity)

    def _warn_unimplemented(self) -> None:
        """Accepted-but-not-yet-implemented knobs warn LOUDLY instead of
        silently corrupting experiments (round-2 review, Weak #5).
        Pure CPU-layout hints are no-ops by design on the TPU build."""
        if self.monotone_constraints_method not in (
                "basic", "intermediate", "advanced"):
            log.warning("unknown monotone_constraints_method=%s; "
                        "falling back to 'basic'"
                        % self.monotone_constraints_method)
            self.monotone_constraints_method = "basic"
        if self.two_round:
            log.warning("two_round loading is a CPU-memory staging hint "
                        "with no effect in this build")
        if self.parser_config_file:
            log.warning("parser_config_file (custom parser plugins) is "
                        "not supported; the built-in CSV/TSV/LibSVM "
                        "parsers are used")
        if self.force_col_wise or self.force_row_wise:
            log.warning("force_col_wise/force_row_wise are CPU histogram "
                        "layout hints; the TPU build always uses one "
                        "row-major device layout")

    @staticmethod
    def _resolve_metrics(metrics: Any) -> List[str]:
        if isinstance(metrics, str):
            metrics = [m for m in metrics.split(",") if m.strip()]
        out: List[str] = []
        for m in metrics:
            m = str(m).strip().lower()
            if m == "":
                continue
            if m not in _METRIC_ALIASES:
                log.fatal("Unknown metric: %s" % m)
            canonical = _METRIC_ALIASES[m]
            if canonical not in out:
                out.append(canonical)
        return out

    # number of models ("trees per iteration") — reference gbdt.cpp:88
    @property
    def num_tree_per_iteration(self) -> int:
        return self.num_class if self.objective in ("multiclass", "multiclassova") else 1

    def to_param_string(self) -> str:
        """key: value lines for the model file 'parameters:' block
        (reference: Config::ToString used by gbdt_model_text.cpp:385)."""
        lines = []
        for f in dataclasses.fields(self):
            if f.name == "raw_params":
                continue
            v = getattr(self, f.name)
            if isinstance(v, bool):
                v = int(v)
            elif isinstance(v, list):
                v = ",".join(str(x) for x in v)
            lines.append(f"[{f.name}: {v}]")
        return "\n".join(lines)


def _coerce(fld: dataclasses.Field, value: Any) -> Any:
    """Coerce a user-supplied value to the field's declared type."""
    tp = fld.type if isinstance(fld.type, str) else getattr(fld.type, "__name__", "")
    if tp.startswith("bool"):
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes", "+")
        return bool(value)
    if tp.startswith("int"):
        return int(value)
    if tp.startswith("float"):
        return float(value)
    if tp.startswith("List[int]"):
        return _parse_list(value, int)
    if tp.startswith("List[float]"):
        return _parse_list(value, float)
    if tp.startswith("List[str]") or tp.startswith("List[List"):
        if isinstance(value, str):
            return [s for s in value.split(",") if s]
        return list(value)
    if tp.startswith("str"):
        return str(value)
    return value


def _parse_list(value: Any, typ) -> list:
    if isinstance(value, str):
        return [typ(x) for x in value.split(",") if x.strip()]
    if isinstance(value, (list, tuple)):
        return [typ(x) for x in value]
    return [typ(value)]
