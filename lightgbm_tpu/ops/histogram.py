"""Histogram construction — the hot op of GBDT training.

TPU-native replacement for the reference's histogram kernels
(CPU: src/io/dense_bin.hpp:99 ``ConstructHistogramInner``; GPU:
src/treelearner/ocl/histogram256.cl; CUDA:
src/treelearner/cuda/cuda_histogram_constructor.cu:18). Those are
scatter-add loops — per row, `hist[bin] += (grad, hess)` — which TPUs
execute poorly (XLA serializes scatters). Instead we reformulate the
accumulation as a one-hot contraction that runs on the MXU:

    onehot[t, f, b] = (bins[t, f] == b)           # exact in any dtype
    hist[f, b, c]   = sum_t onehot[t, f, b] * gh[t, c]

i.e. for each feature a [B, T] @ [T, C] matmul. A `lax.scan` over row
tiles bounds the materialized one-hot to a few MB so XLA keeps it in
VMEM; accumulation is f32. ``precision=HIGHEST`` makes the f32 matmul
exact-enough (bf16x6 passes) — the one-hot factor is exactly
representable, so error is only the f32 accumulation order, same class
as the reference's GPU path (single-precision hists, gpu_use_dp=0,
docs/GPU-Performance.rst precedent).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

# Rows per one-hot tile. VMEM footprint of the one-hot is
# ROW_TILE * F * B * 4 bytes per scan step; XLA additionally tiles the
# contraction, so this just bounds the scan carry granularity.
DEFAULT_ROW_TILE = 512

# Rows per Pallas grid step (the kernel's VMEM working set scales with
# this; 2048 rows × 28 features ≈ 1.2 MB of transients).
PALLAS_ROW_TILE = 2048


def resolve_hist_impl(backend: str = "auto",
                      f64: bool = False,
                      quant_bits: int = 0) -> tuple:
    """Validate Config.hist_backend / Config.tpu_use_f64_hist /
    Config.use_quantized_grad into a static (backend, f64, quant_bits)
    triple the learners thread through their compiled-step cache keys
    (f64 is the analogue of the reference's gpu_use_dp,
    docs/GPU-Performance.rst; quant_bits > 0 selects the integer
    accumulation paths of ops/quantize.py). f64 accumulation requires
    jax_enable_x64 and disables the Pallas kernel (f32-only); it is
    moot under quantization (integer accumulation is already exact), so
    the two together resolve to the quantized mode."""
    backend = (backend or "auto").lower()
    if backend not in ("auto", "onehot", "pallas", "scatter"):
        from ..utils import log
        log.warning("unknown hist_backend=%s; using auto" % backend)
        backend = "auto"
    quant_bits = int(quant_bits or 0)
    if quant_bits not in (0, 8, 16):
        from ..utils import log
        log.warning("quant_grad_bits must be 8 or 16; got %d — using 8"
                    % quant_bits)
        quant_bits = 8
    if f64 and quant_bits:
        _warn_once("tpu_use_f64_hist is ignored under use_quantized_grad "
                   "(integer histogram accumulation is already exact)")
        f64 = False
    if f64 and not jax.config.jax_enable_x64:
        from ..utils import log
        log.warning("tpu_use_f64_hist needs jax_enable_x64; histograms "
                    "stay f32")
        f64 = False
    return backend, bool(f64), quant_bits


# VMEM budget for the Pallas kernel's resident blocks (accumulator +
# row tile + transients). Real cores have ~128 MiB; stay well under so
# Mosaic's own spills/copies fit too.
PALLAS_VMEM_BUDGET = 64 * 1024 * 1024


def _pallas_fits(F: int, num_bins: int, C: int,
                 T: int = PALLAS_ROW_TILE, itemsize: int = 4) -> bool:
    """Static VMEM bound for the kernel's working set: the [F*H, 16*C]
    accumulator (always 4-byte f32/int32) stays resident across the
    grid, plus the per-step row tile and its one-hot/replicated
    transients at the input itemsize (1 byte in int8 mode — which is
    what lets the quantized kernel run a 4x wider row tile)."""
    H = -(-num_bins // 16)
    acc = F * H * 16 * C * 4
    tile = T * F * itemsize + T * C * itemsize   # bins + gh blocks
    trans = T * 16 * C * itemsize * 2 + T * H * itemsize  # g_rep, W, A
    return acc + tile + trans <= PALLAS_VMEM_BUDGET


def _warn_once(msg: str, component: str = "ops.histogram") -> None:
    """One warning per distinct message — but only count it as warned
    when the current verbosity actually emits it, so a training run at
    verbosity=-1 does not permanently swallow the downgrade notice.
    Every distinct message ALSO emits one ``perf_warning`` event
    (regardless of verbosity — the events sink is how tests assert that
    no silent backend fallback happened). ``component`` names the
    module the condition originates in for event-log consumers."""
    from ..utils import log
    if msg not in _warn_once._emitted:
        _warn_once._emitted.add(msg)
        from ..obs import events as obs_events
        obs_events.emit("perf_warning", component=component,
                        message=msg)
    if log._level < log.LogLevel.WARNING:
        return
    if msg in _warn_once._seen:
        return
    _warn_once._seen.add(msg)
    log.warning(msg)


_warn_once._seen = set()
_warn_once._emitted = set()


def _reset_warn_once() -> None:
    """Clear the one-per-message dedup on registry reset (the
    obs/compile._WARNED pattern): a new run — or a test that resets the
    registry — must get its own warning AND its own assertable
    perf_warning event, not a silence inherited from the previous
    run."""
    _warn_once._seen.clear()
    _warn_once._emitted.clear()


from ..obs import compile as obs_compile  # noqa: E402
from ..obs.registry import add_reset_hook  # noqa: E402

add_reset_hook(_reset_warn_once)


@functools.lru_cache(maxsize=1)
def _use_pallas() -> bool:
    """Pallas path only on real TPU backends; the einsum-scan fallback
    serves CPU tests and interpret-mode debugging. A tiny probe kernel
    runs once per process so a Mosaic compile/runtime failure degrades
    to the fallback instead of killing training."""
    if os.environ.get("LGBM_TPU_NO_PALLAS"):
        return False
    try:
        if jax.default_backend() != "tpu" or _pl is None:
            return False
        probe = _pallas_histogram(
            jnp.zeros((PALLAS_ROW_TILE, 2), dtype=jnp.uint8),
            jnp.ones((PALLAS_ROW_TILE, 4), dtype=jnp.float32),
            16, PALLAS_ROW_TILE)
        # jaxlint: disable=JLT001 -- one-shot backend-selection probe
        # (lru_cached once per process), not a training hot path
        ok = float(jax.device_get(probe)[0, 0, 3]) == float(
            PALLAS_ROW_TILE)
        if not ok:
            from ..utils import log
            log.warning("Pallas histogram probe produced wrong sums; "
                        "using the einsum fallback")
        return ok
    except Exception as e:  # pragma: no cover - depends on runtime
        from ..utils import log
        log.warning("Pallas histogram unavailable (%s); using the "
                    "einsum fallback" % type(e).__name__)
        return False


def _acc_dtype_of(gh_dtype):
    """Accumulator dtype per gh row dtype: integer rows accumulate in
    int32/int64 (ops/quantize.py overflow discipline), f64 stays f64,
    anything else f32."""
    if jnp.issubdtype(jnp.dtype(gh_dtype), jnp.integer):
        from .quantize import acc_dtype
        return acc_dtype(gh_dtype)
    return jnp.float64 if gh_dtype == jnp.float64 else jnp.float32


def _segment_histogram(bins: jnp.ndarray, gh: jnp.ndarray,
                       num_bins: int) -> jnp.ndarray:
    """Scatter-add formulation via a flat segment-sum — the direct
    analogue of the reference's CPU hot loop (dense_bin.hpp:99
    ``ConstructHistogramInner``: per row, hist[bin] += (g, h)). On CPU
    this is ~20x less work than the one-hot contraction (O(S·F·C)
    updates vs O(S·F·B·C) FLOPs); on TPU the MXU prefers the matmul
    forms, so this path is selected only for CPU backends. Integer gh
    accumulates int32/int64 — exact and order-invariant — and the int8
    value stream is 4x fewer bytes than f32 through the bandwidth-bound
    broadcast+scatter."""
    S, F = bins.shape
    C = gh.shape[1]
    acc_dtype = _acc_dtype_of(gh.dtype)
    flat = (jnp.arange(F, dtype=jnp.int32)[None, :] * num_bins
            + bins.astype(jnp.int32)).reshape(-1)            # [S*F]
    vals = jnp.broadcast_to(
        gh.astype(acc_dtype)[:, None, :], (S, F, C)).reshape(-1, C)
    out = jax.ops.segment_sum(vals, flat, num_segments=F * num_bins)
    out = out.reshape(F, num_bins, C)
    return out if jnp.issubdtype(acc_dtype, jnp.integer) \
        else out.astype(jnp.float32)


def _tile_histogram(bins_tile: jnp.ndarray, gh_tile: jnp.ndarray,
                    num_bins: int) -> jnp.ndarray:
    """[T, F] uint bins x [T, C] stats -> [F, B, C] partial histogram.
    Accumulates in gh's dtype family (f64 under tpu_use_f64_hist, else
    f32; int32/int64 for quantized integer gh — the int8 x int8 one-hot
    contraction is the MXU's native low-precision matmul shape)."""
    acc_dtype = _acc_dtype_of(gh_tile.dtype)
    onehot = (bins_tile.astype(jnp.int32)[:, :, None]
              == jnp.arange(num_bins, dtype=jnp.int32)[None, None, :])
    if jnp.issubdtype(acc_dtype, jnp.integer):
        # exact in any precision; the one-hot factor rides the row dtype
        # so the contraction stays int8/int16 into an int32/int64 sum
        return jnp.einsum(
            "tfb,tc->fbc", onehot.astype(gh_tile.dtype), gh_tile,
            preferred_element_type=acc_dtype)
    return jnp.einsum(
        "tfb,tc->fbc", onehot.astype(acc_dtype), gh_tile,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=acc_dtype)


def _hist_kernel_body(T: int, F: int, H: int, C: int, bins_ref, gh_ref,
                      out_ref):
    """Pallas TPU kernel: one grid step accumulates a [T, F] row tile
    into the [F*H, 16*C] VMEM-resident histogram accumulator.

    The bin index factorizes as ``bin = hi*16 + lo``; per feature the
    contribution is ``A_f^T @ W_f`` where ``A_f[t, hi]`` is the hi-nibble
    one-hot and ``W_f[t, lo*C+c] = (lo_f[t] == lo) * gh[t, c]``. This
    shapes the MXU matmul as [H, T] x [T, 16*C] — N = 16*C lanes instead
    of the naive one-hot's N = C, and the one-hot factors never leave
    VMEM (the einsum fallback materializes S*F*B floats through HBM).
    Equivalent of the reference's shared-memory histogram kernels
    (cuda_histogram_constructor.cu:18, ocl/histogram256.cl).

    The body is dtype-generic: quantized int8 gh rows contract as
    int8 x int8 MXU matmuls into an int32 accumulator (the one-hot
    factors and transients ride the 1-byte row dtype, which is what
    lets the quantized caller run the 4x wider PALLAS_ROW_TILE_INT in
    the same VMEM budget); f32 rows keep the f32 accumulator."""
    @_pl.when(_pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    b = bins_ref[...].astype(jnp.int32)          # [T, F]
    g = gh_ref[...]                              # [T, C]
    hi = b >> 4
    lo = b & 15
    g_rep = jnp.tile(g, (1, 16))                 # [T, 16*C]
    lane_lo = (jax.lax.broadcasted_iota(jnp.int32, (1, 16 * C), 1)
               // C)                             # [1, 16*C]
    iota_h = jax.lax.broadcasted_iota(jnp.int32, (1, H), 1)
    zero = jnp.zeros((), dtype=g.dtype)
    acc_t = (jnp.int32 if jnp.issubdtype(g.dtype, jnp.integer)
             else jnp.float32)

    def body(f, carry):
        hi_f = jax.lax.dynamic_slice(hi, (0, f), (T, 1))     # [T, 1]
        lo_f = jax.lax.dynamic_slice(lo, (0, f), (T, 1))
        A = (hi_f == iota_h).astype(g.dtype)                 # [T, H]
        W = jnp.where(lo_f == lane_lo, g_rep, zero)          # [T, 16C]
        acc = jax.lax.dot_general(
            A, W, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_t)                    # [H, 16C]
        out_ref[_pl.ds(f * H, H), :] += acc
        return carry

    jax.lax.fori_loop(0, F, body, 0)


try:  # Pallas is TPU-only machinery; import lazily-tolerantly
    from jax.experimental import pallas as _pl
    from jax.experimental.pallas import tpu as _pltpu
except Exception:  # pragma: no cover
    _pl = None
    _pltpu = None


# int8 rows: 1-byte tiles/transients let 4x the rows sit in the same
# VMEM working set as the f32 kernel's PALLAS_ROW_TILE
PALLAS_ROW_TILE_INT = 4 * PALLAS_ROW_TILE


def _pallas_histogram_body(bins: jnp.ndarray, gh: jnp.ndarray,
                           num_bins: int, row_tile: int) -> jnp.ndarray:
    S, F = bins.shape
    C = gh.shape[1]
    H = -(-num_bins // 16)                       # hi-nibble width
    T = row_tile
    pad = (-S) % T
    if pad:
        bins = jnp.concatenate(
            [bins, jnp.zeros((pad, F), dtype=bins.dtype)])
        gh = jnp.concatenate([gh, jnp.zeros((pad, C), dtype=gh.dtype)])
    n_tiles = bins.shape[0] // T
    quantized = jnp.issubdtype(gh.dtype, jnp.integer)
    out_dtype = jnp.int32 if quantized else jnp.float32
    kernel = functools.partial(_hist_kernel_body, T, F, H, C)
    out = _pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            _pl.BlockSpec((T, F), lambda i: (i, 0)),
            _pl.BlockSpec((T, C), lambda i: (i, 0)),
        ],
        out_specs=_pl.BlockSpec((F * H, 16 * C), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((F * H, 16 * C), out_dtype),
    )(bins, gh)
    # [F*H, 16*C] -> [F, H*16, C] -> [F, B, C]
    hist = out.reshape(F, H, 16, C).reshape(F, H * 16, C)
    return hist[:, :num_bins, :]


_pallas_histogram = obs_compile.instrument_jit(
    "ops.pallas_histogram", _pallas_histogram_body,
    static_argnums=(2, 3))


def build_histogram(bins: jnp.ndarray, gh: jnp.ndarray, num_bins: int,
                    row_tile: int = DEFAULT_ROW_TILE,
                    pallas_ok: bool = True,
                    hist_impl: tuple = ("auto", False)) -> jnp.ndarray:
    """Accumulate (grad, hess, count) per (feature, bin).

    Parameters
    ----------
    bins : uint8/uint16/int32 [S, F] — quantized rows (padding rows must
        carry gh == 0; their bin values are irrelevant)
    gh : f32 [S, C] — per-row stats; C is typically 3 = (grad, hess, in-bag)
    num_bins : static histogram width B
    pallas_ok : callers whose rows are SHARDED across a device mesh must
        pass False — pallas_call has no SPMD partitioning rule, so GSPMD
        would all-gather the full bins array per device; the einsum path
        partitions cleanly and lets XLA insert the psum.
    hist_impl : STATIC (backend, f64[, quant_bits]) from
        resolve_hist_impl — callers thread it through their compiled-fn
        cache keys so a setting is never baked stale into a cached
        trace.

    Returns f32 [F, B, C] — or int32/int64 [F, B, C] when ``gh`` holds
    quantized integer rows (ops/quantize.py): integer accumulation is
    exact and order-invariant, and the caller dequantizes once per
    split scan (ops/split.py).
    """
    backend, f64 = hist_impl[0], hist_impl[1]
    S, F = bins.shape
    C = gh.shape[1]
    quantized = jnp.issubdtype(jnp.dtype(gh.dtype), jnp.integer)
    if quantized:
        f64 = False
    # quantized Pallas: int8 rows only (the int16 mode's int64
    # accumulator has no kernel variant; it takes the einsum path)
    p_tile = PALLAS_ROW_TILE_INT if quantized else PALLAS_ROW_TILE
    p_item = 1 if quantized else 4
    want_pallas = (pallas_ok and not f64
                   and backend not in ("onehot", "scatter")
                   and (not quantized or gh.dtype == jnp.int8)
                   and S >= p_tile and C <= 8
                   and _pallas_fits(F, num_bins, C, p_tile, p_item))
    if backend == "pallas" and not (want_pallas and _use_pallas()):
        # Explicit request could not be honored — say why (round-3
        # advisor: a silent downgrade skews kernel benchmarks).
        why = ("sharded-mesh caller" if not pallas_ok else
               "f64 histograms" if f64 else
               "int16 quantized rows (int64 accumulation)"
               if quantized and gh.dtype != jnp.int8 else
               "S=%d < %d row tile" % (S, p_tile)
               if S < p_tile else
               "C=%d > 8 stat columns" % C if C > 8 else
               "VMEM bound (F=%d B=%d)" % (F, num_bins)
               if not _pallas_fits(F, num_bins, C, p_tile, p_item) else
               "no TPU backend / probe failed")
        _warn_once("hist_backend=pallas requested but unavailable here "
                   "(%s); using the einsum path" % why)
    if want_pallas and _use_pallas():
        if isinstance(bins, jax.core.Tracer):
            return _pallas_histogram(bins, gh, num_bins, p_tile)
        try:  # concrete call: compile failures are catchable — degrade
            return _pallas_histogram(bins, gh, num_bins, p_tile)
        except Exception as e:  # pragma: no cover - runtime-dependent
            _warn_once("Pallas histogram failed at shape F=%d B=%d (%s); "
                       "einsum fallback for this and later calls"
                       % (F, num_bins, type(e).__name__))
            _use_pallas.cache_clear()
            os.environ["LGBM_TPU_NO_PALLAS"] = "1"
    if f64:
        gh = gh.astype(jnp.float64)
    if backend == "scatter" or (backend == "auto"
                                and jax.default_backend() == "cpu"):
        return _segment_histogram(bins, gh, num_bins)
    acc_dtype = _acc_dtype_of(gh.dtype)
    out_dtype = acc_dtype if quantized else jnp.float32
    if S <= row_tile:
        return _tile_histogram(bins, gh, num_bins).astype(out_dtype)
    # Pad S to a tile multiple; padded rows use gh = 0 so they vanish.
    pad = (-S) % row_tile
    if pad:
        bins = jnp.concatenate(
            [bins, jnp.zeros((pad, F), dtype=bins.dtype)])
        gh = jnp.concatenate([gh, jnp.zeros((pad, C), dtype=gh.dtype)])
    n_tiles = bins.shape[0] // row_tile
    bins_t = bins.reshape(n_tiles, row_tile, F)
    gh_t = gh.reshape(n_tiles, row_tile, C)

    def step(acc, xs):
        b, g = xs
        return acc + _tile_histogram(b, g, num_bins).astype(acc.dtype), \
            None

    init = jnp.zeros((F, num_bins, C), dtype=acc_dtype)
    hist, _ = jax.lax.scan(step, init, (bins_t, gh_t))
    return hist.astype(out_dtype)


def subtract_histogram(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """Sibling histogram via subtraction (reference:
    serial_tree_learner.cpp:421-424 ``larger.Subtract(smaller)``)."""
    return parent - child


def mask_gh(gh: jnp.ndarray, keep) -> jnp.ndarray:
    """Dtype-preserving row mask: zero the gh rows where ``keep`` is
    False (``keep`` is [S] per-row or a scalar). A float multiply
    would silently de-quantize integer gh rows; ``where`` against a
    same-dtype zero keeps the int8/int16 stream intact."""
    keep = jnp.asarray(keep)
    if keep.ndim == 1:
        keep = keep[:, None]
    return jnp.where(keep, gh, jnp.zeros((), dtype=gh.dtype))


def unpack_bundle_histogram(bhist: jnp.ndarray,
                            gidx_g: jnp.ndarray, gidx_b: jnp.ndarray,
                            zero_fix: jnp.ndarray, zero_bins: jnp.ndarray,
                            totals: jnp.ndarray) -> jnp.ndarray:
    """Bundle histogram [G, Bg, C] → per-feature histogram [F, B, C].

    EFB support (reference: the per-feature slicing of FeatureGroup
    histograms + FixHistogram zero-bin reconstruction,
    src/io/dataset.cpp): a bundled feature's non-zero bins gather 1:1
    from its bundle sub-range (static index tables ``gidx_g``/``gidx_b``,
    -1 = no source), and its zero-bin row is leaf_total − Σ(non-zero) —
    exclusivity means rows under other members' bins are zero rows of
    this feature.

    totals : [C] — the leaf's (grad, hess, count, total) sums, in the
        histogram's own dtype (f32, or int32/int64 in quantized mode —
        where the zero-bin residual reconstruction is EXACT integer
        arithmetic instead of an f32 cancellation).
    """
    F = gidx_g.shape[0]
    zero = jnp.zeros((), dtype=bhist.dtype)
    safe_g = jnp.maximum(gidx_g, 0)
    hist = bhist[safe_g, gidx_b]                       # [F, B, C]
    hist = jnp.where((gidx_g >= 0)[..., None], hist, zero)
    resid = (totals.astype(bhist.dtype)[None, :]
             - jnp.sum(hist, axis=1))                  # [F, C]
    fix = jnp.where(zero_fix[:, None], resid, zero)
    return hist.at[jnp.arange(F), zero_bins].add(fix)
