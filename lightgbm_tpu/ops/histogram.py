"""Histogram construction — the hot op of GBDT training.

TPU-native replacement for the reference's histogram kernels
(CPU: src/io/dense_bin.hpp:99 ``ConstructHistogramInner``; GPU:
src/treelearner/ocl/histogram256.cl; CUDA:
src/treelearner/cuda/cuda_histogram_constructor.cu:18). Those are
scatter-add loops — per row, `hist[bin] += (grad, hess)` — which TPUs
execute poorly (XLA serializes scatters). Instead we reformulate the
accumulation as a one-hot contraction that runs on the MXU:

    onehot[t, f, b] = (bins[t, f] == b)           # exact in any dtype
    hist[f, b, c]   = sum_t onehot[t, f, b] * gh[t, c]

i.e. for each feature a [B, T] @ [T, C] matmul. A `lax.scan` over row
tiles bounds the materialized one-hot to a few MB so XLA keeps it in
VMEM; accumulation is f32. ``precision=HIGHEST`` makes the f32 matmul
exact-enough (bf16x6 passes) — the one-hot factor is exactly
representable, so error is only the f32 accumulation order, same class
as the reference's GPU path (single-precision hists, gpu_use_dp=0,
docs/GPU-Performance.rst precedent).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Rows per one-hot tile. VMEM footprint of the one-hot is
# ROW_TILE * F * B * 4 bytes per scan step; XLA additionally tiles the
# contraction, so this just bounds the scan carry granularity.
DEFAULT_ROW_TILE = 512


def _tile_histogram(bins_tile: jnp.ndarray, gh_tile: jnp.ndarray,
                    num_bins: int) -> jnp.ndarray:
    """[T, F] uint bins x [T, C] stats -> [F, B, C] partial histogram."""
    onehot = (bins_tile.astype(jnp.int32)[:, :, None]
              == jnp.arange(num_bins, dtype=jnp.int32)[None, None, :])
    return jnp.einsum(
        "tfb,tc->fbc", onehot.astype(jnp.float32), gh_tile,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


def build_histogram(bins: jnp.ndarray, gh: jnp.ndarray, num_bins: int,
                    row_tile: int = DEFAULT_ROW_TILE) -> jnp.ndarray:
    """Accumulate (grad, hess, count) per (feature, bin).

    Parameters
    ----------
    bins : uint8/uint16/int32 [S, F] — quantized rows (padding rows must
        carry gh == 0; their bin values are irrelevant)
    gh : f32 [S, C] — per-row stats; C is typically 3 = (grad, hess, in-bag)
    num_bins : static histogram width B

    Returns f32 [F, B, C].
    """
    S, F = bins.shape
    C = gh.shape[1]
    if S <= row_tile:
        return _tile_histogram(bins, gh, num_bins)
    # Pad S to a tile multiple; padded rows use gh = 0 so they vanish.
    pad = (-S) % row_tile
    if pad:
        bins = jnp.concatenate(
            [bins, jnp.zeros((pad, F), dtype=bins.dtype)])
        gh = jnp.concatenate([gh, jnp.zeros((pad, C), dtype=gh.dtype)])
    n_tiles = bins.shape[0] // row_tile
    bins_t = bins.reshape(n_tiles, row_tile, F)
    gh_t = gh.reshape(n_tiles, row_tile, C)

    def step(acc, xs):
        b, g = xs
        return acc + _tile_histogram(b, g, num_bins), None

    init = jnp.zeros((F, num_bins, C), dtype=jnp.float32)
    hist, _ = jax.lax.scan(step, init, (bins_t, gh_t))
    return hist


def subtract_histogram(parent: jnp.ndarray, child: jnp.ndarray) -> jnp.ndarray:
    """Sibling histogram via subtraction (reference:
    serial_tree_learner.cpp:421-424 ``larger.Subtract(smaller)``)."""
    return parent - child


def unpack_bundle_histogram(bhist: jnp.ndarray,
                            gidx_g: jnp.ndarray, gidx_b: jnp.ndarray,
                            zero_fix: jnp.ndarray, zero_bins: jnp.ndarray,
                            totals: jnp.ndarray) -> jnp.ndarray:
    """Bundle histogram [G, Bg, C] → per-feature histogram [F, B, C].

    EFB support (reference: the per-feature slicing of FeatureGroup
    histograms + FixHistogram zero-bin reconstruction,
    src/io/dataset.cpp): a bundled feature's non-zero bins gather 1:1
    from its bundle sub-range (static index tables ``gidx_g``/``gidx_b``,
    -1 = no source), and its zero-bin row is leaf_total − Σ(non-zero) —
    exclusivity means rows under other members' bins are zero rows of
    this feature.

    totals : f32[C] — the leaf's (grad, hess, count, total) sums.
    """
    F = gidx_g.shape[0]
    safe_g = jnp.maximum(gidx_g, 0)
    hist = bhist[safe_g, gidx_b]                       # [F, B, C]
    hist = jnp.where((gidx_g >= 0)[..., None], hist, 0.0)
    resid = totals[None, :] - jnp.sum(hist, axis=1)    # [F, C]
    fix = jnp.where(zero_fix[:, None], resid, 0.0)
    return hist.at[jnp.arange(F), zero_bins].add(fix)
