"""Device-side tree traversal over binned rows.

TPU-native replacement for the host Python node-walk the round-2 review
flagged (models/tree.py predict_by_bin): validation scoring runs per tree
per valid set per iteration, so it must be a device op, not a host loop.

Reference analogue: the CUDA build keeps valid scores on device and walks
trees with a kernel (src/boosting/cuda/cuda_score_updater.*,
src/io/cuda/cuda_tree.cu AddPredictionToScoreKernel). Here the walk is a
lockstep vectorized loop: every row advances one level per iteration of a
``lax.fori_loop`` whose trip count is the tree depth (padded to a power of
two so compiled variants are shared across trees of similar depth). Nodes
are flat arrays (gathers), leaves encoded as ``~leaf`` negatives exactly
like the host Tree / reference tree.h:25.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.binning import MissingType
from ..models.tree import Tree, kCategoricalMask, kDefaultLeftMask
from ..utils import next_pow2 as _next_pow2


class DeviceTree(NamedTuple):
    """Flat node arrays of one tree, padded to a power-of-two node count
    (padding keeps the jitted traversal shared across trees)."""
    feat: jnp.ndarray          # [NI] i32 inner feature index
    tbin: jnp.ndarray          # [NI] i32 threshold bin
    default_left: jnp.ndarray  # [NI] bool
    nan_bin: jnp.ndarray       # [NI] i32 (-1 when feature has no NaN bin)
    zero_bin: jnp.ndarray      # [NI] i32 (-1 unless MissingType.ZERO)
    left: jnp.ndarray          # [NI] i32 (>=0 node, <0 ~leaf)
    right: jnp.ndarray         # [NI] i32
    is_cat: jnp.ndarray        # [NI] bool
    cat_mask: jnp.ndarray      # [NI, B] bool (all-false rows for non-cat)
    leaf_value: jnp.ndarray    # [NL] f32
    depth: int                 # host int: max hops needed


def build_device_tree(tree: Tree, bin_meta, B: int,
                      bundle=None) -> Optional[DeviceTree]:
    """Pack a host Tree into device arrays for binned traversal.
    ``bin_meta`` is the GBDT's (nan_bins, zero_bins, missing_types) per
    inner feature. Returns None for stump trees (constant output).

    ``bundle`` (io/efb.py BundleLayout): when the binned rows are EFB
    bundles, every node's decision becomes a boolean LUT over its bundle
    column's bins (computed host-side from the member/unmap maps — the
    same mechanism as categorical masks), and ``feat`` points at the
    bundle column."""
    ni = tree.num_internal
    if ni == 0:
        return None
    if bundle is not None:
        return _build_bundled_device_tree(tree, bin_meta, B, bundle)
    nan_bins, zero_bins, missing_types = bin_meta
    NI = _next_pow2(ni)
    NL = _next_pow2(tree.num_leaves)
    feat = np.zeros(NI, dtype=np.int32)
    feat[:ni] = tree.split_feature_inner[:ni]
    tbin = np.zeros(NI, dtype=np.int32)
    tbin[:ni] = tree.threshold_in_bin[:ni]
    dt = tree.decision_type[:ni]
    dl = np.zeros(NI, dtype=bool)
    dl[:ni] = (dt & kDefaultLeftMask) != 0
    f = tree.split_feature_inner[:ni]
    nb = np.full(NI, -1, dtype=np.int32)
    zb = np.full(NI, -1, dtype=np.int32)
    nb[:ni] = np.where(missing_types[f] == MissingType.NAN, nan_bins[f], -1)
    zb[:ni] = np.where(missing_types[f] == MissingType.ZERO,
                       zero_bins[f], -1)
    left = np.zeros(NI, dtype=np.int32)
    right = np.zeros(NI, dtype=np.int32)
    left[:ni] = tree.left_child[:ni]
    right[:ni] = tree.right_child[:ni]
    is_cat = np.zeros(NI, dtype=bool)
    is_cat[:ni] = (dt & kCategoricalMask) != 0
    cat_mask = np.zeros((NI, B), dtype=bool)
    for node, mask in tree.cat_bin_masks.items():
        if node < ni:
            m = np.asarray(mask, dtype=bool)[:B]
            cat_mask[node, :len(m)] = m
            is_cat[node] = True
    lv = np.zeros(NL, dtype=np.float32)
    lv[:tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
    depth = int(tree.leaf_depth[:tree.num_leaves].max())
    return DeviceTree(
        feat=jnp.asarray(feat), tbin=jnp.asarray(tbin),
        default_left=jnp.asarray(dl), nan_bin=jnp.asarray(nb),
        zero_bin=jnp.asarray(zb), left=jnp.asarray(left),
        right=jnp.asarray(right), is_cat=jnp.asarray(is_cat),
        cat_mask=jnp.asarray(cat_mask), leaf_value=jnp.asarray(lv),
        depth=depth)


def _build_bundled_device_tree(tree: Tree, bin_meta, B: int,
                               bundle) -> DeviceTree:
    """LUT-mode DeviceTree over EFB-bundled bins: per node, a bool[B]
    left/right table over the node's bundle column."""
    from ..io.binning import MissingType as MT
    nan_bins, zero_bins, missing_types = bin_meta
    ni = tree.num_internal
    NI = _next_pow2(ni)
    NL = _next_pow2(tree.num_leaves)
    feat = np.zeros(NI, dtype=np.int32)
    lut = np.zeros((NI, B), dtype=bool)
    dt_bits = tree.decision_type
    for node in range(ni):
        f = int(tree.split_feature_inner[node])
        g = int(bundle.group_of[f])
        feat[node] = g
        mb = bundle.member[g]
        um = bundle.unmap[g]
        zb = int(zero_bins[f])
        orig = np.where(mb == f, um, zb)[:B]
        if int(dt_bits[node]) & kCategoricalMask:
            mask = np.asarray(tree.cat_bin_masks[node], dtype=bool)
            gl = mask[np.minimum(orig, len(mask) - 1)]
        else:
            tb = int(tree.threshold_in_bin[node])
            dl = bool(int(dt_bits[node]) & kDefaultLeftMask)
            gl = orig <= tb
            if missing_types[f] == MT.NAN:
                gl = np.where(orig == nan_bins[f], dl, gl)
            elif missing_types[f] == MT.ZERO:
                gl = np.where(orig == zero_bins[f], dl, gl)
        lut[node, :len(gl)] = gl
    left = np.zeros(NI, dtype=np.int32)
    right = np.zeros(NI, dtype=np.int32)
    left[:ni] = tree.left_child[:ni]
    right[:ni] = tree.right_child[:ni]
    lv = np.zeros(NL, dtype=np.float32)
    lv[:tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
    depth = int(tree.leaf_depth[:tree.num_leaves].max())
    neg1 = np.full(NI, -1, dtype=np.int32)
    return DeviceTree(
        feat=jnp.asarray(feat), tbin=jnp.asarray(neg1),
        default_left=jnp.zeros(NI, dtype=bool),
        nan_bin=jnp.asarray(neg1), zero_bin=jnp.asarray(neg1),
        left=jnp.asarray(left), right=jnp.asarray(right),
        is_cat=jnp.ones(NI, dtype=bool), cat_mask=jnp.asarray(lut),
        leaf_value=jnp.asarray(lv), depth=depth)


@partial(jax.jit, static_argnames=("trips",))
def _traverse(bins, dt: DeviceTree, trips: int) -> jnp.ndarray:
    """Lockstep binned traversal: [n, F] uint bins → [n] i32 leaf ids."""
    n = bins.shape[0]

    def body(_, node):
        nd = jnp.maximum(node, 0)
        f = dt.feat[nd]
        b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0] \
            .astype(jnp.int32)
        gl = b <= dt.tbin[nd]
        gl = jnp.where(b == dt.nan_bin[nd], dt.default_left[nd], gl)
        gl = jnp.where(b == dt.zero_bin[nd], dt.default_left[nd], gl)
        gl = jnp.where(dt.is_cat[nd], dt.cat_mask[nd, b], gl)
        nxt = jnp.where(gl, dt.left[nd], dt.right[nd])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.fori_loop(0, trips, body,
                             jnp.zeros(n, dtype=jnp.int32))
    # rows still on an internal node after `trips` hops cannot happen when
    # trips >= tree depth; ~node maps leaf encodings back to indices
    return jnp.where(node < 0, ~node, 0).astype(jnp.int32)


def predict_leaf_on_device(bins_dev: jnp.ndarray,
                           dtree: DeviceTree) -> jnp.ndarray:
    """[n] leaf index of every binned row (device)."""
    return _traverse(bins_dev, dtree, _next_pow2(dtree.depth))


@jax.jit
def _gather_leaf_values(leaf_value, leaf):
    return leaf_value[leaf]


def tree_output_on_device(bins_dev: jnp.ndarray,
                          dtree: DeviceTree) -> jnp.ndarray:
    """[n] f32 per-row output of one tree over binned rows (device)."""
    leaf = predict_leaf_on_device(bins_dev, dtree)
    return _gather_leaf_values(dtree.leaf_value, leaf)
