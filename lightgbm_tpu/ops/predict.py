"""Device-side tree traversal over binned rows.

TPU-native replacement for the host Python node-walk the round-2 review
flagged (models/tree.py predict_by_bin): validation scoring runs per tree
per valid set per iteration, so it must be a device op, not a host loop.

Reference analogue: the CUDA build keeps valid scores on device and walks
trees with a kernel (src/boosting/cuda/cuda_score_updater.*,
src/io/cuda/cuda_tree.cu AddPredictionToScoreKernel). Here the walk is a
lockstep vectorized loop: every row advances one level per iteration of a
``lax.fori_loop`` whose trip count is the tree depth (padded to a power of
two so compiled variants are shared across trees of similar depth). Nodes
are flat arrays (gathers), leaves encoded as ``~leaf`` negatives exactly
like the host Tree / reference tree.h:25.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.binning import MissingType
from ..models.tree import Tree, kCategoricalMask, kDefaultLeftMask
from ..obs import compile as obs_compile
from ..utils import next_pow2 as _next_pow2


class DeviceTree(NamedTuple):
    """Flat node arrays of one tree, padded to a power-of-two node count
    (padding keeps the jitted traversal shared across trees)."""
    feat: jnp.ndarray          # [NI] i32 inner feature index
    tbin: jnp.ndarray          # [NI] i32 threshold bin
    default_left: jnp.ndarray  # [NI] bool
    nan_bin: jnp.ndarray       # [NI] i32 (-1 when feature has no NaN bin)
    zero_bin: jnp.ndarray      # [NI] i32 (-1 unless MissingType.ZERO)
    left: jnp.ndarray          # [NI] i32 (>=0 node, <0 ~leaf)
    right: jnp.ndarray         # [NI] i32
    is_cat: jnp.ndarray        # [NI] bool
    cat_mask: jnp.ndarray      # [NI, B] bool (all-false rows for non-cat)
    leaf_value: jnp.ndarray    # [NL] f32
    depth: int                 # host int: max hops needed


def build_device_tree(tree: Tree, bin_meta, B: int,
                      bundle=None) -> Optional[DeviceTree]:
    """Pack a host Tree into device arrays for binned traversal.
    ``bin_meta`` is the GBDT's (nan_bins, zero_bins, missing_types) per
    inner feature. Returns None for stump trees (constant output).

    ``bundle`` (io/efb.py BundleLayout): when the binned rows are EFB
    bundles, every node's decision becomes a boolean LUT over its bundle
    column's bins (computed host-side from the member/unmap maps — the
    same mechanism as categorical masks), and ``feat`` points at the
    bundle column."""
    ni = tree.num_internal
    if ni == 0:
        return None
    if bundle is not None:
        return _build_bundled_device_tree(tree, bin_meta, B, bundle)
    nan_bins, zero_bins, missing_types = bin_meta
    NI = _next_pow2(ni)
    NL = _next_pow2(tree.num_leaves)
    feat = np.zeros(NI, dtype=np.int32)
    feat[:ni] = tree.split_feature_inner[:ni]
    tbin = np.zeros(NI, dtype=np.int32)
    tbin[:ni] = tree.threshold_in_bin[:ni]
    dt = tree.decision_type[:ni]
    dl = np.zeros(NI, dtype=bool)
    dl[:ni] = (dt & kDefaultLeftMask) != 0
    f = tree.split_feature_inner[:ni]
    nb = np.full(NI, -1, dtype=np.int32)
    zb = np.full(NI, -1, dtype=np.int32)
    nb[:ni] = np.where(missing_types[f] == MissingType.NAN, nan_bins[f], -1)
    zb[:ni] = np.where(missing_types[f] == MissingType.ZERO,
                       zero_bins[f], -1)
    left = np.zeros(NI, dtype=np.int32)
    right = np.zeros(NI, dtype=np.int32)
    left[:ni] = tree.left_child[:ni]
    right[:ni] = tree.right_child[:ni]
    is_cat = np.zeros(NI, dtype=bool)
    is_cat[:ni] = (dt & kCategoricalMask) != 0
    cat_mask = np.zeros((NI, B), dtype=bool)
    for node, mask in tree.cat_bin_masks.items():
        if node < ni:
            m = np.asarray(mask, dtype=bool)[:B]
            cat_mask[node, :len(m)] = m
            is_cat[node] = True
    lv = np.zeros(NL, dtype=np.float32)
    lv[:tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
    depth = int(tree.leaf_depth[:tree.num_leaves].max())
    return DeviceTree(
        feat=jnp.asarray(feat), tbin=jnp.asarray(tbin),
        default_left=jnp.asarray(dl), nan_bin=jnp.asarray(nb),
        zero_bin=jnp.asarray(zb), left=jnp.asarray(left),
        right=jnp.asarray(right), is_cat=jnp.asarray(is_cat),
        cat_mask=jnp.asarray(cat_mask), leaf_value=jnp.asarray(lv),
        depth=depth)


def _build_bundled_device_tree(tree: Tree, bin_meta, B: int,
                               bundle) -> DeviceTree:
    """LUT-mode DeviceTree over EFB-bundled bins: per node, a bool[B]
    left/right table over the node's bundle column."""
    from ..io.binning import MissingType as MT
    nan_bins, zero_bins, missing_types = bin_meta
    ni = tree.num_internal
    NI = _next_pow2(ni)
    NL = _next_pow2(tree.num_leaves)
    feat = np.zeros(NI, dtype=np.int32)
    lut = np.zeros((NI, B), dtype=bool)
    dt_bits = tree.decision_type
    for node in range(ni):
        f = int(tree.split_feature_inner[node])
        g = int(bundle.group_of[f])
        feat[node] = g
        mb = bundle.member[g]
        um = bundle.unmap[g]
        zb = int(zero_bins[f])
        orig = np.where(mb == f, um, zb)[:B]
        if int(dt_bits[node]) & kCategoricalMask:
            mask = np.asarray(tree.cat_bin_masks[node], dtype=bool)
            gl = mask[np.minimum(orig, len(mask) - 1)]
        else:
            tb = int(tree.threshold_in_bin[node])
            dl = bool(int(dt_bits[node]) & kDefaultLeftMask)
            gl = orig <= tb
            if missing_types[f] == MT.NAN:
                gl = np.where(orig == nan_bins[f], dl, gl)
            elif missing_types[f] == MT.ZERO:
                gl = np.where(orig == zero_bins[f], dl, gl)
        lut[node, :len(gl)] = gl
    left = np.zeros(NI, dtype=np.int32)
    right = np.zeros(NI, dtype=np.int32)
    left[:ni] = tree.left_child[:ni]
    right[:ni] = tree.right_child[:ni]
    lv = np.zeros(NL, dtype=np.float32)
    lv[:tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
    depth = int(tree.leaf_depth[:tree.num_leaves].max())
    neg1 = np.full(NI, -1, dtype=np.int32)
    return DeviceTree(
        feat=jnp.asarray(feat), tbin=jnp.asarray(neg1),
        default_left=jnp.zeros(NI, dtype=bool),
        nan_bin=jnp.asarray(neg1), zero_bin=jnp.asarray(neg1),
        left=jnp.asarray(left), right=jnp.asarray(right),
        is_cat=jnp.ones(NI, dtype=bool), cat_mask=jnp.asarray(lut),
        leaf_value=jnp.asarray(lv), depth=depth)


def _traverse_body(bins, dt: DeviceTree, trips: int) -> jnp.ndarray:
    """Lockstep binned traversal: [n, F] uint bins → [n] i32 leaf ids."""
    n = bins.shape[0]

    def body(_, node):
        nd = jnp.maximum(node, 0)
        f = dt.feat[nd]
        b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0] \
            .astype(jnp.int32)
        gl = b <= dt.tbin[nd]
        gl = jnp.where(b == dt.nan_bin[nd], dt.default_left[nd], gl)
        gl = jnp.where(b == dt.zero_bin[nd], dt.default_left[nd], gl)
        gl = jnp.where(dt.is_cat[nd], dt.cat_mask[nd, b], gl)
        nxt = jnp.where(gl, dt.left[nd], dt.right[nd])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.fori_loop(0, trips, body,
                             jnp.zeros(n, dtype=jnp.int32))
    # rows still on an internal node after `trips` hops cannot happen when
    # trips >= tree depth; ~node maps leaf encodings back to indices
    return jnp.where(node < 0, ~node, 0).astype(jnp.int32)


_traverse = obs_compile.instrument_jit(
    "predict.traverse", _traverse_body, static_argnames=("trips",))


def predict_leaf_on_device(bins_dev: jnp.ndarray,
                           dtree: DeviceTree) -> jnp.ndarray:
    """[n] leaf index of every binned row (device)."""
    return _traverse(bins_dev, dtree, _next_pow2(dtree.depth))


# ---------------------------------------------------------------------------
# Stacked-forest kernels (serving): the whole forest in one dispatch.
#
# Where the training-side DeviceTree walks ONE tree over dataset-binned
# rows, serving packs ALL T trees' flat node arrays into single [T, NI]
# arrays and vmaps the same lockstep walk over the tree axis — the
# XLA-shaped analogue of batching the forest, not the tree (the lever
# XGBoost-GPU and the reference's CUDA scorer pull; see docs/SERVING.md).
# Rows arrive as RAW float features and are quantized on device against
# the model's own threshold set (serve/forest.py builds the tables), so
# the uint gather matrix never leaves HBM between quantize and walk.
# ---------------------------------------------------------------------------

# sentinel bin ids assigned by the quantizer; they can never collide with
# a real bin (>= 0) or a node threshold index (>= -1)
kNanBin = -2    # NaN value on a MissingType.NAN feature
kZeroBin = -4   # |v| <= kZeroThreshold on a MissingType.ZERO feature


class StackedNodes(NamedTuple):
    """All T trees' node arrays, padded to common [T, NI] / [T, NL]
    shapes (serving analogue of DeviceTree; serve/forest.py packs it).

    Two encodings share this layout (serve/forest.py builds both):

    - **compare nodes**: numeric decisions are ``bin <= tbin`` integer
      compares, categorical ones LUT rows (``is_cat``/``cat_slot``);
    - **LUT nodes**: EVERY node is a boolean LUT row over its feature's
      bin space (``is_cat`` all-True, ``tbin`` all ``-1``) — one gather
      decides the node, which cuts the walk's inner-loop op count on
      wide sparse / EFB-bundled models (the "LUT node" encoding from
      the sparse-oblique-forest direction; docs/SERVING.md)."""
    feat: jnp.ndarray          # [T, NI] i32 COMPACT (used-feature) index
    tbin: jnp.ndarray          # [T, NI] i32 threshold rank (-1: none left)
    default_left: jnp.ndarray  # [T, NI] bool
    left: jnp.ndarray          # [T, NI] i32 (>=0 node, <0 ~leaf)
    right: jnp.ndarray         # [T, NI] i32
    is_cat: jnp.ndarray        # [T, NI] bool
    cat_slot: jnp.ndarray      # [T, NI] i32 row of the shared LUT
    leaf_value: jnp.ndarray    # [T, NL] f32


class QuantizerTables(NamedTuple):
    """Per-USED-feature raw-value→bin tables derived from the model's
    own split thresholds (serve/forest.py builds them; exact in f32).
    ``used`` maps the compacted table rows back to raw row columns, so
    the bins matrix the walk gathers from is [n, U] with U = #features
    the forest actually splits on — the gather-width cut for wide
    sparse models."""
    used: jnp.ndarray          # [U] i32 raw column of each table row
    thresholds: jnp.ndarray    # [U, M] f32 round-down thresholds, +inf pad
    is_cat: jnp.ndarray        # [U] bool
    nan_feat: jnp.ndarray      # [U] bool (MissingType.NAN features)
    zero_feat: jnp.ndarray     # [U] bool (MissingType.ZERO features)
    vmax: jnp.ndarray          # [] i32 max categorical value in the LUT
    zero_eps: jnp.ndarray      # [] f32 round-down f32 of kZeroThreshold


class QuantizerTablesDD(NamedTuple):
    """Double-double quantizer tables for f64 request rows: each f64
    threshold t is the exact pair (round-down-f32(t), integer residual
    rank) — see ``serve/forest.py encode_dd`` for the row-side encoding
    and the exactness argument."""
    used: jnp.ndarray          # [U] i32 raw column of each table row
    thr_hi: jnp.ndarray        # [U, M64] f32 round-down f32(t), +inf pad
    thr_lo: jnp.ndarray        # [U, M64] i32 exact residual rank, 0 pad
    is_cat: jnp.ndarray        # [U] bool
    nan_feat: jnp.ndarray      # [U] bool
    zero_feat: jnp.ndarray     # [U] bool
    vmax: jnp.ndarray          # [] i32


class LinearLeaves(NamedTuple):
    """Linear-leaf (``linear_tree``) models packed into stacked arrays:
    per leaf a constant + up-to-C coefficients over RAW feature columns
    (the leaf's root-path features). ``valid`` masks the padding lanes
    so a NaN in an unused pad column can never poison the NaN-fallback
    check (host semantics: any NaN among the leaf's fitted features →
    constant ``leaf_value`` fallback, models/linear.py)."""
    const: jnp.ndarray         # [T, NL] f32
    coeff: jnp.ndarray         # [T, NL, C] f32 (0 pad)
    feat: jnp.ndarray          # [T, NL, C] i32 RAW feature column (0 pad)
    valid: jnp.ndarray         # [T, NL, C] bool
    has: jnp.ndarray           # [T, NL] bool (a linear fit exists)


def _quantize_rows_impl(X: jnp.ndarray, qt: QuantizerTables) -> jnp.ndarray:
    """[n, F] raw f32 rows → [n, U] i32 model-space bins over the used
    feature columns.

    Numeric bin = #{thresholds on f < v} — so ``bin <= rank(t)`` decides
    exactly like the host's ``v <= t`` (thresholds are stored as the
    largest f32 <= t, which preserves every comparison against
    f32-representable values). NaN/zero missing semantics are resolved
    here once per row, into sentinel bins the walk maps to default_left.
    """
    X = jnp.take(X, qt.used, axis=1)
    isnan = jnp.isnan(X)
    # NaN behaves as 0.0 except on MissingType.NAN features (tree.py
    # _decide: v = where(isnan & missing != NAN, 0, fval))
    Xn = jnp.where(isnan & ~qt.nan_feat[None, :], jnp.float32(0.0), X)
    b = jax.vmap(lambda t, col: jnp.searchsorted(t, col, side="left"),
                 in_axes=(0, 1), out_axes=1)(qt.thresholds, Xn)
    b = b.astype(jnp.int32)
    b = jnp.where(qt.nan_feat[None, :] & isnan, jnp.int32(kNanBin), b)
    b = jnp.where(qt.zero_feat[None, :] & (jnp.abs(Xn) <= qt.zero_eps),
                  jnp.int32(kZeroBin), b)
    # categorical: the "bin" is the category value itself, clamped into
    # the shared LUT's row (out-of-range / negative / NaN → vmax+1, an
    # always-False column == the host's FindInBitset miss → go right)
    vmax = qt.vmax.astype(jnp.float32)
    iv = jnp.clip(jnp.where(isnan, jnp.float32(-1.0), X),
                  -1.0, vmax + 1.0).astype(jnp.int32)
    cb = jnp.where((iv >= 0) & (iv <= qt.vmax), iv, qt.vmax + 1)
    return jnp.where(qt.is_cat[None, :], cb, b)


def _quantize_rows_dd_impl(Xhi: jnp.ndarray, Xlo: jnp.ndarray,
                           qt: QuantizerTablesDD) -> jnp.ndarray:
    """[n, F] double-double rows → [n, U] i32 bins in the model's f64
    threshold grid. The host encoder (serve/forest.py ``encode_dd``)
    already resolved NaN-as-zero and zero-as-missing semantics, so here
    a bin is a lexicographic pair count:

        bin = #{j : (thr_hi_j, thr_lo_j) < (hi, lo)}

    which is EXACTLY #{t_j < v} because the pair encoding is monotone
    and exact for every f64 whose f32 round-down is a normal float.
    The encoder preserves NaN in ``hi`` everywhere (so linear-leaf
    NaN-fallback masks still see it); NaN-as-zero on non-NaN-missing
    numeric features substitutes the exact (0, 0) pair here."""
    Xhi = jnp.take(Xhi, qt.used, axis=1)
    Xlo = jnp.take(Xlo, qt.used, axis=1)
    isnan = jnp.isnan(Xhi)
    as_zero = isnan & ~qt.nan_feat[None, :]
    hi = jnp.where(as_zero, jnp.float32(0.0), Xhi)[:, :, None]
    lo = jnp.where(as_zero, jnp.int32(0), Xlo)[:, :, None]
    thi = qt.thr_hi[None, :, :]
    tlo = qt.thr_lo[None, :, :]
    less = (thi < hi) | ((thi == hi) & (tlo < lo))
    b = jnp.sum(less, axis=2).astype(jnp.int32)
    b = jnp.where(qt.nan_feat[None, :] & isnan, jnp.int32(kNanBin), b)
    # zero-as-missing rides the encoder's lo == -1 sentinel (the f64
    # |v| <= kZeroThreshold test is exact on host, not re-derivable
    # from the pair)
    b = jnp.where(qt.zero_feat[None, :] & (Xlo == -1),
                  jnp.int32(kZeroBin), b)
    vmax = qt.vmax.astype(jnp.float32)
    iv = jnp.clip(jnp.where(isnan, jnp.float32(-1.0), Xhi),
                  -1.0, vmax + 1.0).astype(jnp.int32)
    cb = jnp.where((iv >= 0) & (iv <= qt.vmax), iv, qt.vmax + 1)
    return jnp.where(qt.is_cat[None, :], cb, b)


def _walk_stacked(bins: jnp.ndarray, nodes: StackedNodes,
                  cat_lut: jnp.ndarray, trips: int) -> jnp.ndarray:
    """[n, U] bins → [T, n] leaf ids: the DeviceTree lockstep walk,
    vmapped over the stacked tree axis. The LUT always reserves its two
    last columns for the NaN/zero sentinel bins, so LUT-encoded nodes
    resolve default_left with the same single gather that decides the
    split (compare-encoded categorical nodes never receive sentinels —
    the pad columns are dead for them)."""
    n = bins.shape[0]
    lut_w = cat_lut.shape[1]

    def walk_one(feat, tbin, dl, left, right, is_cat, cat_slot):
        def body(_, node):
            nd = jnp.maximum(node, 0)
            f = feat[nd]
            b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0]
            gl = b <= tbin[nd]
            gl = jnp.where(b == kNanBin, dl[nd], gl)
            gl = jnp.where(b == kZeroBin, dl[nd], gl)
            bi = jnp.where(b == kNanBin, lut_w - 2,
                           jnp.where(b == kZeroBin, lut_w - 1,
                                     jnp.maximum(b, 0)))
            lu = cat_lut[cat_slot[nd], bi]
            gl = jnp.where(is_cat[nd], lu, gl)
            nxt = jnp.where(gl, left[nd], right[nd])
            return jnp.where(node >= 0, nxt, node)

        node = jax.lax.fori_loop(0, trips, body,
                                 jnp.zeros(n, dtype=jnp.int32))
        return jnp.where(node < 0, ~node, 0).astype(jnp.int32)

    return jax.vmap(walk_one)(nodes.feat, nodes.tbin, nodes.default_left,
                              nodes.left, nodes.right, nodes.is_cat,
                              nodes.cat_slot)


def _linear_leaf_values(X, leaves, vals, lin: LinearLeaves):
    """Override stacked leaf values with each leaf's linear model where
    one exists and none of its fitted features is NaN (f32 device math —
    the throughput path; the bit-exact host path accumulates linear
    values in f64 from the same device leaf ids)."""
    def lin_one(leaf_t, val_t, const_t, coeff_t, feat_t, valid_t, has_t):
        f = feat_t[leaf_t]                                   # [n, C]
        xv = jnp.take_along_axis(X, f, axis=1)               # [n, C]
        v = valid_t[leaf_t]
        bad = jnp.any(jnp.isnan(xv) & v, axis=1)
        s = const_t[leaf_t] + jnp.sum(
            jnp.where(v, coeff_t[leaf_t] * xv, jnp.float32(0.0)), axis=1)
        return jnp.where(has_t[leaf_t] & ~bad, s, val_t)

    return jax.vmap(lin_one)(leaves, vals, lin.const, lin.coeff,
                             lin.feat, lin.valid, lin.has)


def _raw_from_leaves(X, leaves, nodes, K, lin):
    vals = jnp.take_along_axis(nodes.leaf_value, leaves, axis=1)  # [T, n]
    if lin is not None:
        vals = _linear_leaf_values(X, leaves, vals, lin)
    # models are iteration-major: tree i contributes to class i % K.
    # Per-class Kahan-compensated f32 sum over the iteration axis: the
    # compensation term recovers the low-order bits a plain f32 sum
    # drops, tightening deep forests from ~1e-5 rel error at 500 trees
    # to ~1 ulp of the correctly rounded result (ROADMAP open item).
    # XLA preserves FP semantics (no reassociation), so (t - s) - y is
    # not folded away.
    per_iter = vals.reshape(-1, K, vals.shape[1])                 # [I, K, n]

    def kahan_step(carry, v):
        s, c = carry
        y = v - c
        t = s + y
        c = (t - s) - y
        return (t, c), None

    zero = jnp.zeros(per_iter.shape[1:], dtype=vals.dtype)
    (total, _), _ = jax.lax.scan(kahan_step, (zero, zero), per_iter)
    return total.T                                                # [n, K]


def _stacked_leaves_body(X, qt, nodes, cat_lut, trips):
    return _walk_stacked(_quantize_rows_impl(X, qt), nodes, cat_lut, trips)


def _stacked_raw_body(X, qt, nodes, cat_lut, trips, K, lin=None):
    leaves = _stacked_leaves_body(X, qt, nodes, cat_lut, trips)
    return _raw_from_leaves(X, leaves, nodes, K, lin)


def _stacked_leaves_dd_body(Xhi, Xlo, qt, nodes, cat_lut, trips):
    return _walk_stacked(_quantize_rows_dd_impl(Xhi, Xlo, qt), nodes,
                         cat_lut, trips)


def _stacked_raw_dd_body(Xhi, Xlo, qt, nodes, cat_lut, trips, K,
                         lin=None):
    leaves = _stacked_leaves_dd_body(Xhi, Xlo, qt, nodes, cat_lut, trips)
    return _raw_from_leaves(Xhi, leaves, nodes, K, lin)


def _make_stacked_jits():
    """Jitted quantize+walk entry points, trace-tracked through
    obs/compile.py (one compile per (row-bucket, forest-shape); the
    serve cache pads rows so a second dispatch at the same bucket hits
    the jit cache with zero retraces — and replicas placing the SAME
    forest shapes on N devices share these traces too, so a fleet
    traces once per shape bucket, not once per device)."""
    leaves = obs_compile.instrument_jit(
        "serve.stacked_leaves", _stacked_leaves_body,
        static_argnames=("trips",))
    raw = obs_compile.instrument_jit(
        "serve.stacked_raw", _stacked_raw_body,
        static_argnames=("trips", "K"))
    leaves_dd = obs_compile.instrument_jit(
        "serve.stacked_leaves_dd", _stacked_leaves_dd_body,
        static_argnames=("trips",))
    raw_dd = obs_compile.instrument_jit(
        "serve.stacked_raw_dd", _stacked_raw_dd_body,
        static_argnames=("trips", "K"))
    return leaves, raw, leaves_dd, raw_dd


(stacked_forest_leaves, stacked_forest_raw,
 stacked_forest_leaves_dd, stacked_forest_raw_dd) = _make_stacked_jits()


def _gather_leaf_values_body(leaf_value, leaf):
    return leaf_value[leaf]


_gather_leaf_values = obs_compile.instrument_jit(
    "predict.gather_leaf", _gather_leaf_values_body)


def tree_output_on_device(bins_dev: jnp.ndarray,
                          dtree: DeviceTree) -> jnp.ndarray:
    """[n] f32 per-row output of one tree over binned rows (device)."""
    leaf = predict_leaf_on_device(bins_dev, dtree)
    return _gather_leaf_values(dtree.leaf_value, leaf)
