"""Gradient discretization for quantized histogram training.

TPU-native analogue of the reference's quantized training
(``use_quantized_grad``, src/treelearner/gradient_discretizer.cpp:
per-iteration max-|grad|/max-hess scales, stochastic rounding to a few
bits, integer histogram accumulation). The motivation is bandwidth, not
FLOPs: histogram construction is bandwidth-bound (arXiv 1706.08359,
1806.11248), and an int8 (grad, hess) row vector moves 4x fewer bytes
than f32 through every histogram pass, every sharded-mesh psum, and —
on TPU — feeds the MXU's int8 matmul path in the one-hot contraction.

Scheme (per boosting iteration / per tree):

- ``g_scale = max|g| / qmax``, ``h_scale = max|h| / qmax`` over in-bag
  rows (the reference's per-iteration scale, gradient_discretizer.cpp).
- stochastic rounding ``q = floor(x / scale + u)``, ``u ~ U[0, 1)`` —
  unbiased (``E[q * scale] = x``), seeded per tree so serial and mesh
  learners draw identical integers for identical rows (the draw happens
  on the UNPADDED [N] row vector: learners pad to different row
  multiples, and a padded-shape draw would make the quantized rows
  depend on the pad — the make_rand_bins padding-invariance lesson).
- histogram accumulation in int32 (int64 under ``jax_enable_x64`` for
  16-bit rows), which makes per-bin sums order-invariant and sibling
  subtraction BIT-EXACT — a correctness win over the f32 path, whose
  subtraction drifts by accumulation-order rounding.
- split gain dequantizes once per scan (ops/split.py): the integer bin
  sums convert to f32 and multiply by the scale a single time, so a
  deep leaf's tiny sums carry exactly one rounding instead of one per
  accumulated row.

Overflow discipline: a leaf's channel sum is bounded by ``qmax * rows``.
``effective_quant_max`` caps qmax so that bound stays inside the
accumulator dtype — with int32 accumulation a 16-bit request degrades
toward 8 bits as the row count grows past ~64k, and even the 8-bit
range shrinks below 127 past ~16.9M rows (a perf_warning event records
any cap); enabling ``jax_enable_x64`` lifts 16-bit accumulation to
int64 and restores the full range at any scale.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..obs import compile as obs_compile

# avoid a zero divisor when an iteration's gradients are identically 0
kTinyScale = 1e-30

_INT32_MAX = 2 ** 31 - 1


def quant_dtype(bits: int):
    """Row-vector dtype for a quant_grad_bits setting."""
    return jnp.int8 if bits <= 8 else jnp.int16


def acc_dtype(gh_dtype):
    """Histogram accumulator dtype for integer gh rows: int32, lifted
    to int64 for 16-bit rows when x64 is available (the int32 bound
    qmax*rows is handled by effective_quant_max otherwise)."""
    if jnp.dtype(gh_dtype).itemsize > 1 and jax.config.jax_enable_x64:
        return jnp.int64
    return jnp.int32


def effective_quant_max(bits: int, max_rows: int) -> int:
    """Largest per-row integer magnitude such that a sum over
    ``max_rows`` rows cannot overflow the accumulator. Full range
    (2^(bits-1) - 1) when the accumulator is int64 (16-bit rows under
    x64); under int32 accumulation the cap applies to BOTH widths —
    8-bit keeps its full 127 up to 2^31/127 ≈ 16.9M rows, beyond which
    the effective range shrinks too (a one-sided gradient channel can
    genuinely sum to qmax*rows, e.g. the root histogram of a skewed
    binary objective — silent wraparound is worse than coarser
    quantization, and quant_warn_capped records the cap)."""
    qmax = (1 << (bits - 1)) - 1
    if jnp.dtype(quant_dtype(bits)).itemsize > 1 \
            and jax.config.jax_enable_x64:
        return qmax
    cap = _INT32_MAX // max(int(max_rows), 1)
    return max(min(qmax, cap), 1)


def quant_warn_capped(bits: int, qmax: int, max_rows: int) -> None:
    """One warning + assertable event when the requested bit width was
    capped by the int32 accumulator bound (ops/histogram._warn_once
    carries the perf_warning event plumbing)."""
    full = (1 << (bits - 1)) - 1
    if qmax < full:
        from .histogram import _warn_once
        _warn_once("quant_grad_bits=%d capped to |q|<=%d for %d rows "
                   "(int32 histogram accumulation%s)"
                   % (bits, qmax, max_rows,
                      "; enable jax_enable_x64 for int64 accumulators "
                      "and the full range" if bits > 8 else ""),
                   component="ops.quantize")


def _quantize_gh(grad, hess, ind, key, qmax: int, dtype) -> tuple:
    """Discretize per-row (grad, hess) to signed integers.

    Parameters
    ----------
    grad, hess : f32[N] (or any float dtype)
    ind : f32[N] in-bag indicator (0/1; GOSS amplification is already
        folded into grad/hess by the sample strategy)
    key : PRNG key for the stochastic rounding draw
    qmax : STATIC target magnitude (effective_quant_max)
    dtype : STATIC row dtype (quant_dtype)

    Returns (gh int[N, 4] = (q_grad, q_hess, in-bag, 1),
             qscale f32[2] = (g_scale, h_scale)).
    """
    g = grad * ind
    h = hess * ind
    qmaxf = jnp.float32(qmax)
    gs = jnp.maximum(jnp.max(jnp.abs(g)), kTinyScale) / qmaxf
    hs = jnp.maximum(jnp.max(jnp.abs(h)), kTinyScale) / qmaxf
    u = jax.random.uniform(key, (g.shape[0], 2))
    qg = jnp.clip(jnp.floor(g / gs + u[:, 0]), -qmaxf, qmaxf)
    qh = jnp.clip(jnp.floor(h / hs + u[:, 1]), -qmaxf, qmaxf)
    gh = jnp.stack([qg, qh, ind,
                    jnp.ones_like(ind)], axis=1).astype(dtype)
    return gh, jnp.stack([gs, hs]).astype(jnp.float32)


quantize_gh = obs_compile.instrument_jit(
    "ops.quantize_gh", _quantize_gh, static_argnums=(4, 5))


def _tree_key(base_key, ctr):
    """Advance the device-side tree counter and derive the tree's
    stochastic-rounding key: ``fold_in(base, ctr + 1)``. The counter
    sequence (1, 2, ...) reproduces the host tree numbering the key
    derivation used before, bit-exactly — but the counter lives on
    device, so the steady-state training loop performs ZERO per-tree
    seed transfers (each new tree number used to be a fresh
    ``dev_u32`` device_put). The batched scan threads the same
    fold-in through its carry (parallel/data_parallel.py)."""
    nxt = ctr + jnp.uint32(1)
    return jax.random.fold_in(base_key, nxt), nxt


tree_key = obs_compile.instrument_jit("ops.quantize_tree_key", _tree_key)


def sum_gh(gh: jnp.ndarray) -> jnp.ndarray:
    """Channel sums with the overflow-safe accumulator: integer gh sums
    in acc_dtype (exact), float gh keeps its dtype (the existing f32
    behavior)."""
    if jnp.issubdtype(gh.dtype, jnp.integer):
        return jnp.sum(gh, axis=0, dtype=acc_dtype(gh.dtype))
    return jnp.sum(gh, axis=0)


def scale4(qscale) -> jnp.ndarray:
    """[4] channel dequantization vector: (g_scale, h_scale, 1, 1) —
    the count channels are already exact integers."""
    return jnp.concatenate(
        [jnp.asarray(qscale, dtype=jnp.float32),
         jnp.ones(2, dtype=jnp.float32)])


def dequantize_sums(sums: jnp.ndarray, qscale) -> jnp.ndarray:
    """[.., 4] integer channel sums → f32, one rounding per entry."""
    if not jnp.issubdtype(sums.dtype, jnp.integer):
        return sums
    return sums.astype(jnp.float32) * scale4(qscale)


def dequantize_hist(hist: jnp.ndarray, qscale) -> jnp.ndarray:
    """[.., 4] histogram → f32 for a split scan: integer (quantized)
    histograms scale by (g_scale, h_scale, 1, 1) — the single
    per-scan rounding — float histograms pass through untouched. The
    ones fallback for a missing scale exists only for trace-shaped
    callers in exact mode; quantized learners always pass their
    current ``_qscale``.

    The barrier pins the dequantized values: without it XLA is free to
    contract the scale multiply into the split scan's cumsum chains
    (an FMA), and WHETHER it does depends on the surrounding program —
    the same scan then returns different last-ulp gains inside the
    frontier-batched grower than inside the one-split finish,
    breaking the learners' bit-parity contract. Materializing the
    product makes every compile see the same f32 inputs."""
    if not jnp.issubdtype(hist.dtype, jnp.integer):
        return hist
    sv = (scale4(qscale) if qscale is not None
          else jnp.ones(4, dtype=jnp.float32))
    return jax.lax.optimization_barrier(hist.astype(jnp.float32) * sv)
