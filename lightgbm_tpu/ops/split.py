"""Best-split search over (grad, hess, count) histograms — the TPU analogue of
the reference's per-feature threshold scan.

Reference semantics reproduced (src/treelearner/feature_histogram.hpp:85
``FindBestThreshold`` / ``FindBestThresholdSequentially``; closed forms at
:477+ ``CalculateSplittedLeafOutput`` / ``GetSplitGains``; CUDA analogue
src/treelearner/cuda/cuda_best_split_finder.cu:603):

- leaf output  = -ThresholdL1(sum_grad, l1) / (sum_hess + l2), clipped to
  +-max_delta_step when positive
- leaf gain    = -(2*ThresholdL1(g,l1)*out + (h+l2)*out^2)  (equals
  ThresholdL1(g)^2/(h+l2) when the output is unclipped)
- a split is valid iff both children have >= min_data_in_leaf rows and
  >= min_sum_hessian, and split gain exceeds parent gain + min_gain_to_split
- missing handling: features with MissingType.NAN hold NaN rows in their last
  bin; the scan evaluates both "NaN goes right" (natural — the NaN bin is
  never <= threshold) and "NaN goes left" placements and records
  ``default_left``. MissingType.ZERO rows sit in the zero bin and follow the
  natural bin comparison, so default_left = (zero_bin <= threshold).

Instead of the reference's sequential per-feature loop (or the CUDA warp
prefix-sum scan), everything here is one vectorized pass: cumulative sums over
the bin axis give left-side stats for every (feature, threshold) at once, a
masked argmax picks the winner. This maps to a handful of XLA reductions, no
data-dependent control flow.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..io.binning import MissingType

_NEG_INF = -jnp.inf


class SplitParams(NamedTuple):
    """Scalar hyper-parameters of the split search (all traced, so one
    compiled kernel serves any setting). Mirror of the Config fields used by
    the reference's FeatureHistogram (config.h:291-406; categorical knobs
    :452-472)."""
    lambda_l1: jnp.ndarray
    lambda_l2: jnp.ndarray
    min_data_in_leaf: jnp.ndarray
    min_sum_hessian_in_leaf: jnp.ndarray
    min_gain_to_split: jnp.ndarray
    max_delta_step: jnp.ndarray
    cat_l2: jnp.ndarray
    cat_smooth: jnp.ndarray
    min_data_per_group: jnp.ndarray
    max_cat_threshold: jnp.ndarray
    path_smooth: jnp.ndarray = 0.0
    # CEGB scalars (cost_effective_gradient_boosting.hpp:80-87)
    cegb_tradeoff: jnp.ndarray = 1.0
    cegb_penalty_split: jnp.ndarray = 0.0
    # monotone split gain penalty (config.h:503)
    monotone_penalty: jnp.ndarray = 0.0

    @classmethod
    def from_config(cls, config) -> "SplitParams":
        return cls(
            lambda_l1=jnp.float32(config.lambda_l1),
            lambda_l2=jnp.float32(config.lambda_l2),
            min_data_in_leaf=jnp.float32(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=jnp.float32(config.min_sum_hessian_in_leaf),
            min_gain_to_split=jnp.float32(config.min_gain_to_split),
            max_delta_step=jnp.float32(config.max_delta_step),
            cat_l2=jnp.float32(config.cat_l2),
            cat_smooth=jnp.float32(config.cat_smooth),
            min_data_per_group=jnp.float32(config.min_data_per_group),
            max_cat_threshold=jnp.int32(config.max_cat_threshold),
            path_smooth=jnp.float32(config.path_smooth),
            cegb_tradeoff=jnp.float32(config.cegb_tradeoff),
            cegb_penalty_split=jnp.float32(config.cegb_penalty_split),
            monotone_penalty=jnp.float32(config.monotone_penalty),
        )


class FeatureMeta(NamedTuple):
    """Per-feature static metadata, device-resident (int32 [F] each).
    Derived from the BinMappers at dataset finalization."""
    num_bin: jnp.ndarray        # bins actually used by feature f
    missing_type: jnp.ndarray   # MissingType value
    zero_bin: jnp.ndarray       # bin holding value 0.0 (default_bin)
    is_categorical: jnp.ndarray  # bool[F]
    use_onehot: jnp.ndarray     # bool[F]: cat feature with few categories
    monotone: jnp.ndarray       # i8[F]: -1/0/+1 monotone constraint

    @classmethod
    def from_dataset(cls, dataset, max_cat_to_onehot: int = 4
                     ) -> "FeatureMeta":
        import numpy as np
        from ..io.binning import BinType
        is_cat = np.asarray(
            [m.bin_type == BinType.CATEGORICAL
             for m in dataset.bin_mappers], dtype=bool)
        num_bin = np.asarray(dataset.num_bin_per_feature, dtype=np.int32)
        mc = dataset.monotone_constraints
        monotone = (np.zeros(len(num_bin), dtype=np.int8) if mc is None
                    else np.asarray(mc, dtype=np.int8))
        return cls(
            num_bin=jnp.asarray(num_bin),
            missing_type=jnp.asarray(
                np.asarray([m.missing_type for m in dataset.bin_mappers],
                           dtype=np.int32)),
            zero_bin=jnp.asarray(
                np.asarray([m.default_bin for m in dataset.bin_mappers],
                           dtype=np.int32)),
            is_categorical=jnp.asarray(is_cat),
            use_onehot=jnp.asarray(
                is_cat & (num_bin <= max_cat_to_onehot)),
            monotone=jnp.asarray(monotone),
        )


def pad_feature_meta(meta: "FeatureMeta", pad: int) -> "FeatureMeta":
    """Append ``pad`` trivial features (num_bin 1 → never a valid split
    candidate). Used to pad the feature axis to a canonical width so
    compiled step variants are shared across datasets."""
    if pad <= 0:
        return meta

    def padv(a, fill):
        return jnp.concatenate(
            [a, jnp.full((pad,), fill, dtype=a.dtype)])

    return FeatureMeta(
        num_bin=padv(meta.num_bin, 1),
        missing_type=padv(meta.missing_type, 0),
        zero_bin=padv(meta.zero_bin, 0),
        is_categorical=padv(meta.is_categorical, False),
        use_onehot=padv(meta.use_onehot, False),
        monotone=padv(meta.monotone, 0),
    )


class SplitInfo(NamedTuple):
    """Best split of one leaf — all 0-d device arrays (except
    ``cat_mask``). The TPU analogue of the reference's POD ``SplitInfo``
    (src/treelearner/split_info.hpp:22).

    ``*_count`` are in-bag row counts (what min_data_in_leaf and leaf_count
    use, matching the reference under bagging); ``*_total_count`` count every
    partitioned row including out-of-bag ones — the learner sizes its row
    compaction buffers with these. For categorical winners
    (``is_categorical``), ``cat_mask`` is the bool[B] set of bins routed
    left (the device analogue of the reference's ``cat_threshold`` bin
    list)."""
    gain: jnp.ndarray            # f32; relative gain (already minus shift); <=0 => invalid
    feature: jnp.ndarray         # i32 inner feature index; -1 if invalid
    threshold_bin: jnp.ndarray   # i32
    default_left: jnp.ndarray    # bool
    is_categorical: jnp.ndarray  # bool
    cat_mask: jnp.ndarray        # bool[B] — bins going left (cat only)
    left_sum_grad: jnp.ndarray   # f32
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray      # f32 (exact for counts < 2^24)
    left_total_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_grad: jnp.ndarray
    right_sum_hess: jnp.ndarray
    right_count: jnp.ndarray
    right_total_count: jnp.ndarray
    right_output: jnp.ndarray
    # monotone-constraint bounds inherited by the children (reference:
    # BasicLeafConstraints, src/treelearner/monotone_constraints.hpp)
    left_min_output: jnp.ndarray
    left_max_output: jnp.ndarray
    right_min_output: jnp.ndarray
    right_max_output: jnp.ndarray


def select_frontier(gain: jnp.ndarray, k: int):
    """(leaves [k] i32, sel_gain [k] f32) of the top-``k`` pending
    split candidates, slot 0 GUARANTEED to be ``jnp.argmax(gain)``
    (ties included). The frontier-batched growers
    (treelearner/sharded.py) speculate these as the next ``k``
    leaf-wise splits in order; pinning slot 0 to the argmax is what
    guarantees every validated sweep round accepts at least one split
    — livelock-free even where ``lax.top_k``'s tie ordering disagrees
    with repeated argmax.

    ``sel_gain`` is the SELECTION value, not a gather of ``gain``:
    when fewer than ``k`` live candidates exist, ``top_k`` over the
    masked vector hands back arbitrary -inf slots whose indices may
    ALIAS a live leaf — reading that leaf's record would resurrect an
    already-consumed candidate (a stale re-split the order validation
    cannot distinguish from the real one). The -inf selection value is
    what marks such a slot dead; callers must thread it into the
    speculation record's gain."""
    best = jnp.argmax(gain).astype(jnp.int32)
    if k <= 1:
        return best[None], gain[best][None]
    masked = gain.at[best].set(-jnp.inf)
    vals, rest = jax.lax.top_k(masked, k - 1)
    return (jnp.concatenate([best[None], rest.astype(jnp.int32)]),
            jnp.concatenate([gain[best][None], vals]))


def threshold_l1(s: jnp.ndarray, l1: jnp.ndarray) -> jnp.ndarray:
    """Soft-threshold by the L1 penalty (reference:
    feature_histogram.hpp ``ThresholdL1``)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_grad, sum_hess, p: SplitParams, l2=None):
    """Closed-form leaf weight (reference: CalculateSplittedLeafOutput,
    feature_histogram.hpp:477+). ``l2`` overrides lambda_l2 (the
    categorical path adds cat_l2, :384)."""
    if l2 is None:
        l2 = p.lambda_l2
    out = -threshold_l1(sum_grad, p.lambda_l1) / (sum_hess + l2)
    return jnp.where(p.max_delta_step > 0.0,
                     jnp.clip(out, -p.max_delta_step, p.max_delta_step),
                     out)


def leaf_gain_given_output(sum_grad, sum_hess, output, p: SplitParams,
                           l2=None):
    """reference: GetLeafGainGivenOutput — exact also when the output was
    clipped by max_delta_step."""
    if l2 is None:
        l2 = p.lambda_l2
    sg = threshold_l1(sum_grad, p.lambda_l1)
    return -(2.0 * sg * output + (sum_hess + l2) * output * output)


def leaf_gain(sum_grad, sum_hess, p: SplitParams, l2=None):
    return leaf_gain_given_output(
        sum_grad, sum_hess, calculate_leaf_output(sum_grad, sum_hess, p, l2),
        p, l2)


def smooth_output(out, count, parent_output, p: SplitParams):
    """Path smoothing toward the parent's output (reference:
    CalculateSplittedLeafOutput USE_SMOOTHING branch,
    feature_histogram.hpp:743-765): out*(n/α)/(n/α+1) + parent/(n/α+1),
    applied after max_delta_step clipping, before monotone clamping."""
    alpha = jnp.maximum(p.path_smooth, jnp.float32(1e-30))
    f = count / alpha
    smoothed = out * f / (f + 1.0) + parent_output / (f + 1.0)
    return jnp.where(p.path_smooth > kSmoothEps, smoothed, out)


kSmoothEps = 1e-15


def make_rand_bins(key, meta: "FeatureMeta", params: SplitParams):
    """extra_trees (config.h:368): one random candidate threshold per
    feature per leaf (reference: meta_->rand.NextInt calls in
    feature_histogram.hpp:109,321,402). Returns (numerical threshold,
    one-hot bin, sorted-prefix position) per feature.

    Seeding contract shared by ALL learners: feature f's draw depends
    only on (key, f) — each feature folds its index into the node key
    and draws from its own stream. A whole-vector ``uniform(key, (F,))``
    draw would make the values depend on the padded feature count,
    and the serial learner pads F to a multiple of 8 while the mesh
    learners don't — their extra_trees splits would diverge."""
    F = meta.num_bin.shape[0]
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        key, jnp.arange(F, dtype=jnp.uint32))
    u = jax.vmap(lambda k: jax.random.uniform(k, (3,)))(keys)
    rand_num = jnp.floor(
        u[:, 0] * jnp.maximum(meta.num_bin - 2, 1)).astype(jnp.int32)
    rand_oh = 1 + jnp.floor(
        u[:, 1] * jnp.maximum(meta.num_bin - 1, 1)).astype(jnp.int32)
    max_thr = jnp.maximum(
        jnp.minimum(params.max_cat_threshold, (meta.num_bin + 1) // 2), 1)
    rand_sorted = jnp.floor(u[:, 2] * max_thr).astype(jnp.int32)
    return rand_num, rand_oh, rand_sorted


def find_best_split(hist: jnp.ndarray,
                    sum_grad: jnp.ndarray,
                    sum_hess: jnp.ndarray,
                    sum_count: jnp.ndarray,
                    sum_total_count: jnp.ndarray,
                    meta: FeatureMeta,
                    params: SplitParams,
                    feature_mask: jnp.ndarray,
                    min_output=None,
                    max_output=None,
                    parent_output=None,
                    rand_bins=None,
                    gain_penalty=None,
                    leaf_depth=None,
                    has_categorical: bool = True,
                    bound_arrays=None,
                    hist_scale=None) -> SplitInfo:
    """Scan a leaf histogram for the best (feature, threshold) pair.

    Parameters
    ----------
    hist : f32[F, B, 4] — per (feature, bin) sums of
        (grad, hess, in-bag count, total count). In quantized-gradient
        mode this arrives as int32/int64 (exact integer accumulation,
        ops/quantize.py) and is dequantized ONCE here — each bin sum
        carries a single rounding from the scale multiply, however deep
        the leaf, instead of the f32 path's one rounding per
        accumulated row; the count channels convert exactly.
    hist_scale : f32[2] (g_scale, h_scale) — required meaningful values
        only when ``hist`` is integer; the leaf totals
        (sum_grad/sum_hess/...) are passed already dequantized.
    sum_grad/sum_hess/sum_count/sum_total_count : leaf totals (f32 scalars)
    meta : FeatureMeta (i32[F] arrays)
    params : SplitParams scalars
    feature_mask : bool[F] — feature_fraction / interaction-constraint mask
      (reference: src/treelearner/col_sampler.hpp)
    has_categorical : STATIC — when the dataset has no categorical
      features the one-hot/sorted-subset scans (two argsorts plus a
      sequential 256-step lax.scan) are compiled out entirely; they are
      dead weight in every split step of an all-numerical dataset.
    bound_arrays : monotone_constraints_method=advanced only — a
      ``(min_c, max_c)`` pair of f32[F, B] per-(feature, bin) output
      constraints (reference: AdvancedFeatureConstraints' piecewise
      thresholds/constraints lists, monotone_constraints.hpp:260,
      expanded dense over the bin axis; pad bins must carry -inf/+inf).
      The per-threshold left/right child bounds are their running
      extrema (reference: CumulativeFeatureConstraint,
      monotone_constraints.hpp:144 — a left child covering bins
      ``[0, t]`` is clamped by every constraint piece overlapping it,
      the right child by pieces overlapping ``[t+1, ...)``); candidates
      whose clamp interval inverts are rejected, mirroring the
      ``best_*_constraints.min > .max → continue`` skip in
      feature_histogram.hpp:950.
    """
    F, B, _ = hist.shape
    from .quantize import dequantize_hist
    hist = dequantize_hist(hist, hist_scale)
    g, h, c, tc = hist[..., 0], hist[..., 1], hist[..., 2], hist[..., 3]
    if min_output is None:
        min_output = jnp.float32(-jnp.inf)
    if max_output is None:
        max_output = jnp.float32(jnp.inf)
    if parent_output is None:
        parent_output = jnp.float32(0.0)

    if bound_arrays is not None:
        min_c, max_c = bound_arrays                              # [F, B]
        lmin_b = jax.lax.cummax(min_c, axis=1)                   # [F, B]
        lmax_b = jax.lax.cummin(max_c, axis=1)
        neg = jnp.full((F, 1), -jnp.inf, dtype=jnp.float32)
        pos = jnp.full((F, 1), jnp.inf, dtype=jnp.float32)
        rmin_b = jnp.concatenate(
            [jax.lax.cummax(min_c, axis=1, reverse=True)[:, 1:], neg], 1)
        rmax_b = jnp.concatenate(
            [jax.lax.cummin(max_c, axis=1, reverse=True)[:, 1:], pos], 1)
        bounds_ok = (lmin_b <= lmax_b) & (rmin_b <= rmax_b)      # [F, B]
        # categorical splits see the leaf-wide (threshold-independent)
        # clamp — pad bins are ±inf-neutral so the row extremum is the
        # most restrictive piece
        flat_min = jnp.max(min_c, axis=1)[:, None]               # [F, 1]
        flat_max = jnp.min(max_c, axis=1)[:, None]
    else:
        flat_min = min_output
        flat_max = max_output

    def bounded_output(sg, sh, n, l2=None, lo=None, hi=None):
        out = calculate_leaf_output(sg, sh, params, l2)
        out = smooth_output(out, n, parent_output, params)
        lo = min_output if lo is None else lo
        hi = max_output if hi is None else hi
        return jnp.clip(out, lo, hi)

    def bounded_gain(sg, sh, n, l2=None):
        return leaf_gain_given_output(
            sg, sh, bounded_output(sg, sh, n, l2, flat_min, flat_max),
            params, l2)

    is_cat = meta.is_categorical                                 # [F]
    is_num = ~is_cat

    # ---------------- numerical scan ----------------
    # Left-side stats for threshold t = sum over bins <= t.
    left_g = jnp.cumsum(g, axis=1)
    left_h = jnp.cumsum(h, axis=1)
    left_c = jnp.cumsum(c, axis=1)
    left_tc = jnp.cumsum(tc, axis=1)

    bin_ids = jnp.arange(B, dtype=jnp.int32)[None, :]            # [1, B]
    num_bin = meta.num_bin[:, None]                              # [F, 1]
    is_nan_missing = (meta.missing_type == MissingType.NAN)      # [F]
    nan_bin = jnp.clip(meta.num_bin - 1, 0, B - 1)               # [F]

    # NaN-bin contents, zero where the feature has no NaN bin.
    take = lambda a: jnp.take_along_axis(a, nan_bin[:, None], axis=1)[:, 0]
    nan_g = jnp.where(is_nan_missing, take(g), 0.0)              # [F]
    nan_h = jnp.where(is_nan_missing, take(h), 0.0)
    nan_c = jnp.where(is_nan_missing, take(c), 0.0)
    nan_tc = jnp.where(is_nan_missing, take(tc), 0.0)

    # Valid thresholds: t <= num_bin - 2 (right side must be reachable); for
    # NaN-missing features the NaN bin itself is not a threshold either
    # (reference scans value bins only).
    t_max = jnp.where(is_nan_missing[:, None], num_bin - 2, num_bin - 1)
    valid_t = (bin_ids < t_max) & feature_mask[:, None] \
        & is_num[:, None]                                        # [F, B]
    if rand_bins is not None:
        # extra_trees: only the per-feature random threshold is a candidate
        valid_t = valid_t & (bin_ids == rand_bins[0][:, None])

    mono = meta.monotone.astype(jnp.int32)[:, None]              # [F, 1]

    def split_gain(lg, lh, lc):
        rg, rh, rc = sum_grad - lg, sum_hess - lh, sum_count - lc
        ok = ((lc >= params.min_data_in_leaf) &
              (rc >= params.min_data_in_leaf) &
              (lh >= params.min_sum_hessian_in_leaf) &
              (rh >= params.min_sum_hessian_in_leaf))
        if bound_arrays is not None:
            out_l = bounded_output(lg, lh, lc, lo=lmin_b, hi=lmax_b)
            out_r = bounded_output(rg, rh, rc, lo=rmin_b, hi=rmax_b)
            ok = ok & bounds_ok
        else:
            out_l = bounded_output(lg, lh, lc)
            out_r = bounded_output(rg, rh, rc)
        # monotone filtering (reference: BasicLeafConstraints split
        # rejection, monotone_constraints.hpp)
        mono_ok = ~(((mono > 0) & (out_l > out_r))
                    | ((mono < 0) & (out_l < out_r)))
        gain = (leaf_gain_given_output(lg, lh, out_l, params)
                + leaf_gain_given_output(rg, rh, out_r, params))
        return jnp.where(ok & valid_t & mono_ok, gain, _NEG_INF)

    # Variant 0: natural placement (NaN bin stays right).
    gain_r = split_gain(left_g, left_h, left_c)
    # Variant 1: NaN bin moved to the left side (default_left).
    gain_l = split_gain(left_g + nan_g[:, None],
                        left_h + nan_h[:, None],
                        left_c + nan_c[:, None])
    # Only distinct for NaN-missing features; suppress the duplicate
    # elsewhere so argmax tie-breaking is deterministic.
    gain_l = jnp.where(is_nan_missing[:, None], gain_l, _NEG_INF)

    kEps = 1e-15
    if has_categorical:
        # ---------------- categorical scans ----------------
        # reference: FindBestThresholdCategoricalInner
        # (src/treelearner/feature_histogram.hpp:278-520). Candidate bins are
        # 1..num_bin-1 (bin 0 = NaN/other always routes right).
        cat_bin_ok = ((bin_ids >= 1) & (bin_ids < num_bin)
                      & is_cat[:, None] & feature_mask[:, None])     # [F, B]
        sum_g_ = sum_grad
        sum_h_ = sum_hess
        sum_c_ = sum_count

        # one-hot mode (num_bin <= max_cat_to_onehot; plain lambda_l2)
        oh_ok = (cat_bin_ok & meta.use_onehot[:, None]
                 & (c >= params.min_data_in_leaf)
                 & (h >= params.min_sum_hessian_in_leaf)
                 & ((sum_c_ - c) >= params.min_data_in_leaf)
                 & ((sum_h_ - h - kEps)
                    >= params.min_sum_hessian_in_leaf))
        if rand_bins is not None:
            oh_ok = oh_ok & (bin_ids == rand_bins[1][:, None])
        gain_oh = bounded_gain(g, h + kEps, c) \
            + bounded_gain(sum_g_ - g, sum_h_ - h - kEps, sum_c_ - c)
        gain_oh = jnp.where(oh_ok, gain_oh, _NEG_INF)

        # sorted-subset mode (l2 += cat_l2; sort by g/(h+cat_smooth))
        cat_l2 = params.lambda_l2 + params.cat_l2
        sort_elig = (cat_bin_ok & ~meta.use_onehot[:, None]
                     & (c >= params.cat_smooth))                     # [F, B]
        used_bin = jnp.sum(sort_elig, axis=1).astype(jnp.int32)      # [F]
        ratio = jnp.where(sort_elig, g / (h + params.cat_smooth), jnp.inf)
        order = jnp.argsort(ratio, axis=1, stable=True)              # [F, B]
        rank = jnp.argsort(order, axis=1, stable=True) \
            .astype(jnp.int32)                                       # [F, B]
        sg_s = jnp.take_along_axis(g, order, axis=1)
        sh_s = jnp.take_along_axis(h, order, axis=1)
        sc_s = jnp.take_along_axis(c, order, axis=1)
        stc_s = jnp.take_along_axis(tc, order, axis=1)
        max_num_cat = jnp.minimum(params.max_cat_threshold,
                                  (used_bin + 1) // 2)               # [F]

        def cat_dir_scan(sgd, shd, scd, stcd):
            """Prefix scan in one direction over sorted bins; returns
            per-prefix gains [F, B] plus prefix stats."""
            lg = jnp.cumsum(sgd, axis=1)
            lh = jnp.cumsum(shd, axis=1) + kEps
            lc = jnp.cumsum(scd, axis=1)
            ltc = jnp.cumsum(stcd, axis=1)
            rg, rh, rc = sum_g_ - lg, sum_h_ - lh, sum_c_ - lc
            idx = jnp.arange(B, dtype=jnp.int32)[None, :]
            pos_ok = (idx < used_bin[:, None]) & (idx < max_num_cat[:, None])
            cont = (lc < params.min_data_in_leaf) \
                | (lh < params.min_sum_hessian_in_leaf)
            brk = (~cont) & ((rc < params.min_data_in_leaf)
                             | (rc < params.min_data_per_group)
                             | (rh < params.min_sum_hessian_in_leaf))
            # sequential min_data_per_group batching (reference
            # feature_histogram.hpp:443-447): accumulate counts, evaluate
            # only when the running group reaches min_data_per_group, then
            # reset. lax.scan over the (<=256) bin positions.
            def step(carry, xs):
                cnt_cur, broken = carry
                cnt_i, cont_i, brk_i, pos_i = xs
                cnt_cur = cnt_cur + cnt_i
                can_eval = (pos_i & ~broken & ~cont_i & ~brk_i
                            & (cnt_cur >= params.min_data_per_group))
                cnt_cur = jnp.where(can_eval, 0.0, cnt_cur)
                broken = broken | (brk_i & pos_i)
                return (cnt_cur, broken), can_eval

            (_, _), can_eval = jax.lax.scan(
                step,
                (jnp.zeros(F), jnp.zeros(F, dtype=bool)),
                (scd.T, cont.T, brk.T, pos_ok.T))
            can_eval = can_eval.T                                    # [F, B]
            gains = bounded_gain(lg, lh, lc, cat_l2) \
                + bounded_gain(rg, rh, rc, cat_l2)
            return jnp.where(can_eval, gains, _NEG_INF), (lg, lh, lc, ltc)

        gain_cs_f, stats_f = cat_dir_scan(sg_s, sh_s, sc_s, stc_s)
        # reverse direction: prefixes from the high end of the sorted order,
        # but only over the eligible (first used_bin) positions — roll the
        # reversed arrays so eligible bins come first
        def rev_eligible(a):
            ar = jnp.flip(a, axis=1)
            shift = B - used_bin                                    # [F]
            idx = (jnp.arange(B, dtype=jnp.int32)[None, :]
                   + shift[:, None]) % B
            return jnp.take_along_axis(ar, idx, axis=1)

        gain_cs_r, stats_r = cat_dir_scan(
            rev_eligible(sg_s), rev_eligible(sh_s), rev_eligible(sc_s),
            rev_eligible(stc_s))
        if rand_bins is not None:
            # extra_trees sorted-subset mode: only the random prefix length
            # (reference: rand.NextInt(0, max_threshold), fh.hpp:402)
            rs = rand_bins[2][:, None] == bin_ids
            gain_cs_f = jnp.where(rs, gain_cs_f, _NEG_INF)
            gain_cs_r = jnp.where(rs, gain_cs_r, _NEG_INF)

    # Parent-gain baseline, subtracted per variant BEFORE the argmax
    # (reference: min_gain_shift). Under path smoothing the numerical
    # baseline recomputes the smoothed own-output (BeforeNumercal,
    # fh.hpp:99-110) while the categorical baseline scores the stored
    # parent output directly (fh.hpp:294-303); without smoothing both
    # reduce to the plain closed form.
    parent_gain_plain = leaf_gain(sum_grad, sum_hess, params)
    own_out = calculate_leaf_output(sum_grad, sum_hess, params)
    own_smoothed = smooth_output(own_out, sum_count, parent_output, params)
    use_smooth = params.path_smooth > kSmoothEps
    parent_gain_num = jnp.where(
        use_smooth,
        leaf_gain_given_output(sum_grad, sum_hess, own_smoothed, params),
        parent_gain_plain)
    parent_gain_cat = jnp.where(
        use_smooth,
        leaf_gain_given_output(sum_grad, sum_hess, parent_output, params),
        parent_gain_plain)
    shift_num = parent_gain_num + params.min_gain_to_split
    shift_cat = parent_gain_cat + params.min_gain_to_split

    if has_categorical:
        gains = jnp.stack([gain_r - shift_num, gain_l - shift_num,
                           gain_oh - shift_cat, gain_cs_f - shift_cat,
                           gain_cs_r - shift_cat])
    else:
        gains = jnp.stack([gain_r - shift_num, gain_l - shift_num])
    if gain_penalty is not None:
        # CEGB per-feature gain penalty (reference:
        # CostEfficientGradientBoosting::DeltaGain,
        # cost_effective_gradient_boosting.hpp:80 — threshold-independent,
        # so it reorders features without changing per-feature thresholds)
        gains = gains - gain_penalty[None, :, None]
    if leaf_depth is not None:
        # monotone split gain penalty (reference:
        # ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:355):
        # gains of splits on monotone features shrink with depth
        kMPEps = 1e-10
        p = params.monotone_penalty
        d = leaf_depth.astype(jnp.float32)
        factor = jnp.where(
            p >= d + 1.0, kMPEps,
            jnp.where(p <= 1.0,
                      1.0 - p / jnp.exp2(d) + kMPEps,
                      1.0 - jnp.exp2(p - 1.0 - d) + kMPEps))
        is_mono = (meta.monotone != 0) & (p > 0.0)
        mult = jnp.where(is_mono, factor, 1.0)[None, :, None]
        gains = jnp.where(jnp.isfinite(gains), gains * mult, gains)

    flat = gains.reshape(-1)
    best = jnp.argmax(flat)
    best_gain_rel = flat[best]
    variant, rem = best // (F * B), best % (F * B)
    feature, tbin = (rem // B).astype(jnp.int32), (rem % B).astype(jnp.int32)

    # Reconstruct the winning split's stats per variant.
    is_l = variant == 1
    lg_n = left_g[feature, tbin] + jnp.where(is_l, nan_g[feature], 0.0)
    lh_n = left_h[feature, tbin] + jnp.where(is_l, nan_h[feature], 0.0)
    lc_n = left_c[feature, tbin] + jnp.where(is_l, nan_c[feature], 0.0)
    ltc_n = left_tc[feature, tbin] + jnp.where(is_l, nan_tc[feature], 0.0)

    if has_categorical:
        winner_is_cat = variant >= 2
        lg = jnp.select(
            [variant <= 1, variant == 2, variant == 3, variant == 4],
            [lg_n, g[feature, tbin], stats_f[0][feature, tbin],
             stats_r[0][feature, tbin]])
        lh = jnp.select(
            [variant <= 1, variant == 2, variant == 3, variant == 4],
            [lh_n, h[feature, tbin] + kEps, stats_f[1][feature, tbin],
             stats_r[1][feature, tbin]])
        lc = jnp.select(
            [variant <= 1, variant == 2, variant == 3, variant == 4],
            [lc_n, c[feature, tbin], stats_f[2][feature, tbin],
             stats_r[2][feature, tbin]])
        ltc = jnp.select(
            [variant <= 1, variant == 2, variant == 3, variant == 4],
            [ltc_n, tc[feature, tbin], stats_f[3][feature, tbin],
             stats_r[3][feature, tbin]])
    else:
        winner_is_cat = jnp.asarray(False)
        lg, lh, lc, ltc = lg_n, lh_n, lc_n, ltc_n
    rg, rh, rc = sum_grad - lg, sum_hess - lh, sum_count - lc
    rtc = sum_total_count - ltc

    gain_rel = best_gain_rel
    is_valid = jnp.isfinite(best_gain_rel) & (gain_rel > 0.0)

    default_left = jnp.where(
        winner_is_cat, False,
        jnp.where(is_nan_missing[feature], variant == 1,
                  (meta.missing_type[feature] == MissingType.ZERO)
                  & (meta.zero_bin[feature] <= tbin)))

    if has_categorical:
        # categorical left-bin mask: one-hot → {tbin}; sorted fwd →
        # sorted rank <= tbin; sorted rev → the tbin+1 highest-ratio
        # eligible bins
        rk = rank[feature]                                       # [B]
        ub = used_bin[feature]
        mask_oh = jnp.arange(B, dtype=jnp.int32) == tbin
        mask_fwd = rk <= tbin
        mask_rev = (rk >= ub - 1 - tbin) & (rk < ub)
        elig_row = sort_elig[feature]
        cat_mask = jnp.select(
            [variant == 2, variant == 3, variant == 4],
            [mask_oh, mask_fwd & elig_row, mask_rev & elig_row],
            jnp.zeros(B, dtype=bool))
        out_l2 = jnp.where(variant >= 3, cat_l2, params.lambda_l2)
    else:
        cat_mask = jnp.zeros(B, dtype=bool)
        out_l2 = params.lambda_l2
    if bound_arrays is not None:
        # the winner's outputs must carry the same per-threshold clamp
        # the gain scan used (reference: CalculateSplittedLeafOutput
        # with best_left/right_constraints, feature_histogram.hpp:1060)
        w_lmin = jnp.where(winner_is_cat, flat_min[feature, 0],
                           lmin_b[feature, tbin])
        w_lmax = jnp.where(winner_is_cat, flat_max[feature, 0],
                           lmax_b[feature, tbin])
        w_rmin = jnp.where(winner_is_cat, flat_min[feature, 0],
                           rmin_b[feature, tbin])
        w_rmax = jnp.where(winner_is_cat, flat_max[feature, 0],
                           rmax_b[feature, tbin])
        out_left = bounded_output(lg, lh, lc, out_l2, w_lmin, w_lmax)
        out_right = bounded_output(rg, rh, rc, out_l2, w_rmin, w_rmax)
    else:
        out_left = bounded_output(lg, lh, lc, out_l2)
        out_right = bounded_output(rg, rh, rc, out_l2)
    # children bounds (reference: BasicLeafConstraints::Update — the
    # mid-point between child outputs caps the monotone side)
    mc_w = jnp.where(winner_is_cat, 0,
                     meta.monotone[feature].astype(jnp.int32))
    mid = (out_left + out_right) / 2.0
    left_max = jnp.where(mc_w > 0, jnp.minimum(max_output, mid),
                         max_output)
    right_min = jnp.where(mc_w > 0, jnp.maximum(min_output, mid),
                          min_output)
    left_min = jnp.where(mc_w < 0, jnp.maximum(min_output, mid),
                         min_output)
    right_max = jnp.where(mc_w < 0, jnp.minimum(max_output, mid),
                          max_output)
    return SplitInfo(
        gain=jnp.where(is_valid, gain_rel, _NEG_INF).astype(jnp.float32),
        feature=jnp.where(is_valid, feature, -1),
        threshold_bin=tbin,
        default_left=default_left,
        is_categorical=winner_is_cat,
        cat_mask=cat_mask,
        left_sum_grad=lg, left_sum_hess=lh, left_count=lc,
        left_total_count=ltc,
        left_output=out_left,
        right_sum_grad=rg, right_sum_hess=rh, right_count=rc,
        right_total_count=rtc,
        right_output=out_right,
        left_min_output=left_min, left_max_output=left_max,
        right_min_output=right_min, right_max_output=right_max,
    )
