"""Best-split search over (grad, hess, count) histograms — the TPU analogue of
the reference's per-feature threshold scan.

Reference semantics reproduced (src/treelearner/feature_histogram.hpp:85
``FindBestThreshold`` / ``FindBestThresholdSequentially``; closed forms at
:477+ ``CalculateSplittedLeafOutput`` / ``GetSplitGains``; CUDA analogue
src/treelearner/cuda/cuda_best_split_finder.cu:603):

- leaf output  = -ThresholdL1(sum_grad, l1) / (sum_hess + l2), clipped to
  +-max_delta_step when positive
- leaf gain    = -(2*ThresholdL1(g,l1)*out + (h+l2)*out^2)  (equals
  ThresholdL1(g)^2/(h+l2) when the output is unclipped)
- a split is valid iff both children have >= min_data_in_leaf rows and
  >= min_sum_hessian, and split gain exceeds parent gain + min_gain_to_split
- missing handling: features with MissingType.NAN hold NaN rows in their last
  bin; the scan evaluates both "NaN goes right" (natural — the NaN bin is
  never <= threshold) and "NaN goes left" placements and records
  ``default_left``. MissingType.ZERO rows sit in the zero bin and follow the
  natural bin comparison, so default_left = (zero_bin <= threshold).

Instead of the reference's sequential per-feature loop (or the CUDA warp
prefix-sum scan), everything here is one vectorized pass: cumulative sums over
the bin axis give left-side stats for every (feature, threshold) at once, a
masked argmax picks the winner. This maps to a handful of XLA reductions, no
data-dependent control flow.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..io.binning import MissingType

_NEG_INF = -jnp.inf


class SplitParams(NamedTuple):
    """Scalar hyper-parameters of the split search (all traced, so one
    compiled kernel serves any setting). Mirror of the Config fields used by
    the reference's FeatureHistogram (config.h:291-406)."""
    lambda_l1: jnp.ndarray
    lambda_l2: jnp.ndarray
    min_data_in_leaf: jnp.ndarray
    min_sum_hessian_in_leaf: jnp.ndarray
    min_gain_to_split: jnp.ndarray
    max_delta_step: jnp.ndarray

    @classmethod
    def from_config(cls, config) -> "SplitParams":
        return cls(
            lambda_l1=jnp.float32(config.lambda_l1),
            lambda_l2=jnp.float32(config.lambda_l2),
            min_data_in_leaf=jnp.float32(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=jnp.float32(config.min_sum_hessian_in_leaf),
            min_gain_to_split=jnp.float32(config.min_gain_to_split),
            max_delta_step=jnp.float32(config.max_delta_step),
        )


class FeatureMeta(NamedTuple):
    """Per-feature static metadata, device-resident (int32 [F] each).
    Derived from the BinMappers at dataset finalization."""
    num_bin: jnp.ndarray        # bins actually used by feature f
    missing_type: jnp.ndarray   # MissingType value
    zero_bin: jnp.ndarray       # bin holding value 0.0 (default_bin)

    @classmethod
    def from_dataset(cls, dataset) -> "FeatureMeta":
        import numpy as np
        return cls(
            num_bin=jnp.asarray(np.asarray(dataset.num_bin_per_feature,
                                           dtype=np.int32)),
            missing_type=jnp.asarray(
                np.asarray([m.missing_type for m in dataset.bin_mappers],
                           dtype=np.int32)),
            zero_bin=jnp.asarray(
                np.asarray([m.default_bin for m in dataset.bin_mappers],
                           dtype=np.int32)),
        )


class SplitInfo(NamedTuple):
    """Best split of one leaf — all 0-d device arrays. The TPU analogue of
    the reference's POD ``SplitInfo`` (src/treelearner/split_info.hpp:22).

    ``*_count`` are in-bag row counts (what min_data_in_leaf and leaf_count
    use, matching the reference under bagging); ``*_total_count`` count every
    partitioned row including out-of-bag ones — the learner sizes its row
    compaction buffers with these."""
    gain: jnp.ndarray            # f32; relative gain (already minus shift); <=0 => invalid
    feature: jnp.ndarray         # i32 inner feature index; -1 if invalid
    threshold_bin: jnp.ndarray   # i32
    default_left: jnp.ndarray    # bool
    left_sum_grad: jnp.ndarray   # f32
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray      # f32 (exact for counts < 2^24)
    left_total_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_grad: jnp.ndarray
    right_sum_hess: jnp.ndarray
    right_count: jnp.ndarray
    right_total_count: jnp.ndarray
    right_output: jnp.ndarray


def threshold_l1(s: jnp.ndarray, l1: jnp.ndarray) -> jnp.ndarray:
    """Soft-threshold by the L1 penalty (reference:
    feature_histogram.hpp ``ThresholdL1``)."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_grad, sum_hess, p: SplitParams):
    """Closed-form leaf weight (reference: CalculateSplittedLeafOutput,
    feature_histogram.hpp:477+)."""
    out = -threshold_l1(sum_grad, p.lambda_l1) / (sum_hess + p.lambda_l2)
    return jnp.where(p.max_delta_step > 0.0,
                     jnp.clip(out, -p.max_delta_step, p.max_delta_step),
                     out)


def leaf_gain_given_output(sum_grad, sum_hess, output, p: SplitParams):
    """reference: GetLeafGainGivenOutput — exact also when the output was
    clipped by max_delta_step."""
    sg = threshold_l1(sum_grad, p.lambda_l1)
    return -(2.0 * sg * output + (sum_hess + p.lambda_l2) * output * output)


def leaf_gain(sum_grad, sum_hess, p: SplitParams):
    return leaf_gain_given_output(
        sum_grad, sum_hess, calculate_leaf_output(sum_grad, sum_hess, p), p)


def find_best_split(hist: jnp.ndarray,
                    sum_grad: jnp.ndarray,
                    sum_hess: jnp.ndarray,
                    sum_count: jnp.ndarray,
                    sum_total_count: jnp.ndarray,
                    meta: FeatureMeta,
                    params: SplitParams,
                    feature_mask: jnp.ndarray) -> SplitInfo:
    """Scan a leaf histogram for the best (feature, threshold) pair.

    Parameters
    ----------
    hist : f32[F, B, 4] — per (feature, bin) sums of
        (grad, hess, in-bag count, total count)
    sum_grad/sum_hess/sum_count/sum_total_count : leaf totals (f32 scalars)
    meta : FeatureMeta (i32[F] arrays)
    params : SplitParams scalars
    feature_mask : bool[F] — feature_fraction / interaction-constraint mask
      (reference: src/treelearner/col_sampler.hpp)
    """
    F, B, _ = hist.shape
    g, h, c, tc = hist[..., 0], hist[..., 1], hist[..., 2], hist[..., 3]

    # Left-side stats for threshold t = sum over bins <= t.
    left_g = jnp.cumsum(g, axis=1)
    left_h = jnp.cumsum(h, axis=1)
    left_c = jnp.cumsum(c, axis=1)
    left_tc = jnp.cumsum(tc, axis=1)

    bin_ids = jnp.arange(B, dtype=jnp.int32)[None, :]            # [1, B]
    num_bin = meta.num_bin[:, None]                              # [F, 1]
    is_nan_missing = (meta.missing_type == MissingType.NAN)      # [F]
    nan_bin = jnp.clip(meta.num_bin - 1, 0, B - 1)               # [F]

    # NaN-bin contents, zero where the feature has no NaN bin.
    take = lambda a: jnp.take_along_axis(a, nan_bin[:, None], axis=1)[:, 0]
    nan_g = jnp.where(is_nan_missing, take(g), 0.0)              # [F]
    nan_h = jnp.where(is_nan_missing, take(h), 0.0)
    nan_c = jnp.where(is_nan_missing, take(c), 0.0)
    nan_tc = jnp.where(is_nan_missing, take(tc), 0.0)

    # Valid thresholds: t <= num_bin - 2 (right side must be reachable); for
    # NaN-missing features the NaN bin itself is not a threshold either
    # (reference scans value bins only).
    t_max = jnp.where(is_nan_missing[:, None], num_bin - 2, num_bin - 1)
    valid_t = (bin_ids < t_max) & feature_mask[:, None]          # [F, B]

    def split_gain(lg, lh, lc):
        rg, rh, rc = sum_grad - lg, sum_hess - lh, sum_count - lc
        ok = ((lc >= params.min_data_in_leaf) &
              (rc >= params.min_data_in_leaf) &
              (lh >= params.min_sum_hessian_in_leaf) &
              (rh >= params.min_sum_hessian_in_leaf))
        gain = (leaf_gain(lg, lh, params) + leaf_gain(rg, rh, params))
        return jnp.where(ok & valid_t, gain, _NEG_INF)

    # Variant 0: natural placement (NaN bin stays right).
    gain_r = split_gain(left_g, left_h, left_c)
    # Variant 1: NaN bin moved to the left side (default_left).
    gain_l = split_gain(left_g + nan_g[:, None],
                        left_h + nan_h[:, None],
                        left_c + nan_c[:, None])
    # Only distinct for NaN-missing features; suppress the duplicate
    # elsewhere so argmax tie-breaking is deterministic.
    gain_l = jnp.where(is_nan_missing[:, None], gain_l, _NEG_INF)

    gains = jnp.stack([gain_r, gain_l])                          # [2, F, B]
    parent_gain = leaf_gain(sum_grad, sum_hess, params)
    shift = parent_gain + params.min_gain_to_split

    flat = gains.reshape(-1)
    best = jnp.argmax(flat)
    best_gain_abs = flat[best]
    variant, rem = best // (F * B), best % (F * B)
    feature, tbin = (rem // B).astype(jnp.int32), (rem % B).astype(jnp.int32)

    # Reconstruct the winning split's stats.
    is_l = variant == 1
    lg = left_g[feature, tbin] + jnp.where(is_l, nan_g[feature], 0.0)
    lh = left_h[feature, tbin] + jnp.where(is_l, nan_h[feature], 0.0)
    lc = left_c[feature, tbin] + jnp.where(is_l, nan_c[feature], 0.0)
    ltc = left_tc[feature, tbin] + jnp.where(is_l, nan_tc[feature], 0.0)
    rg, rh, rc = sum_grad - lg, sum_hess - lh, sum_count - lc
    rtc = sum_total_count - ltc

    gain_rel = best_gain_abs - shift
    is_valid = jnp.isfinite(best_gain_abs) & (gain_rel > 0.0)

    default_left = jnp.where(
        is_nan_missing[feature], variant == 1,
        (meta.missing_type[feature] == MissingType.ZERO)
        & (meta.zero_bin[feature] <= tbin))

    return SplitInfo(
        gain=jnp.where(is_valid, gain_rel, _NEG_INF).astype(jnp.float32),
        feature=jnp.where(is_valid, feature, -1),
        threshold_bin=tbin,
        default_left=default_left,
        left_sum_grad=lg, left_sum_hess=lh, left_count=lc,
        left_total_count=ltc,
        left_output=calculate_leaf_output(lg, lh, params),
        right_sum_grad=rg, right_sum_hess=rh, right_count=rc,
        right_total_count=rtc,
        right_output=calculate_leaf_output(rg, rh, params),
    )
