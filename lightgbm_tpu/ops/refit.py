"""Device refit kernel: per-leaf gradient statistics over a frozen forest.

The reference's ``GBDT::RefitTree`` (src/boosting/gbdt.cpp:250) walks
every tree on the host, row by row. Here the leaf assignment for ALL
trees comes from one stacked-forest walk (``ops/predict.py`` via
``serve.StackedForest.leaves_device``), and each tree's per-leaf
gradient/hessian sums are ``jax.ops.segment_sum`` reductions — a pure
device replay. One jitted step with a stable signature serves every
tree (the tree index and class index ride in as traced device scalars),
so a T-tree refit costs one trace, T dispatches, and a single read-back
of the updated [T, NL] leaf table at the end.

Precision: the device sums run in f32 (the repo does not enable x64),
while the host oracle (``boosting/refit.py:refit_model``) accumulates in
f64 — parity is within the documented tolerance (docs/REFRESH.md), not
bit-exact. The serialized model text IS exact for what the device
computed: leaf values round-trip through the shortest-round-trip decimal
formatter unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..obs import compile as obs_compile


def _refit_tree_step(score, g, h, k, ti, leaf_ids, old_vals, num_leaves,
                     l1, l2, max_delta, shrinkage, decay):
    """One tree of the refit replay.

    score      [n] (K==1) or [n, K] f32 running raw scores (device) —
               rank matches what the objective's get_gradients takes,
               so the caller never slices eagerly between steps
    g, h       [n] or [n, K] gradients/hessians for the CURRENT score
    k          traced i32    class index (tree ti's column)
    ti         traced i32    tree index into the stacked arrays
    leaf_ids   [T, n] i32    stacked leaf assignment (frozen structure)
    old_vals   [T, NL] f32   current leaf values
    num_leaves static int    NL (padded; segment count)
    l1/l2/max_delta/shrinkage/decay: traced f32 scalars

    Returns (new_vals [NL], score') — the closed-form regularized leaf
    optimum over the rows landing in each leaf, decay-mixed with the old
    value (reference: feature_histogram.hpp CalculateSplittedLeafOutput;
    config.h:524 refit_decay_rate). Empty leaves keep their old value,
    same as the host oracle's ``if not rows.any(): continue``.
    """
    ids = jnp.take(leaf_ids, ti, axis=0)
    old = jnp.take(old_vals, ti, axis=0)
    gk = g if g.ndim == 1 else jnp.take(g, k, axis=1)
    hk = h if h.ndim == 1 else jnp.take(h, k, axis=1)
    sg = jax.ops.segment_sum(gk, ids, num_segments=num_leaves)
    sh = jax.ops.segment_sum(hk, ids, num_segments=num_leaves)
    cnt = jax.ops.segment_sum(jnp.ones_like(gk), ids,
                              num_segments=num_leaves)
    thresholded = jnp.sign(sg) * jnp.maximum(jnp.abs(sg) - l1, 0.0)
    out = -thresholded / (sh + l2)
    # max_delta_step arrives as +inf when disabled: clip is the identity
    out = jnp.clip(out, -max_delta, max_delta)
    mixed = decay * old + (1.0 - decay) * shrinkage * out
    new_vals = jnp.where(cnt > 0, mixed, old)
    if score.ndim == 1:
        score = score + jnp.take(new_vals, ids)
    else:
        score = score.at[:, k].add(jnp.take(new_vals, ids))
    return new_vals, score


refit_tree_step = obs_compile.instrument_jit(
    "refit.tree_step", _refit_tree_step, static_argnums=(7,))
