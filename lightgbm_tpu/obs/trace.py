"""Span tracing with Chrome-trace / Perfetto JSON export.

Layers a span model (trace_id / span_id / parent links, process+host
tagged) onto the telemetry the pipeline already emits, WITHOUT touching
any call site:

- every ``registry.timer.scope`` (binning, root_histogram,
  split_batches, gradients, score_update, predict_batch, ...) becomes a
  ``ph:"X"`` complete event on the calling thread's lane, parented by
  the enclosing scope via a thread-local span stack;
- every ``events.emit`` record becomes an instant event on the same
  lane (``jit_trace`` events instead become spans on a dedicated
  compile lane, carrying cost_analysis FLOPs / bytes when captured);
- the registry's async readiness drainer reports device completion of
  watched stage outputs as spans on a device-readiness lane;
- per-iteration device memory gauges land as counter tracks.

Enable with ``LIGHTGBM_TPU_TRACE=/path/to/trace.json`` (or
:func:`configure`). The file is a standard Chrome-trace JSON object —
open it at https://ui.perfetto.dev or chrome://tracing. Multi-process
(dtrain) runs write one file per rank (the rank is folded into the
path); ``tools/trace_report.py merge`` interleaves them by wall clock
into one file with per-rank process lanes.

Timestamps are wall-anchored but perf_counter-derived: one (wall, perf)
origin pair is sampled at import and every event timestamp is
``origin_wall + (perf_now - origin_perf)``, so intra-process ordering
is strictly monotone while cross-process merge still lines up on the
wall clock.

The span buffer is in-memory and bounded (``kMaxEvents``); it is
written on :func:`flush` (registered atexit), on :func:`configure`,
and the export rewrites the whole file — partial JSON is never left
behind.

For runs of unbounded length, ``LIGHTGBM_TPU_TRACE_STREAM=dir`` (or
:func:`configure_stream`) replaces the single bounded buffer with a
STREAMING SPOOL: events stage in a small in-memory chunk, a writer
thread serializes chunks off the hot path, and whenever the current
segment reaches ``LIGHTGBM_TPU_TRACE_SEGMENT_BYTES`` (default 8 MiB)
it is finalized ATOMICALLY (tmp + rename) as
``segment-r<rank>-<seq>.json`` — a self-contained Chrome-trace file —
inside the directory. Memory stays bounded at (staging chunk + writer
backlog + one segment); when the writer backlog is full, whole chunks
are dropped and counted under ``trace/dropped_events`` instead of
growing RSS. ``tools/trace_report.py`` validates / merges / summarizes
/ tails segment directories. Flush (atexit, ``log.fatal``,
:func:`configure`) finalizes the partial tail segment, so the on-disk
directory never holds invalid JSON.

``LIGHTGBM_TPU_TRACE_FORMAT=compact`` switches the streaming spool to
the string-interned varint binary segment format of
:mod:`obs.trace_compact` (``segment-r<rank>-<seq>.ctrace``, ≥3x
smaller on disk, same atomic finalize + rotation + drop accounting);
``tools/trace_report.py`` reads both transparently and ``convert``
turns compact segments back into lossless Chrome-trace JSON. Every
segment's ``otherData`` carries the run-correlation id
(``obs.events.run_id`` / ``LIGHTGBM_TPU_RUN_ID``) so fleet reports can
join segments with gateway metrics.
"""
from __future__ import annotations

import atexit
import itertools
import json
import os
import socket
import sys
import threading
import time
from typing import Dict, List, Optional

from . import events as _events
from . import faults
from . import trace_compact as _compact
from .registry import install_trace_hooks as _install_trace_hooks
from .registry import registry

_ENV_VAR = "LIGHTGBM_TPU_TRACE"
_ENV_STREAM = "LIGHTGBM_TPU_TRACE_STREAM"
_ENV_SEGMENT_BYTES = "LIGHTGBM_TPU_TRACE_SEGMENT_BYTES"
_ENV_FORMAT = "LIGHTGBM_TPU_TRACE_FORMAT"

kMaxEvents = 1 << 18
kDefaultSegmentBytes = 8 << 20
# streaming spool: hot-path staging chunk size and writer backlog cap
# (chunks). Memory in flight is bounded by
# stage_events * (1 + max_pending) events + one serialized segment.
kStreamStageEvents = 1024
kStreamMaxPending = 64

_lock = threading.Lock()
_events_buf: List[dict] = []
_dropped = 0
_path_override: Optional[str] = None
_stream_override: Optional[str] = None
_stream_disabled = False  # configure_stream(None) = explicitly OFF
_spool: Optional["_Spool"] = None
_span_seq = itertools.count(1)
_tls = threading.local()

# wall-anchored monotone clock origin (see module docstring)
_t0_wall = time.time()
_t0_perf = time.perf_counter()

_trace_id: Optional[str] = None
_process_index: Optional[int] = None

# lane (tid) allocation: stable small ints + a thread_name metadata
# record per lane; special string keys reserve the synthetic lanes
_lane_ids: Dict[object, int] = {}
_lane_names: Dict[int, str] = {}
kReadyLane = "device::ready"
kCompileLane = "jit::compile"


def _now_us() -> float:
    return (_t0_wall + (time.perf_counter() - _t0_perf)) * 1e6


def _perf_to_us(t_perf: float) -> float:
    return (_t0_wall + (t_perf - _t0_perf)) * 1e6


# The env sinks are resolved ONCE at import (unlike the event log's
# per-emit read): active() sits on every stage-scope entry, and the
# telemetry-off fast path must stay a couple of attribute reads, not an
# os.environ lookup per scope. Late re-pointing goes through
# configure() / configure_stream().
_env_path = os.environ.get(_ENV_VAR) or None
_env_stream = os.environ.get(_ENV_STREAM) or None


def sink_path() -> Optional[str]:
    return _path_override or _env_path


def stream_dir() -> Optional[str]:
    """Segment-directory sink (streaming mode); takes precedence over
    the single-file sink when both are configured. None after an
    explicit ``configure_stream(None)`` even when the env var is set —
    detaching must not silently re-open (and re-write) the env
    directory."""
    if _stream_disabled:
        return None
    return _stream_override or _env_stream


def _streaming_configured() -> bool:
    return not _stream_disabled and (_stream_override is not None
                                     or _env_stream is not None)


def active() -> bool:
    return (_path_override is not None or _env_path is not None
            or _streaming_configured())


def trace_id() -> str:
    global _trace_id
    if _trace_id is None:
        _trace_id = "%d-%x" % (os.getpid(), int(time.time() * 1e6))
    return _trace_id


def process_index() -> int:
    """The rank used as the Chrome-trace pid (one lane group per rank
    after merge). Resolved from jax.process_index() when jax is already
    initialized, else 0; :func:`set_process_index` overrides."""
    global _process_index
    if _process_index is None:
        idx = 0
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                idx = int(jax.process_index())
            except Exception:
                idx = 0
        _process_index = idx
    return _process_index


def set_process_index(idx: int) -> None:
    global _process_index
    _process_index = int(idx)


def rank_path(path: str, rank: int) -> str:
    """Per-rank trace file name: ``trace.json`` → ``trace.rank1.json``
    (rank 0 keeps the plain path so single-process usage is unchanged).
    Idempotent — re-ranking an already-ranked path (a second
    dtrain.train() in one process) returns it unchanged."""
    if rank == 0:
        return path
    root, ext = os.path.splitext(path)
    suffix = ".rank%d" % rank
    if root.endswith(suffix):
        return path
    return root + suffix + ext


def configure(path: Optional[str],
              process_index_override: Optional[int] = None,
              keep_buffer: bool = False) -> None:
    """Pin the trace sink programmatically (overrides the env var; None
    falls back to ``LIGHTGBM_TPU_TRACE`` as read at import). By default
    flushes to the OLD sink and then RESETS the span buffer, so each
    configured sink holds one self-contained trace.

    ``keep_buffer=True`` re-points WITHOUT touching the old sink:
    buffered events move to the new path as-is. dtrain uses this to
    fold the rank into the path — rank>0 must never write (not even a
    departing flush to) the shared un-ranked file."""
    global _path_override, _trace_id, _dropped
    if not keep_buffer:
        flush()
    with _lock:
        _path_override = path
        if not keep_buffer:
            _events_buf.clear()
            _lane_ids.clear()
            _lane_names.clear()
            _dropped = 0
            _trace_id = None
    if process_index_override is not None:
        set_process_index(process_index_override)


def configure_stream(dirpath: Optional[str],
                     segment_bytes: Optional[int] = None,
                     stage_events: Optional[int] = None,
                     max_pending: Optional[int] = None,
                     process_index_override: Optional[int] = None,
                     segment_format: Optional[str] = None) -> None:
    """Pin the streaming segment-directory sink programmatically
    (overrides ``LIGHTGBM_TPU_TRACE_STREAM``). ``None`` turns
    streaming OFF outright — unlike :func:`configure` it does NOT fall
    back to the env var: detaching must never silently re-open the
    env directory and restart its segment sequence over the previous
    run's files. Flushes whichever sink is currently active first, so
    each configured directory holds one self-contained segment
    sequence. ``segment_bytes`` / ``stage_events`` / ``max_pending``
    override the rotation size, the hot-path staging chunk, and the
    writer backlog cap (tests shrink all three to force rotation and
    drops at toy scale); ``segment_format`` (``"json"`` default /
    ``"compact"``) overrides ``LIGHTGBM_TPU_TRACE_FORMAT``."""
    global _stream_override, _stream_disabled, _spool, _trace_id
    old = _spool
    # whichever sink is currently active gets its staged events first
    # (a single-file trace switching into streaming mode must not
    # orphan its buffer)
    flush()
    with _lock:
        _stream_override = dirpath
        _stream_disabled = dirpath is None
        _spool = None
        if old is not None:
            _lane_ids.clear()
            _lane_names.clear()
            _trace_id = None
        if stream_dir() is not None:
            _spool = _Spool(stream_dir(), segment_bytes=segment_bytes,
                            stage_events=stage_events,
                            max_pending=max_pending,
                            segment_format=segment_format)
    if process_index_override is not None:
        set_process_index(process_index_override)


def _lane(key, name: str) -> int:
    # under _lock: concurrent first-use from the trainer, the readiness
    # drainer, and serve workers must not hand two threads one tid
    with _lock:
        lane = _lane_ids.get(key)
        if lane is None:
            lane = len(_lane_ids) + 1
            _lane_ids[key] = lane
            _lane_names[lane] = name
        return lane


def _thread_lane() -> int:
    # keyed by (ident, name), not bare ident: CPython recycles thread
    # ids, and a recycled id must not inherit a dead thread's lane label
    t = threading.current_thread()
    return _lane((t.ident, t.name), t.name)


def _push(ev: dict) -> None:
    global _dropped
    with _lock:
        sp = _ensure_spool_locked()
        if sp is not None:
            sp.push(ev)
            return
        if len(_events_buf) >= kMaxEvents:
            _dropped += 1
            registry.inc("trace/dropped_events")
            return
        _events_buf.append(ev)


def _ensure_spool_locked() -> Optional["_Spool"]:
    """The active spool, creating it lazily when streaming is enabled
    via the env var alone (configure_stream creates it eagerly).
    Caller holds ``_lock``."""
    global _spool
    if _spool is None and stream_dir() is not None:
        _spool = _Spool(stream_dir())
    return _spool


class _Spool:
    """Size-rotated streaming segment writer.

    Hot path: :meth:`push` (under the module ``_lock``) appends to a
    small staging list; every ``stage_events`` events the chunk is
    handed to a writer thread through a BOUNDED backlog — when the
    backlog is full (writer can't keep up / disk wedged) the chunk is
    dropped whole and counted under ``trace/dropped_events``, so RSS
    stays bounded no matter how long the run is.

    Writer thread: serializes each event once (a json line, or — in
    ``compact`` format — interned varint records via
    obs/trace_compact.py) and, when the serialized size of the open
    segment reaches ``segment_bytes``, finalizes it ATOMICALLY — the
    full document (lane metadata + events + otherData) is written to
    ``<name>.tmp`` and ``os.replace``d to
    ``segment-r<rank>-<seq>.json`` / ``.ctrace``. Every file in the
    directory is therefore always a complete, valid segment; readers
    (``trace_report.py tail``) never see a partial one.

    :meth:`flush` (atexit, ``log.fatal``, configure) drains staging +
    backlog and finalizes the partial tail segment. Never raises."""

    def __init__(self, dirpath: str,
                 segment_bytes: Optional[int] = None,
                 stage_events: Optional[int] = None,
                 max_pending: Optional[int] = None,
                 segment_format: Optional[str] = None) -> None:
        self.dir = dirpath
        if segment_bytes is None:
            try:
                segment_bytes = int(os.environ.get(
                    _ENV_SEGMENT_BYTES, kDefaultSegmentBytes))
            except ValueError:
                segment_bytes = kDefaultSegmentBytes
        if segment_format is None:
            segment_format = os.environ.get(_ENV_FORMAT) or "json"
        segment_format = segment_format.strip().lower()
        if segment_format not in ("json", "compact"):
            from ..utils import log
            log.warning_always(
                "unknown %s %r (json|compact) — using json"
                % (_ENV_FORMAT, segment_format))
            segment_format = "json"
        self.format = segment_format
        self.segment_bytes = max(int(segment_bytes), 1)
        self.stage_events = max(int(stage_events or kStreamStageEvents), 1)
        self.max_pending = max(int(max_pending or kStreamMaxPending), 1)
        self._staging: List[dict] = []
        self._pending: List[List[dict]] = []
        self._cond = threading.Condition()
        self._busy = False
        self._io = threading.Lock()
        self._lines: List[str] = []
        self._bytes = 0
        self._enc: Optional[_compact.SegmentEncoder] = None
        self._seq = 0
        self._seq_resumed = False
        self.events_emitted = 0
        self.dropped = 0
        self._thread: Optional[threading.Thread] = None
        os.makedirs(dirpath, exist_ok=True)

    # -- hot path (caller holds the module _lock) -----------------------
    def push(self, ev: dict) -> None:
        self._staging.append(ev)
        self.events_emitted += 1
        if len(self._staging) >= self.stage_events:
            self._hand_off()

    def _hand_off(self) -> None:
        chunk, self._staging = self._staging, []
        if not chunk:
            return
        with self._cond:
            if len(self._pending) >= self.max_pending:
                self.dropped += len(chunk)
                registry.inc("trace/dropped_events", len(chunk))
                return
            self._pending.append(chunk)
            self._ensure_thread()
            self._cond.notify()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="obs-trace-spool", daemon=True)
            self._thread.start()

    # -- writer ---------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending:
                    self._cond.wait()
                chunk = self._pending.pop(0)
                self._busy = True
            try:
                self._write_chunk(chunk)
            except Exception:
                pass  # a full disk must not kill the writer
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _write_chunk(self, chunk: List[dict]) -> None:
        with self._io:
            if self.format == "compact":
                # incremental binary encode: the open segment's memory
                # cost is its (already final) encoded bytes, same bound
                # as the JSON line list
                if self._enc is None:
                    self._enc = _compact.SegmentEncoder()
                for ev in chunk:
                    self._enc.add_event(ev)
                if self._enc.encoded_size >= self.segment_bytes:
                    self._finalize_io_locked()
                return
            for ev in chunk:
                line = json.dumps(ev)
                self._lines.append(line)
                self._bytes += len(line) + 1
            if self._bytes >= self.segment_bytes:
                self._finalize_io_locked()

    def _finalize_io_locked(self) -> None:
        """Write the open segment as one complete Chrome-trace file.
        Caller holds ``_io``; takes the module ``_lock`` only for the
        lane-name snapshot (never the reverse order — push under
        ``_lock`` touches only staging/backlog)."""
        compact = self.format == "compact"
        n_payload = (self._enc.n_events if compact and self._enc
                     else len(self._lines))
        if not n_payload:
            return
        pid = process_index()
        if not self._seq_resumed:
            # continue after any segments already in the directory for
            # this rank (a restarted run, or a re-configured spool):
            # on-disk segments are evidence and must never be
            # overwritten. Deferred to first finalize — the rank may
            # be pinned (dtrain) after the spool is constructed. Both
            # extensions count: a run restarted with the other format
            # must not reuse a live sequence number.
            self._seq_resumed = True
            prefix = "segment-r%d-" % pid
            try:
                for f in os.listdir(self.dir):
                    if not f.startswith(prefix):
                        continue
                    stem = f[len(prefix):]
                    for ext in (".json", _compact.EXTENSION):
                        if stem.endswith(ext):
                            try:
                                seq = int(stem[:-len(ext)])
                            except ValueError:
                                break
                            self._seq = max(self._seq, seq + 1)
                            break
            except OSError:
                pass
        with _lock:
            lanes = dict(_lane_names)
        meta_events = _metadata_events(lanes, pid)
        other = {"trace_id": trace_id(), "host": socket.gethostname(),
                 "os_pid": os.getpid(), "process_index": pid,
                 "run_id": _events.run_id(),
                 "segment_index": self._seq, "events": n_payload,
                 "dropped_events": self.dropped,
                 "producer": "lightgbm_tpu/obs/trace.py"}
        if compact:
            other["format"] = "compact"
            name = "segment-r%d-%05d%s" % (pid, self._seq,
                                           _compact.EXTENSION)
            # lane metadata is only known at finalize; it appends after
            # the payload records (read_segment restores meta-first
            # ordering on decode)
            for m in meta_events:
                self._enc.add_event(m)
            body = self._enc.segment_bytes(other)
            mode = "wb"
        else:
            meta = [json.dumps(m) for m in meta_events]
            name = "segment-r%d-%05d.json" % (pid, self._seq)
            body = ('{"traceEvents":[' + ",".join(meta + self._lines)
                    + '],"displayTimeUnit":"ms","otherData":'
                    + json.dumps(other) + "}")
            mode = "w"
        path = os.path.join(self.dir, name)

        def _write():
            faults.check("trace_finalize", segment=name)
            tmp = path + ".tmp"
            with open(tmp, mode) as f:
                f.write(body)
            os.replace(tmp, path)

        # telemetry must never take training down: a segment whose
        # finalize fails even after the bounded retries is DROPPED
        # (counted like a backlog overflow) and the spool stays alive
        from ..utils.retry import retry_call
        try:
            retry_call(_write, site="trace_finalize")
        except Exception:
            self.dropped += n_payload
            registry.inc("trace/dropped_events", n_payload)
            self._lines = []
            self._bytes = 0
            self._enc = None
            return
        self._seq += 1
        self._lines = []
        self._bytes = 0
        self._enc = None
        registry.inc("trace/segments_written")

    # -- flush ----------------------------------------------------------
    def flush(self, timeout: float = 10.0) -> None:
        """Drain staging + writer backlog, then finalize the partial
        tail segment. Never raises."""
        try:
            with _lock:
                self._hand_off()
            deadline = time.perf_counter() + timeout
            with self._cond:
                while self._pending or self._busy:
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cond.wait(timeout=min(left, 0.1))
            with self._io:
                self._finalize_io_locked()
        except Exception:
            pass


def _base_args(span_id: int = 0, parent: int = 0) -> dict:
    args = {"trace_id": trace_id()}
    if span_id:
        args["span_id"] = span_id
    if parent:
        args["parent_span_id"] = parent
    return args


# ----------------------------------------------------------------------
# registry scope hooks (the span stack)
# ----------------------------------------------------------------------

class _Hooks:
    """Installed into obs.registry so StageTimer.scope opens/closes
    spans without the registry importing this module."""

    @staticmethod
    def active() -> bool:
        return active()

    @staticmethod
    def begin(name: str):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        span_id = next(_span_seq)
        parent = stack[-1] if stack else 0
        stack.append(span_id)
        return (name, span_id, parent, _now_us())

    @staticmethod
    def end(token) -> None:
        name, span_id, parent, t0 = token
        stack = getattr(_tls, "stack", None)
        if stack:
            # normally a plain pop; sweep on mismatch so one leaked
            # scope cannot corrupt every later parent link
            if stack[-1] == span_id:
                stack.pop()
            elif span_id in stack:
                del stack[stack.index(span_id):]
        _push({"name": name, "ph": "X", "ts": t0,
               "dur": max(_now_us() - t0, 0.001),
               "pid": process_index(), "tid": _thread_lane(),
               "cat": "stage", "args": _base_args(span_id, parent)})

    @staticmethod
    def current_span() -> int:
        """Span id open on the calling thread (0 = none) — the token
        the readiness drainer carries so a ``::ready`` span lands on
        the exact span that submitted the watch, not on whichever
        span a FIFO pairing happened to be processing."""
        stack = getattr(_tls, "stack", None)
        return stack[-1] if stack else 0

    @staticmethod
    def ready_span(name: str, t0_perf: float, t1_perf: float,
                   queued_s: float = 0.0, for_span: int = 0) -> None:
        """Device-readiness span from the registry's async drainer.
        One lane PER STREAM (stage name): concurrent stages resolve on
        separate drainer threads, so their spans may overlap in time —
        distinct lanes keep the per-lane nesting invariant intact."""
        span_id = next(_span_seq)
        args = _base_args(span_id, parent=for_span)
        args["queued_ms"] = round(queued_s * 1e3, 3)
        _push({"name": name + "::ready", "ph": "X",
               "ts": _perf_to_us(t0_perf),
               "dur": max((t1_perf - t0_perf) * 1e6, 0.001),
               "pid": process_index(),
               "tid": _lane((kReadyLane, name), kReadyLane + ":" + name),
               "cat": "ready", "args": args})


_install_trace_hooks(_Hooks)


# ----------------------------------------------------------------------
# event tap (events.emit → instant events / compile spans)
# ----------------------------------------------------------------------

def _note_event(rec: dict) -> None:
    if rec.get("event") == "jit_trace":
        # render the Python-trace window as a span on the compile lane;
        # cost_analysis fields captured by obs/compile.py ride in args.
        # Deferred replays carry ended_ts — the trace really finished
        # back then, so the span is placed at its true time
        dur = max(float(rec.get("trace_seconds", 0.0)) * 1e6, 0.001)
        end_us = float(rec.get("ended_ts") or rec.get("ts") or 0.0) * 1e6
        if not end_us:
            end_us = _now_us()
        args = _base_args(next(_span_seq))
        for k in ("fn", "count", "trace_seconds", "flops",
                  "bytes_accessed", "bytes_per_flop", "hlo_bytes"):
            if k in rec:
                args[k] = rec[k]
        # per-thread compile lane: concurrent traces (serve worker vs
        # trainer) must not partially overlap on one lane
        _push({"name": "jit::%s" % rec.get("fn", "?"), "ph": "X",
               "ts": end_us - dur, "dur": dur,
               "pid": process_index(),
               "tid": _lane((kCompileLane, threading.get_ident()),
                            kCompileLane),
               "cat": "compile", "args": args})
        return
    args = _base_args()
    for k, v in rec.items():
        # run_id is per-run constant — it lives once in the segment's
        # otherData, not on every instant event
        if k not in ("ts", "event", "run_id"):
            args[k] = v
    stack = getattr(_tls, "stack", None)
    if stack:
        args["parent_span_id"] = stack[-1]
    _push({"name": rec.get("event", "?"), "ph": "i", "ts": _now_us(),
           "s": "t", "pid": process_index(), "tid": _thread_lane(),
           "cat": "event", "args": args})


_events.install_trace_tap(active, _note_event)


# ----------------------------------------------------------------------
# counters / device memory gauges
# ----------------------------------------------------------------------

def counter(name: str, values: Dict[str, float]) -> None:
    """Chrome counter track (rendered as a stacked area in Perfetto)."""
    if not active() or not values:
        return
    _push({"name": name, "ph": "C", "ts": _now_us(),
           "pid": process_index(), "tid": 0, "args": dict(values)})


def record_device_memory(reg=registry) -> Dict[str, float]:
    """Per-iteration HBM gauges: ``device.memory_stats()`` peak /
    in-use bytes where the backend reports them (TPU/GPU), live-buffer
    count fallback otherwise (the CPU backend returns None). Lands in
    the registry's gauges and, when tracing, on a counter track."""
    out: Dict[str, float] = {}
    try:
        import jax
        dev = jax.devices()[0]
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            for src, dst in (("bytes_in_use", "device/bytes_in_use"),
                             ("peak_bytes_in_use",
                              "device/peak_bytes_in_use"),
                             ("bytes_limit", "device/bytes_limit")):
                if src in stats:
                    val = float(stats[src])
                    reg.gauge(dst, val)
                    out[dst] = val
        else:
            n = float(len(jax.live_arrays()))
            reg.gauge("device/live_buffers", n)
            out["device/live_buffers"] = n
    except Exception:
        return out
    if out:
        counter("device_memory", out)
    return out


# obs.export resolved once (same rule as compile.py's _get_trace):
# sample_iteration runs once per boosting iteration and must not pay
# import machinery per call
_export_mod = None


def _get_export():
    global _export_mod
    if _export_mod is None:
        from . import export
        _export_mod = export
    return _export_mod


_profiler_session = None  # None = not started, True = live, False = failed


def maybe_start_profiler_session(reg=registry) -> bool:
    """Optional ``jax.profiler`` device-trace session riding sample
    mode: with ``LIGHTGBM_TPU_TIMETAG=sample`` and
    ``LIGHTGBM_TPU_PROFILE_DIR=<logdir>`` set, the first sampled
    iteration starts one trace session (stopped atexit) — the stage
    scopes' TraceAnnotations then attribute device kernels to the same
    stage names in TensorBoard/Perfetto, with zero hot-path fences."""
    global _profiler_session
    if _profiler_session is not None:
        return _profiler_session is True
    logdir = os.environ.get("LIGHTGBM_TPU_PROFILE_DIR")
    if not logdir or not reg.timer.sampling:
        return False
    try:
        from .registry import start_device_trace, stop_device_trace
        start_device_trace(logdir)
        _profiler_session = True

        def _stop():
            try:
                stop_device_trace()
            except Exception:
                pass
        atexit.register(_stop)
        return True
    except Exception:
        _profiler_session = False
        return False


def sample_iteration(iter_idx: int, reg=registry) -> None:
    """Per-iteration telemetry hook for the boosting drivers: device
    memory gauges (+ the optional profiler session) only under the
    explicit profiling modes — TIMETAG fencing/sample or an active span
    trace. Programmatic ``registry.enable()`` alone (the bench's
    aggregate timing) skips it: the live-buffer fallback walks every
    live array, which would perturb the measured loop. Cheap no-op when
    off — safe on the hot path. Also the training-side tick for the
    metrics snapshot exporter + SLO watchdogs (obs/export.py), which
    gate themselves on their own env/config."""
    _get_export().tick(reg)
    if not (reg.timer.sampling or reg.fence() or active()):
        return
    maybe_start_profiler_session(reg)
    record_device_memory(reg)


# ----------------------------------------------------------------------
# export
# ----------------------------------------------------------------------

def _metadata_events(lanes: Dict[int, str], pid: int) -> List[dict]:
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": "rank %d (%s)"
                      % (pid, socket.gethostname())}},
            {"name": "process_sort_index", "ph": "M", "pid": pid,
             "tid": 0, "args": {"sort_index": pid}}]
    for lane, name in sorted(lanes.items()):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": lane, "args": {"name": name}})
    return meta


def flush() -> None:
    """Drain in-flight readiness watches, then write the sink: in
    streaming mode, spool the staged events and finalize the partial
    tail segment; in single-file mode, (re)write the whole Chrome-trace
    JSON. Never raises — telemetry must not take the caller down."""
    if stream_dir() is not None:
        sp = _spool
        try:
            registry.drain_ready(timeout=5.0)
        except Exception:
            pass
        if sp is not None:
            sp.flush()
        return
    path = sink_path()
    if path is None:
        return
    try:
        registry.drain_ready(timeout=5.0)
        with _lock:
            if not _events_buf:
                return
            pid = process_index()
            evs = (_metadata_events(dict(_lane_names), pid)
                   + list(_events_buf))
            dropped = _dropped
        doc = {"traceEvents": evs,
               "displayTimeUnit": "ms",
               "otherData": {"trace_id": trace_id(),
                             "host": socket.gethostname(),
                             "os_pid": os.getpid(),
                             "process_index": pid,
                             "run_id": _events.run_id(),
                             "dropped_events": dropped,
                             "producer": "lightgbm_tpu/obs/trace.py"}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except Exception:
        pass


atexit.register(flush)
