"""Deterministic fault injection — the substrate for chaos tests.

Every failure mode the fault-tolerant plane claims to survive needs a
way to be PROVOKED on demand, deterministically, in-process or from the
environment. This module provides named injection sites at the I/O and
staging boundaries a long run crosses:

================== ====================================================
site               fires inside
================== ====================================================
shard_open         ShardedBinnedDataset.shard_bins_host (memmap open)
prefetch_device_put ShardPrefetcher worker staging (jax.device_put)
spill_write        sharded construction shard spill (np.save)
trace_finalize     streaming trace segment finalize (obs/trace.py)
metrics_dump       OpenMetrics snapshot dump (obs/export.py)
registry_swap      serve ModelRegistry.publish AND canary promote
checkpoint_finalize ft/checkpoint.py directory finalize (rename)
serve_admit        PredictServer.submit admission (request intake)
serve_dispatch     PredictServer worker dispatch (predictor.predict)
gateway_push       SnapshotPusher metrics POST (obs/gateway.py)
================== ====================================================

A schedule is a ``;``-separated spec string (``LIGHTGBM_TPU_FAULTS``
env var, or :func:`configure` programmatically)::

    site:mode[:arg[:ERRNO[:seed]]]

with ``mode`` one of ``nth`` (fail exactly the arg-th call, 1-based),
``once`` (first call only), ``always`` (every call), or ``prob`` (each
call independently with probability arg, drawn from a RandomState
seeded by ``seed`` — the same spec replays the same firing pattern).
``ERRNO`` names the errno of the raised :class:`InjectedFault`
(default EIO); e.g. ``spill_write:nth:2:ENOSPC`` makes the second
shard spill hit a full disk.

:func:`check` raises :class:`InjectedFault` — an ``OSError`` subclass,
so production retry/degradation code handles injected and real
failures through exactly the same paths — and first emits a
``fault_injected`` event (flushed: the evidence must survive whatever
the fault takes down) plus the ``ft/faults_injected`` counter. With no
schedule configured a check is one dict lookup + one env read: cheap
enough to sit on staging paths permanently.
"""
from __future__ import annotations

import errno as _errno
import os
import threading
from typing import Dict, List, Optional, Union

import numpy as np

from ..utils import log
from . import events
from .registry import registry

_ENV = "LIGHTGBM_TPU_FAULTS"

SITES = ("shard_open", "prefetch_device_put", "spill_write",
         "trace_finalize", "metrics_dump", "registry_swap",
         "checkpoint_finalize", "serve_admit", "serve_dispatch",
         "gateway_push")


class InjectedFault(OSError):
    """An injected failure; an OSError (with errno) so call sites treat
    it exactly like the real thing."""


class _Spec:
    __slots__ = ("site", "mode", "arg", "errno_no", "errno_name",
                 "seed", "calls", "fired", "rng")

    def __init__(self, site: str, mode: str, arg: float,
                 errno_name: str, seed: int):
        if mode not in ("nth", "once", "always", "prob"):
            raise ValueError("unknown fault mode %r" % mode)
        self.site = site
        self.mode = mode
        self.arg = arg
        self.errno_name = errno_name or "EIO"
        self.errno_no = getattr(_errno, self.errno_name, None)
        if self.errno_no is None:
            raise ValueError("unknown errno name %r" % errno_name)
        self.seed = seed
        self.calls = 0
        self.fired = 0
        self.rng = (np.random.RandomState(seed & 0x7FFFFFFF)
                    if mode == "prob" else None)

    def should_fire(self) -> bool:
        """Advance this spec's call counter and decide. Caller holds
        the module lock."""
        self.calls += 1
        if self.mode == "nth":
            hit = self.calls == int(self.arg)
        elif self.mode == "once":
            hit = self.fired == 0
        elif self.mode == "always":
            hit = True
        else:  # prob
            hit = bool(self.rng.random_sample() < self.arg)
        if hit:
            self.fired += 1
        return hit


def parse_spec(text: str) -> List[_Spec]:
    """Parse a ``;``-separated schedule string; raises ValueError on a
    malformed entry (a chaos test with a typoed schedule must not
    silently test nothing)."""
    specs: List[_Spec] = []
    for entry in (text or "").replace(",", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError("fault spec %r needs site:mode" % entry)
        site, mode = parts[0].strip(), parts[1].strip()
        arg_s = parts[2].strip() if len(parts) > 2 else ""
        err_s = parts[3].strip() if len(parts) > 3 else ""
        seed_s = parts[4].strip() if len(parts) > 4 else ""
        if mode in ("nth", "prob"):
            if not arg_s:
                raise ValueError("fault spec %r: mode %r needs an arg"
                                 % (entry, mode))
            arg = float(arg_s)
            if mode == "nth" and arg < 1:
                raise ValueError("fault spec %r: nth arg is 1-based"
                                 % entry)
        else:
            arg = float(arg_s) if arg_s else 0.0
        seed = int(seed_s) if seed_s else 0
        if site not in SITES:
            # a typoed site parses but never fires — a chaos schedule
            # that silently tests nothing. Warn loudly; stay non-fatal
            # so ad-hoc sites (tests, future call sites) keep working
            log.warning_always(
                "fault spec names unknown site %r (wired sites: %s)"
                % (site, ", ".join(SITES)))
        specs.append(_Spec(site, mode, arg, err_s or "EIO", seed))
    return specs


_lock = threading.Lock()
_specs: Dict[str, List[_Spec]] = {}
_override = False        # configure() beats the env var
_env_cached: Optional[str] = None


def configure(spec: Union[str, List[str], None]) -> None:
    """Install a schedule programmatically (a string, a list of spec
    strings, or None to clear and fall back to the env var)."""
    global _specs, _override, _env_cached
    with _lock:
        if spec is None:
            _specs, _override, _env_cached = {}, False, None
            return
        if isinstance(spec, (list, tuple)):
            spec = ";".join(spec)
        parsed = parse_spec(spec)
        _specs = {}
        for s in parsed:
            _specs.setdefault(s.site, []).append(s)
        _override = True


def reset() -> None:
    """Clear every schedule and call counter (tests)."""
    configure(None)


def _current(site: str) -> List[_Spec]:
    """Site's active specs; lazily (re)parses the env schedule whenever
    its value changes, so late ``os.environ`` assignment works like the
    other telemetry env vars."""
    global _specs, _env_cached
    if _override:
        return _specs.get(site, ())
    env = os.environ.get(_ENV) or ""
    if env != _env_cached:
        with _lock:
            if env != _env_cached:
                try:
                    parsed = parse_spec(env)
                except ValueError as e:
                    log.warning_always(
                        "ignoring malformed %s: %s" % (_ENV, e))
                    parsed = []
                _specs = {}
                for s in parsed:
                    _specs.setdefault(s.site, []).append(s)
                _env_cached = env
    return _specs.get(site, ())


def enabled() -> bool:
    return bool(_specs) or bool(os.environ.get(_ENV))


def check(site: str, **ctx) -> None:
    """Fault gate for ``site``: no-op unless a configured spec decides
    this call fails, in which case it emits the (flushed)
    ``fault_injected`` event + counter and raises
    :class:`InjectedFault`."""
    specs = _current(site)
    if not specs:
        return
    for spec in specs:
        with _lock:
            hit = spec.should_fire()
        if not hit:
            continue
        registry.inc("ft/faults_injected")
        registry.inc("ft/faults_injected/" + site)
        events.emit("fault_injected", site=site, call=spec.calls,
                    mode=spec.mode, errno=spec.errno_name,
                    **{k: str(v) for k, v in ctx.items()})
        events.flush()
        raise InjectedFault(
            spec.errno_no,
            "injected fault at %s (call %d, mode %s)"
            % (site, spec.calls, spec.mode))
