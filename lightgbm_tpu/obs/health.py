"""Backend health events: selection, fallback, degradation.

Round-5 evidence (BENCH_r05.json) motivated this module: a silent CPU
fallback — "tpu backend probe failed/timed out (3 attempts)" — whose
only trace was a substring in a free-text unit field. Backend state is
now a first-class, machine-readable event:

- ``backend``          — which platform is actually executing, emitted
  once per process at first training.
- ``backend_fallback`` — a requested accelerator degraded to another
  platform, with the reason; always mirrored as a Warning log line.
"""
from __future__ import annotations

from typing import Optional

from ..utils import log
from . import events
from .registry import registry

_reported = False


def record_backend(platform: Optional[str] = None,
                   source: str = "") -> Optional[str]:
    """Emit the ``backend`` event (platform + device count). With no
    explicit ``platform``, asks jax — safe only once a backend exists.
    Also sets the ``backend`` gauge consumed by bench."""
    n_devices = None
    try:
        import jax
        if platform is None:
            platform = jax.default_backend()
        n_devices = len(jax.devices())
    except Exception:
        if platform is None:
            return None
    global _reported
    _reported = True  # an explicit record IS the process's record
    registry.gauge("backend", platform)
    events.emit("backend", platform=platform, num_devices=n_devices,
                source=source)
    return platform


def record_backend_once(source: str = "") -> None:
    """Process-wide once-only backend record (first training emits)."""
    global _reported
    if _reported:
        return
    _reported = True
    record_backend(source=source)


def record_backend_fallback(reason: str, requested: str = "tpu",
                            actual: str = "cpu") -> None:
    """An accelerator request degraded: Warning log (the reference's
    Log::Warning discipline — degradation is never silent, so the
    verbosity gate is bypassed) + a structured ``backend_fallback``
    event + a counter."""
    log.warning_always("backend fallback: requested %s, running on %s "
                       "(%s)" % (requested, actual, reason))
    registry.inc("backend_fallback")
    events.emit("backend_fallback", requested=requested, actual=actual,
                reason=reason)
    events.flush()  # degradation evidence must survive a crash
