"""Backend health events + SLO watchdogs.

Round-5 evidence (BENCH_r05.json) motivated this module: a silent CPU
fallback — "tpu backend probe failed/timed out (3 attempts)" — whose
only trace was a substring in a free-text unit field. Backend state is
now a first-class, machine-readable event:

- ``backend``          — which platform is actually executing, emitted
  once per process at first training.
- ``backend_fallback`` — a requested accelerator degraded to another
  platform, with the reason; always mirrored as a Warning log line.

:class:`Watchdog` runs threshold rules over the registry snapshot
stream (obs/export.py feeds it one snapshot per exporter tick) and
emits a structured ``health`` event EXACTLY ONCE per breach: a rule
fires on the false→true transition of its condition and re-arms when
the condition clears, so a saturated queue produces one event, not one
per snapshot. Default rules: retrace spike (jit trace-count delta per
interval), backend fallback, serve queue-depth saturation, and trace
drop counters (spool + readiness drainer).
"""
from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

from ..utils import log
from . import events
from .registry import registry

_reported = False


def record_backend(platform: Optional[str] = None,
                   source: str = "") -> Optional[str]:
    """Emit the ``backend`` event (platform + device count). With no
    explicit ``platform``, asks jax — safe only once a backend exists.
    Also sets the ``backend`` gauge consumed by bench."""
    n_devices = None
    try:
        import jax
        if platform is None:
            platform = jax.default_backend()
        n_devices = len(jax.devices())
    except Exception:
        if platform is None:
            return None
    global _reported
    _reported = True  # an explicit record IS the process's record
    registry.gauge("backend", platform)
    events.emit("backend", platform=platform, num_devices=n_devices,
                source=source)
    return platform


def record_backend_once(source: str = "") -> None:
    """Process-wide once-only backend record (first training emits)."""
    global _reported
    if _reported:
        return
    _reported = True
    record_backend(source=source)


def record_backend_fallback(reason: str, requested: str = "tpu",
                            actual: str = "cpu") -> None:
    """An accelerator request degraded: Warning log (the reference's
    Log::Warning discipline — degradation is never silent, so the
    verbosity gate is bypassed) + a structured ``backend_fallback``
    event + a counter."""
    log.warning_always("backend fallback: requested %s, running on %s "
                       "(%s)" % (requested, actual, reason))
    registry.inc("backend_fallback")
    events.emit("backend_fallback", requested=requested, actual=actual,
                reason=reason)
    events.flush()  # degradation evidence must survive a crash


# ----------------------------------------------------------------------
# SLO watchdogs over the snapshot stream
# ----------------------------------------------------------------------

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class WatchRule:
    """One threshold rule: ``check(snapshot, state)`` returns a detail
    dict while the condition holds, else None. ``state`` is a per-rule
    dict the rule may use for counter deltas across snapshots.
    ``component`` names the subsystem whose signal the rule watches —
    it rides on the emitted ``health`` event so a consumer can route a
    breach without parsing the rule name."""

    def __init__(self, name: str,
                 check: Callable[[dict, dict], Optional[dict]],
                 component: str = "obs.health") -> None:
        self.name = name
        self.check = check
        self.component = component


def _counter_delta(snap: dict, state: dict, match, state_key: str,
                   first_is_baseline: bool) -> float:
    """Delta of the summed counters since the previous snapshot. With
    ``first_is_baseline`` the first observation arms the rule without
    firing (retrace watch: warm-up compiles are normal); without it the
    baseline is 0, so pre-existing occurrences fire on first look
    (fallback / drops: already-degraded is still degraded)."""
    counters = snap.get("counters", {})
    total = float(sum(v for k, v in counters.items()
                      if (k in match if isinstance(match, (set, frozenset))
                          else k.startswith(match))))
    if state_key not in state:
        state[state_key] = total if first_is_baseline else 0.0
    delta = total - state[state_key]
    state[state_key] = total
    return delta


def default_rules() -> List[WatchRule]:
    """The stock SLO rules. Thresholds are env-tunable:

    - ``LIGHTGBM_TPU_WATCH_RETRACE_SPIKE`` (default 8): total new jit
      traces between two snapshots at or above this = a retrace storm
      (steady state should re-trace ~never);
    - ``LIGHTGBM_TPU_WATCH_QUEUE_DEPTH`` (default 1024): serve queue
      depth at or above this = admission saturation;
    - ``LIGHTGBM_TPU_WATCH_PREFETCH_STALL`` (default 0.25): share of
      the snapshot window the out-of-core shard prefetcher spent
      stalling the consumer (``io/prefetch_stall_ms`` delta over wall
      time between snapshots) at or above this = a starving loader —
      on a day-long out-of-core run the device is idle that fraction
      of the time waiting for shard bytes;
    - ``LIGHTGBM_TPU_WATCH_RETRY_STORM`` (default 16): total new
      I/O retries (``ft/retries``) plus injected faults per snapshot
      window at or above this = ``fault_storm`` — the run is limping
      on its retry layer (a flaky disk/runtime), act before the
      retries start exhausting;
    - ``LIGHTGBM_TPU_WATCH_SHED_RATE`` (default 0.05): share of the
      window's serve submissions shed by admission control
      (``serve/shed_total`` delta over ``serve/requests`` delta) at or
      above this = sustained overload — capacity, not a blip, is the
      problem (a minimum of 8 sheds per window filters noise);
    - ``serve/breaker_state`` at 2 (open) = ``breaker_open`` — the
      serving worker is failing every dispatch and shedding load by
      design; level-based like queue saturation, re-arms when the
      half-open probe closes it;
    - backend fallback, trace drops, and exhausted retries
      (``retry_exhausted`` — some I/O site gave up after its bounded
      attempts, utils/retry.py) fire on ANY new occurrence;
    - ``refresh_slo`` — the continuous-refresh contract
      (lightgbm_tpu/loop/, docs/REFRESH.md), armed ONLY while the
      ``refresh/active`` gauge is truthy (the RefreshController sets
      it around its loop and evaluates once at arm time to baseline
      the counters): serving p99 during a refresh
      (``refresh/serve_p99_ms`` gauge) at or above
      ``LIGHTGBM_TPU_WATCH_REFRESH_P99_MS`` (default 250), more
      rollbacks in one refresh window than the
      ``LIGHTGBM_TPU_WATCH_REFRESH_ROLLBACKS`` budget (default 1 —
      the chaos schedule's single poisoned canary is expected, a
      second rollback is not), or ANY stranded future
      (``serve/drain_failed`` delta) is a breach.
    """
    retrace_thr = _env_float("LIGHTGBM_TPU_WATCH_RETRACE_SPIKE", 8)
    queue_thr = _env_float("LIGHTGBM_TPU_WATCH_QUEUE_DEPTH", 1024)
    stall_thr = _env_float("LIGHTGBM_TPU_WATCH_PREFETCH_STALL", 0.25)
    storm_thr = _env_float("LIGHTGBM_TPU_WATCH_RETRY_STORM", 16)
    shed_thr = _env_float("LIGHTGBM_TPU_WATCH_SHED_RATE", 0.05)
    # below this much new stall time the share is noise, not starvation
    kMinStallMs = 50.0
    # below this many sheds per window the rate is noise, not overload
    kMinSheds = 8.0

    def retrace_spike(snap, state):
        delta = _counter_delta(snap, state, "jit_trace/", "prev",
                               first_is_baseline=True)
        if delta >= retrace_thr:
            return {"value": delta, "threshold": retrace_thr,
                    "detail": "%d new jit traces in one snapshot "
                              "interval" % delta}
        return None

    def backend_fallback(snap, state):
        delta = _counter_delta(snap, state,
                               frozenset(("backend_fallback",)), "prev",
                               first_is_baseline=False)
        if delta > 0:
            return {"value": delta, "threshold": 1,
                    "detail": "backend fallback recorded"}
        return None

    def queue_saturation(snap, state):
        depth = float(snap.get("gauges", {}).get("serve/queue_depth", 0))
        if depth >= queue_thr:
            return {"value": depth, "threshold": queue_thr,
                    "detail": "serve queue depth saturated"}
        return None

    def trace_drops(snap, state):
        # trace/dropped_events covers both sinks: the streaming
        # spool's backlog-full chunk drops and the bounded single-file
        # buffer's overflow (the per-stream readiness drainer cannot
        # drop — coalescing caps each stream at one in-flight watch)
        delta = _counter_delta(
            snap, state, frozenset(("trace/dropped_events",)),
            "prev", first_is_baseline=False)
        if delta > 0:
            return {"value": delta, "threshold": 1,
                    "detail": "trace events dropped (spool backlog "
                              "full or span buffer overflow)"}
        return None

    def prefetch_stall(snap, state):
        # share of the window the shard consumer sat blocked on
        # staging (io/shards.py ShardPrefetcher counts blocked ms);
        # the first observation arms the baseline — construction-time
        # staging before the first snapshot is not a breach
        now = time.monotonic()
        delta_ms = _counter_delta(
            snap, state, frozenset(("io/prefetch_stall_ms",)), "prev",
            first_is_baseline=True)
        prev_t = state.get("prev_t")
        state["prev_t"] = now
        if prev_t is None or delta_ms < kMinStallMs:
            return None
        window = max(now - prev_t, 1e-9)
        share = (delta_ms / 1000.0) / window
        if share >= stall_thr:
            return {"value": round(min(share, 1.0), 4),
                    "threshold": stall_thr,
                    "detail": "shard prefetcher stalled the consumer "
                              "%.0f ms over a %.1f s window "
                              "(loader starving the device)"
                              % (delta_ms, window)}
        return None

    def retry_exhausted(snap, state):
        # any I/O site that gave up after its bounded attempts is a
        # breach on its own — whatever failure followed (fatal, dropped
        # segment, skipped dump) already happened
        delta = _counter_delta(
            snap, state, frozenset(("ft/retry_exhausted",)), "prev",
            first_is_baseline=False)
        if delta > 0:
            return {"value": delta, "threshold": 1,
                    "detail": "an I/O retry site gave up after its "
                              "bounded attempts"}
        return None

    def fault_storm(snap, state):
        # rate rule (retries + injected faults per window): the first
        # snapshot arms the baseline like retrace_spike — retries that
        # happened before watching started are history, not a storm
        delta = _counter_delta(
            snap, state,
            frozenset(("ft/retries", "ft/faults_injected")), "prev",
            first_is_baseline=True)
        if delta >= storm_thr:
            return {"value": delta, "threshold": storm_thr,
                    "detail": "%d I/O retries/injected faults in one "
                              "snapshot interval (run is limping on "
                              "the retry layer)" % delta}
        return None

    def shed_rate(snap, state):
        # rate rule over the serving plane's admission control: the
        # first snapshot arms both baselines (sheds before watching
        # started are history), then the windowed shed share of
        # submissions is the signal — absolute shed counts grow
        # forever on a healthy server that survived one spike
        shed = _counter_delta(snap, state,
                              frozenset(("serve/shed_total",)),
                              "prev_shed", first_is_baseline=True)
        subs = _counter_delta(snap, state,
                              frozenset(("serve/requests",)),
                              "prev_req", first_is_baseline=True)
        if shed < kMinSheds:
            return None
        share = shed / max(subs, shed, 1.0)
        if share >= shed_thr:
            return {"value": round(share, 4), "threshold": shed_thr,
                    "detail": "admission control shed %d of %d serve "
                              "submissions in one snapshot window "
                              "(sustained overload)" % (shed, subs)}
        return None

    def breaker_open(snap, state):
        # level-based like queue_saturation: one event per open
        # episode, re-arms when the half-open probe closes the
        # breaker. The gauge is a per-model FAMILY
        # (serve/breaker_state/<model>) — the worst state across
        # every breaker is the signal, so one server closing cannot
        # mask another still open
        worst = 0.0
        for k, v in snap.get("gauges", {}).items():
            if k == "serve/breaker_state" \
                    or k.startswith("serve/breaker_state/"):
                try:
                    worst = max(worst, float(v))
                except (TypeError, ValueError):
                    continue
        if worst >= 2:
            return {"value": worst, "threshold": 2,
                    "detail": "a serve circuit breaker is OPEN — every "
                              "dispatch is failing and submits are "
                              "being rejected fast"}
        return None

    refresh_p99_thr = _env_float("LIGHTGBM_TPU_WATCH_REFRESH_P99_MS",
                                 250)
    refresh_rb_budget = _env_float(
        "LIGHTGBM_TPU_WATCH_REFRESH_ROLLBACKS", 1)

    def refresh_slo(snap, state):
        # the closed-loop refresh contract: armed only while the
        # refresh/active gauge is up. Counter baselines keep tracking
        # while idle, so history before a refresh window can never
        # fire; the per-window rollback accumulator resets when the
        # window closes.
        gauges = snap.get("gauges", {})
        rb = _counter_delta(snap, state,
                            frozenset(("serve/rollbacks",)),
                            "prev_rb", first_is_baseline=True)
        stranded = _counter_delta(snap, state,
                                  frozenset(("serve/drain_failed",)),
                                  "prev_drain", first_is_baseline=True)
        if not gauges.get("refresh/active"):
            state.pop("rb_window", None)
            return None
        state["rb_window"] = state.get("rb_window", 0.0) + rb
        if stranded > 0:
            return {"value": stranded, "threshold": 1,
                    "detail": "%d futures stranded by a server drain "
                              "during a refresh window" % stranded}
        if state["rb_window"] > refresh_rb_budget:
            return {"value": state["rb_window"],
                    "threshold": refresh_rb_budget,
                    "detail": "%d canary rollbacks in one refresh "
                              "window exceed the budget of %d"
                              % (state["rb_window"], refresh_rb_budget)}
        p99 = float(gauges.get("refresh/serve_p99_ms", 0.0))
        if p99 >= refresh_p99_thr:
            return {"value": round(p99, 3),
                    "threshold": refresh_p99_thr,
                    "detail": "serving p99 %.1f ms during a refresh "
                              "window (SLO %.0f ms)"
                              % (p99, refresh_p99_thr)}
        return None

    # ---- data/model quality rules (obs/quality.py gauges) ------------
    psi_thr = _env_float("LIGHTGBM_TPU_WATCH_PSI", 0.25)
    score_psi_thr = _env_float("LIGHTGBM_TPU_WATCH_SCORE_PSI", 0.25)
    label_psi_thr = _env_float("LIGHTGBM_TPU_WATCH_LABEL_PSI", 0.25)
    edge_thr = _env_float("LIGHTGBM_TPU_WATCH_EDGE_MASS", 0.10)
    edge_windows = _env_float("LIGHTGBM_TPU_WATCH_EDGE_WINDOWS", 3)

    def feature_drift(snap, state):
        # level rule over the drained drift window: worst per-feature
        # PSI at or above LIGHTGBM_TPU_WATCH_PSI (default 0.25, the
        # classic "distribution has shifted" PSI rule of thumb); fires
        # once per breach episode, re-arms when a window scores clean
        gauges = snap.get("gauges", {})
        v = float(gauges.get("quality/psi_max", 0.0))
        if v < psi_thr:
            return None
        worst, worst_v = "?", -1.0
        for k, g in gauges.items():
            if k.startswith("quality/psi/feature/"):
                try:
                    g = float(g)
                except (TypeError, ValueError):
                    continue
                if g > worst_v:
                    worst, worst_v = k.rsplit("/", 1)[1], g
        return {"value": round(v, 4), "threshold": psi_thr,
                "feature": worst,
                "detail": "serving-input drift: PSI %.3f on feature %s "
                          "(threshold %.2f)" % (v, worst, psi_thr)}

    def prediction_drift(snap, state):
        v = float(snap.get("gauges", {}).get("quality/score_psi", 0.0))
        if v >= score_psi_thr:
            return {"value": round(v, 4), "threshold": score_psi_thr,
                    "detail": "prediction-score drift: PSI %.3f vs the "
                              "training-score histogram (threshold "
                              "%.2f)" % (v, score_psi_thr)}
        return None

    def label_drift(snap, state):
        v = float(snap.get("gauges", {}).get("quality/label_psi", 0.0))
        if v >= label_psi_thr:
            return {"value": round(v, 4), "threshold": label_psi_thr,
                    "detail": "label drift: PSI %.3f vs the training "
                              "label histogram (threshold %.2f)"
                              % (v, label_psi_thr)}
        return None

    def retrain_required(snap, state):
        # sustained mass in the grid's catch-all edge bins means the
        # frozen bin boundaries no longer cover the data: a refresh
        # (refit/resume on the same mappers) cannot fix that — only a
        # full retrain (new spill, new mappers) can. Counted per
        # DRAINED window (quality/windows delta), needs
        # LIGHTGBM_TPU_WATCH_EDGE_WINDOWS consecutive breaching
        # windows so one weird batch cannot demand a retrain
        counters = snap.get("counters", {})
        wins = float(counters.get("quality/windows", 0.0))
        prev = state.get("prev_windows")
        state["prev_windows"] = wins
        if prev is not None and wins > prev:
            em = float(snap.get("gauges", {})
                       .get("quality/edge_mass", 0.0))
            state["streak"] = state.get("streak", 0) + 1 \
                if em >= edge_thr else 0
            state["last_em"] = em
        if state.get("streak", 0) >= edge_windows:
            return {"value": round(state.get("last_em", 0.0), 4),
                    "threshold": edge_thr,
                    "windows": state["streak"],
                    "detail": "%.0f%% excess mass in overflow/edge "
                              "bins for %d consecutive windows — the "
                              "frozen bin boundaries no longer cover "
                              "the data; refresh cycles cannot fix "
                              "this, schedule a full retrain (new "
                              "spill, new mappers)"
                              % (100 * state.get("last_em", 0.0),
                                 state["streak"])}
        return None

    return [WatchRule("retrace_spike", retrace_spike),
            WatchRule("backend_fallback", backend_fallback),
            WatchRule("queue_saturation", queue_saturation),
            WatchRule("trace_drops", trace_drops),
            WatchRule("prefetch_stall", prefetch_stall),
            WatchRule("retry_exhausted", retry_exhausted),
            WatchRule("fault_storm", fault_storm),
            WatchRule("shed_rate", shed_rate),
            WatchRule("breaker_open", breaker_open),
            WatchRule("refresh_slo", refresh_slo),
            WatchRule("feature_drift", feature_drift,
                      component="obs.quality"),
            WatchRule("prediction_drift", prediction_drift,
                      component="obs.quality"),
            WatchRule("label_drift", label_drift,
                      component="obs.quality"),
            WatchRule("retrain_required", retrain_required,
                      component="obs.quality")]


def fleet_rules() -> List[WatchRule]:
    """Watchdog rules over the GATEWAY's aggregated fleet snapshot
    (``obs.gateway.MetricsGateway.fleet_snapshot``: one entry per
    pushing (rank, process) source with push age + pre-extracted
    aggregates), evaluated at the gateway on every push and every
    ``/healthz`` scrape. Same :class:`Watchdog` once-per-breach +
    re-arm contract as the per-process rules. Thresholds:

    - ``LIGHTGBM_TPU_WATCH_RANK_SKEW`` (default 2.0): slowest/fastest
      rank ratio of summed stage seconds at or above this = one rank
      is dragging the synchronous collective loop (every other rank
      waits at the allreduce — the whole fleet runs at the straggler's
      speed); needs ≥ 2 reporting ranks and ≥ 1 s on the slowest so
      warm-up noise can't fire it;
    - ``LIGHTGBM_TPU_WATCH_PUSH_STALE_S`` (default 30): a source whose
      last push is at least this old = ``dead_rank`` — the process is
      hung, partitioned, or gone; level-based, re-arms when pushes
      resume (a ``/healthz`` scrape is also an evaluation tick, since
      a dead rank by definition stops generating push evaluations);
    - ``LIGHTGBM_TPU_WATCH_SHED_RATE`` (default 0.05, shared with the
      per-process rule): fleet-wide windowed shed share of serve
      submissions summed ACROSS sources at or above this =
      ``fleet_shed_rate`` — the fleet as a whole is overloaded even
      if no single replica's local rate trips its own rule.
    """
    skew_thr = _env_float("LIGHTGBM_TPU_WATCH_RANK_SKEW", 2.0)
    shed_thr = _env_float("LIGHTGBM_TPU_WATCH_SHED_RATE", 0.05)
    # below this much stage time on the SLOWEST rank, ratios are
    # warm-up noise, not skew
    kMinStageSeconds = 1.0
    kMinSheds = 8.0

    def _ranks(snap):
        return (snap.get("fleet") or {}).get("ranks") or {}

    def rank_skew(snap, state):
        # per RANK, not per source: a rank's train + serve processes
        # both push, and stage seconds belong to the rank they ran on
        per_rank: Dict[str, float] = {}
        for e in _ranks(snap).values():
            r = str(e.get("rank", "?"))
            per_rank[r] = per_rank.get(r, 0.0) \
                + float(e.get("stage_seconds", 0.0))
        per_rank = {r: s for r, s in per_rank.items() if s > 0.0}
        if len(per_rank) < 2:
            return None
        slow_r = max(per_rank, key=per_rank.get)
        fast_r = min(per_rank, key=per_rank.get)
        slowest, fastest = per_rank[slow_r], per_rank[fast_r]
        if slowest < kMinStageSeconds:
            return None
        ratio = slowest / max(fastest, 1e-9)
        if ratio >= skew_thr:
            return {"value": round(ratio, 3), "threshold": skew_thr,
                    "detail": "rank %s spent %.1fx the stage seconds "
                              "of rank %s (%.2fs vs %.2fs) — the "
                              "collective loop runs at the "
                              "straggler's speed"
                              % (slow_r, ratio, fast_r,
                                 slowest, fastest)}
        return None

    def dead_rank(snap, state):
        fleet = snap.get("fleet") or {}
        stale_after = float(fleet.get("stale_after_s", 30.0))
        stale = {k: float(e.get("age_s", 0.0))
                 for k, e in _ranks(snap).items()
                 if float(e.get("age_s", 0.0)) >= stale_after}
        if stale:
            worst = max(stale.values())
            return {"value": round(worst, 3), "threshold": stale_after,
                    "detail": "no push from %s for %.1fs (stale after "
                              "%.0fs) — hung, partitioned, or dead"
                              % (", ".join(sorted(stale)), worst,
                                 stale_after)}
        return None

    def fleet_shed_rate(snap, state):
        # windowed like the per-process shed_rate: first observation
        # arms the baselines, then the fleet-summed deltas are the
        # signal (cumulative counters grow forever on a healthy fleet
        # that survived one spike)
        shed = sum(float(e.get("shed_total", 0.0))
                   for e in _ranks(snap).values())
        reqs = sum(float(e.get("requests", 0.0))
                   for e in _ranks(snap).values())
        if "prev_shed" not in state:
            state["prev_shed"], state["prev_req"] = shed, reqs
            return None
        d_shed = shed - state["prev_shed"]
        d_req = reqs - state["prev_req"]
        state["prev_shed"], state["prev_req"] = shed, reqs
        if d_shed < kMinSheds:
            return None
        share = d_shed / max(d_req, d_shed, 1.0)
        if share >= shed_thr:
            return {"value": round(share, 4), "threshold": shed_thr,
                    "detail": "the fleet shed %d of %d serve "
                              "submissions in one push window "
                              "(fleet-wide overload)"
                              % (d_shed, d_req)}
        return None

    return [WatchRule("rank_skew", rank_skew),
            WatchRule("dead_rank", dead_rank),
            WatchRule("fleet_shed_rate", fleet_shed_rate)]


class Watchdog:
    """Evaluate threshold rules over successive registry snapshots,
    emitting one ``health`` event per breach (false→true transition;
    the rule re-arms when its condition clears). Each firing also
    increments the ``health/<rule>`` counter, so breaches are visible
    in the very /metrics stream being watched."""

    def __init__(self, reg=registry,
                 rules: Optional[List[WatchRule]] = None) -> None:
        self.reg = reg
        self.rules = rules if rules is not None else default_rules()
        self._state: Dict[str, dict] = {}
        self._breached: Dict[str, bool] = {}
        self._last_fired: Dict[str, dict] = {}

    def evaluate(self, snapshot: Optional[dict] = None) -> List[dict]:
        """Run every rule against ``snapshot`` (default: a fresh
        ``reg.snapshot()``); returns the list of NEW breaches fired
        this evaluation. Never raises."""
        if snapshot is None:
            snapshot = self.reg.snapshot()
        fired: List[dict] = []
        for rule in self.rules:
            try:
                detail = rule.check(snapshot,
                                    self._state.setdefault(rule.name, {}))
            except Exception:
                continue
            breached = detail is not None
            if breached and not self._breached.get(rule.name, False):
                rec = dict(rule=rule.name, severity="warning",
                           component=getattr(rule, "component",
                                             "obs.health"),
                           **detail)
                self._last_fired[rule.name] = rec
                fired.append(rec)
                self.reg.inc("health/" + rule.name)
                log.warning("health watchdog: %s — %s"
                            % (rule.name, detail.get("detail", "")))
                events.emit("health", **rec)
            self._breached[rule.name] = breached
        if fired:
            events.flush()  # breach evidence must survive a crash
        return fired

    def breached(self) -> List[dict]:
        """Rules currently in breach (for /healthz)."""
        return [self._last_fired[name]
                for name, b in sorted(self._breached.items())
                if b and name in self._last_fired]
