"""Data & model quality plane: binned drift detection on the serving
path, reference profiles captured at training/spill time, and the drift
scores that gate the refresh loop.

The whole layer rides the paper's histogram substrate: every row is
already quantized into <=255 integer bins at training time (``BinMapper``,
io/binning.py) and at serving time (on-device quantization against the
model's own threshold grid, ops/predict.py). Distribution monitoring is
therefore a small ``[F, B]`` count reduction over arrays the hot path
already computes — the same economy the GPU boosting line exploits for
split finding, applied to watching the data instead of splitting it.

Three pieces:

- :class:`ReferenceProfile` — per-feature bin-count histograms over the
  TRAINING grid (incl. NaN/zero/categorical sentinel mass), a label
  histogram, and (added at checkpoint time) a prediction-score
  histogram. Captured during the spill pass by :class:`ProfileBuilder`
  via one jitted device reduction per shard, serialized into the spill
  manifest (io/shards.py) and the checkpoint dir (ft/checkpoint.py) so
  ``attach``/resume reload it.
- :class:`QualityMonitor` — live serving-side accumulation: per-chunk
  windowed per-feature bin counts kept ON DEVICE (one extra scatter-add
  per dispatched chunk, explicit transfers only, zero per-batch host
  read-back) plus host-side score/label histograms. Replica-safe the
  same way PR 11's bucket dict is: one shared state dict, one lock.
- drift math — :func:`psi` and :func:`js_divergence` over count
  vectors, computed host-side only at the exporter tick (``drain``),
  published as ``quality/...`` gauges that obs/export.py folds into
  ``{feature=}``-labeled OpenMetrics families and obs/health.py watches
  (``feature_drift`` / ``prediction_drift`` / ``label_drift`` /
  ``retrain_required``).

Grid note: the serving grid (model thresholds) is a *coarsening* of the
training grid — every numeric model threshold is one of the feature's
``bin_upper_bound`` values (serve/forest.py), so the training-grid
reference projects onto the serving grid by sending each training bin's
representative value (its midpoint) through ``searchsorted`` once, on
the host, at monitor-construction time. The monitor's quantizer is
pinned to the model it was built against: drift is always measured on
one fixed grid even while refresh cycles publish new leaf values.
"""
from __future__ import annotations

import json
import math
import threading
from typing import Dict, List, Optional

import numpy as np

from . import compile as obs_compile
from . import events as obs_events
from .registry import registry as obs_registry

kScoreBins = 32      # fixed-width prediction-score histogram bins
kLabelBins = 32      # fixed-width label histogram bins
kEpsilon = 1e-4      # probability floor for PSI/JS smoothing

__all__ = [
    "psi", "js_divergence", "fixed_histogram", "histogram_edges",
    "ReferenceProfile", "ProfileBuilder", "QualityMonitor",
    "register_monitor", "unregister_monitor", "drain_all",
]


# ---------------------------------------------------------------------------
# drift math (host-side, f64, over small count vectors)
# ---------------------------------------------------------------------------

def _smooth(counts: np.ndarray, eps: float) -> Optional[np.ndarray]:
    """Counts -> probabilities with an ``eps`` floor (so empty bins in
    either distribution cannot blow up the logs). Returns None for an
    all-zero vector — the caller treats that window/profile as absent
    rather than inventing a uniform distribution."""
    c = np.asarray(counts, dtype=np.float64).ravel()
    total = c.sum()
    if not np.isfinite(total) or total <= 0:
        return None
    p = c / total
    p = np.clip(p, eps, None)
    return p / p.sum()


def psi(ref_counts, live_counts, eps: float = kEpsilon) -> float:
    """Population Stability Index between two count vectors over the
    same bin grid: ``sum((q - p) * ln(q / p))`` with ``eps``-floored
    probabilities (f64). 0 = identical; common rules of thumb flag
    ~0.1 as drifting and ~0.25 as shifted. Returns 0.0 when either
    side is empty (no evidence is not drift)."""
    p = _smooth(ref_counts, eps)
    q = _smooth(live_counts, eps)
    if p is None or q is None:
        return 0.0
    return float(np.sum((q - p) * np.log(q / p)))


def js_divergence(ref_counts, live_counts, eps: float = 1e-12) -> float:
    """Jensen-Shannon divergence (base-2 logs, so the result lives in
    [0, 1]) between two count vectors over the same grid. Symmetric and
    bounded, which makes it the cross-feature-comparable companion to
    the unbounded PSI. Returns 0.0 when either side is empty."""
    p = _smooth(ref_counts, eps)
    q = _smooth(live_counts, eps)
    if p is None or q is None:
        return 0.0
    m = 0.5 * (p + q)

    def _kl(a, b):
        mask = a > 0
        return float(np.sum(a[mask] * np.log2(a[mask] / b[mask])))

    return 0.5 * _kl(p, m) + 0.5 * _kl(q, m)


def histogram_edges(values: np.ndarray, bins: int) -> List[float]:
    """``bins - 1`` inner edges spanning the finite values (10% margin
    each side, so near-boundary mass on later windows lands inside
    rather than in the overflow lanes). Degenerate spans widen to +-1."""
    v = np.asarray(values, dtype=np.float64).ravel()
    v = v[np.isfinite(v)]
    if v.size == 0:
        lo, hi = 0.0, 1.0
    else:
        lo, hi = float(v.min()), float(v.max())
    span = hi - lo
    if span <= 0:
        span = max(abs(hi), 1.0)
        lo, hi = lo - span, hi + span
    else:
        lo, hi = lo - 0.1 * span, hi + 0.1 * span
    return [float(x) for x in np.linspace(lo, hi, max(bins - 1, 1))]


def fixed_histogram(values: np.ndarray, edges) -> np.ndarray:
    """Count finite ``values`` into ``len(edges) + 1`` bins (the outer
    two catch under/overflow, so total mass is preserved no matter how
    far a later window wanders off the reference's support)."""
    e = np.asarray(edges, dtype=np.float64)
    v = np.asarray(values, dtype=np.float64).ravel()
    v = v[np.isfinite(v)]
    idx = np.searchsorted(e, v, side="right")
    return np.bincount(idx, minlength=len(e) + 1).astype(np.int64)


# ---------------------------------------------------------------------------
# reference profiles (training grid)
# ---------------------------------------------------------------------------

class ReferenceProfile:
    """Per-feature bin-count histograms over the TRAINING (BinMapper)
    grid, plus label and (optionally) prediction-score histograms.

    Self-contained: it carries the slice of mapper state (bin upper
    bounds, missing type, categorical value map) needed to project each
    training bin onto any model's serving grid, so a profile loaded
    from an old spill manifest or checkpoint needs nothing else."""

    kVersion = 1

    def __init__(self, used: List[int], counts: List[np.ndarray],
                 mappers_meta: List[dict], num_rows: int,
                 label_hist: Optional[dict] = None,
                 score_hist: Optional[dict] = None,
                 feature_names: Optional[List[str]] = None) -> None:
        self.used = [int(f) for f in used]
        self.counts = [np.asarray(c, dtype=np.int64) for c in counts]
        self.mappers_meta = mappers_meta
        self.num_rows = int(num_rows)
        self.label_hist = label_hist
        self.score_hist = score_hist
        self.feature_names = feature_names

    # -- serialization -------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "version": self.kVersion,
            "num_rows": self.num_rows,
            "used": self.used,
            "counts": [[int(v) for v in c] for c in self.counts],
            "mappers": self.mappers_meta,
            "label_hist": self.label_hist,
            "score_hist": self.score_hist,
            "feature_names": self.feature_names,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ReferenceProfile":
        return cls(used=d["used"],
                   counts=[np.asarray(c, dtype=np.int64)
                           for c in d["counts"]],
                   mappers_meta=d["mappers"],
                   num_rows=d["num_rows"],
                   label_hist=d.get("label_hist"),
                   score_hist=d.get("score_hist"),
                   feature_names=d.get("feature_names"))

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)

    @classmethod
    def load(cls, path: str) -> "ReferenceProfile":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    # -- score hist attachment (ft/checkpoint.py, at save time) --------
    def attach_scores(self, scores: np.ndarray, objective=None) -> None:
        """Stamp the prediction-score histogram. Pass the model's
        ``objective`` so the reference lives in SERVING output space
        (``convert_output`` — e.g. sigmoid probabilities for binary):
        the live side histograms what the server hands back, and raw
        margins vs probabilities would read as permanent score
        drift."""
        s = np.asarray(scores, dtype=np.float64)
        if objective is not None:
            s = np.asarray(objective.convert_output(s),
                           dtype=np.float64)
        if s.ndim > 1:
            s = s[:, 0]
        edges = histogram_edges(s, kScoreBins)
        self.score_hist = {
            "edges": edges,
            "counts": [int(v) for v in fixed_histogram(s, edges)],
        }


def _mapper_meta(m) -> dict:
    """The projection-relevant slice of a BinMapper's state."""
    return {
        "num_bin": int(m.num_bin),
        "missing_type": int(m.missing_type),
        "bin_type": int(m.bin_type),
        "bin_upper_bound": [float(v) for v in m.bin_upper_bound],
        "bin_2_categorical": [int(v) for v in m.bin_2_categorical],
        "min_val": float(m.min_val),
        "max_val": float(m.max_val),
        "default_bin": int(m.default_bin),
    }


class ProfileBuilder:
    """Accumulates the training-grid reference profile during the spill
    pass (io/shards.py pass 2): one jitted scatter-add reduction per
    shard buffer over the already-binned block, label histogram on the
    host. The shard buffers all share one fixed ``[shard_rows, F]``
    shape, so the reduction traces once per spill."""

    def __init__(self, mappers, used_feature_map: List[int],
                 feature_names: Optional[List[str]] = None) -> None:
        self._mappers = list(mappers)
        self._used = [int(f) for f in used_feature_map]
        self._names = feature_names
        self._max_bin = max([int(m.num_bin) for m in self._mappers]
                            or [1])
        self._counts = None           # device [F, max_bin] i32
        self._rows = 0
        self._label_edges = None
        self._label_counts = None

    def add_block(self, bins_block: np.ndarray, n_valid: int) -> None:
        """Accumulate ``bins_block[:n_valid]`` (host uint bins, fixed
        shape) into the device counts — ``n_valid`` rides in as a
        traced scalar so every shard reuses one trace."""
        if not self._mappers:
            return
        import jax

        f_cnt = bins_block.shape[1]
        if self._counts is None:
            self._counts = jax.device_put(
                np.zeros((f_cnt, self._max_bin), dtype=np.int32))
        b = jax.device_put(
            np.ascontiguousarray(bins_block, dtype=np.int32))
        nv = jax.device_put(np.int32(n_valid))
        self._counts = _profile_accum_jit()(b, nv, self._counts)
        self._rows += int(n_valid)

    def add_labels(self, y: np.ndarray) -> None:
        y = np.asarray(y, dtype=np.float64).ravel()
        if self._label_edges is None:
            self._label_edges = histogram_edges(y, kLabelBins)
            self._label_counts = np.zeros(len(self._label_edges) + 1,
                                          dtype=np.int64)
        self._label_counts += fixed_histogram(y, self._label_edges)

    def finalize(self) -> ReferenceProfile:
        if self._counts is None:
            counts = np.zeros((len(self._mappers), self._max_bin),
                              dtype=np.int64)
        else:
            import jax
            # one read-back per spill: the finished [F, B] reference
            # counts leave the device exactly once, at finalization
            counts = np.asarray(jax.device_get(self._counts),
                                dtype=np.int64)
        label_hist = None
        if self._label_edges is not None:
            label_hist = {
                "edges": self._label_edges,
                "counts": [int(v) for v in self._label_counts],
            }
        return ReferenceProfile(
            used=self._used,
            counts=[counts[j, :int(m.num_bin)]
                    for j, m in enumerate(self._mappers)],
            mappers_meta=[_mapper_meta(m) for m in self._mappers],
            num_rows=self._rows,
            label_hist=label_hist,
            feature_names=self._names)


_profile_jit_lock = threading.Lock()
_profile_jit = None


def _profile_accum_jit():
    """Module-level jit shared across builders (one trace per block
    shape): ``counts[f, bins[i, f]] += 1`` for the first n_valid rows."""
    global _profile_jit
    with _profile_jit_lock:
        if _profile_jit is None:
            import jax.numpy as jnp

            def _body(b, n_valid, counts):
                n, f_cnt = b.shape
                mask = (jnp.arange(n) < n_valid).astype(counts.dtype)
                bmax = counts.shape[1]
                b = jnp.clip(b, 0, bmax - 1)
                rows = jnp.broadcast_to(jnp.arange(f_cnt)[None, :],
                                        b.shape)
                return counts.at[rows, b].add(mask[:, None])

            _profile_jit = obs_compile.instrument_jit(
                "quality.profile_accum", _body)
        return _profile_jit


# ---------------------------------------------------------------------------
# serving-grid projection (host-side, once per monitor)
# ---------------------------------------------------------------------------

def _project_feature(meta: dict, counts: np.ndarray, thr: np.ndarray,
                     is_cat: bool, nan_feat: bool, zero_feat: bool,
                     vmax: int, width: int) -> np.ndarray:
    """One feature's training-grid counts -> serving-grid counts
    ``[width]`` (last two lanes = NaN / zero sentinels).

    Every numeric serving threshold is one of the training grid's
    ``bin_upper_bound`` values (serve/forest.py), so each training bin
    maps WHOLLY into one serving bin; the bin's midpoint is the
    representative value sent through the same ``searchsorted`` the
    device quantizer runs. Categorical bins route through their
    category value exactly like the device LUT clamp."""
    out = np.zeros(width, dtype=np.int64)
    nan_lane, zero_lane = width - 2, width - 1
    num_bin = int(meta["num_bin"])
    counts = np.asarray(counts, dtype=np.int64)

    if is_cat:
        b2c = meta.get("bin_2_categorical") or []
        for b in range(min(num_bin, len(counts))):
            c = int(counts[b])
            if c == 0:
                continue
            v = int(b2c[b]) if b < len(b2c) else -1
            sb = v if 0 <= v <= vmax else vmax + 1
            out[min(sb, width - 3)] += c
        return out

    bub = [float(v) for v in meta.get("bin_upper_bound") or [math.inf]]
    missing_type = int(meta["missing_type"])
    min_val = float(meta.get("min_val", 0.0))
    max_val = float(meta.get("max_val", 0.0))
    # training bin that holds the value 0.0 (the zero sentinel's home);
    # BinMapper records it exactly (default_bin = value_to_bin(0.0))
    zero_bin = int(meta.get("default_bin", 0))
    n_grid = len(bub)
    for b in range(min(num_bin, len(counts))):
        c = int(counts[b])
        if c == 0:
            continue
        # MissingType.NAN (== 2, io/binning.py) puts NaN in the last
        # bin (its appended upper bound is the NaN itself)
        if missing_type == 2 and b == num_bin - 1:
            out[nan_lane] += c
            continue
        if zero_feat and b == zero_bin:
            out[zero_lane] += c
            continue
        upper = bub[b] if b < n_grid else math.inf
        if b == 0:
            lower = min_val if min_val <= upper else upper - 1.0
        else:
            lower = bub[b - 1]
        if math.isinf(upper):
            rep = max_val if max_val > lower else lower + 1.0
        elif math.isinf(lower) or lower > upper:
            rep = upper
        else:
            rep = 0.5 * (lower + upper)
        if nan_feat and not math.isfinite(rep):
            out[nan_lane] += c
            continue
        sb = int(np.searchsorted(thr, np.float32(rep), side="left"))
        out[min(max(sb, 0), width - 3)] += c
    return out


# ---------------------------------------------------------------------------
# live serving-side accumulation
# ---------------------------------------------------------------------------

_accum_jit_lock = threading.Lock()
_accum_jit = None


def _quality_accum_jit():
    """Module-level jit shared across monitors AND replicas (one trace
    per (chunk shape, grid shape), paid at warm): quantize the raw
    chunk against the monitor's pinned grid and scatter-add the first
    ``n_valid`` rows into the ``[U, W]`` window counts. Sentinels ride
    in the last two lanes exactly like the LUT walk's columns
    (serve/forest.py: ``W - 2`` NaN, ``W - 1`` zero)."""
    global _accum_jit
    with _accum_jit_lock:
        if _accum_jit is None:
            import jax.numpy as jnp

            from ..ops.predict import (_quantize_rows_impl, kNanBin,
                                       kZeroBin)

            def _body(x, qt, n_valid, counts):
                b = _quantize_rows_impl(x, qt)          # [n, U]
                w = counts.shape[1]
                b = jnp.where(b == jnp.int32(kNanBin), w - 2,
                              jnp.where(b == jnp.int32(kZeroBin), w - 1,
                                        jnp.clip(b, 0, w - 3)))
                mask = (jnp.arange(x.shape[0]) < n_valid) \
                    .astype(counts.dtype)
                u = jnp.broadcast_to(jnp.arange(b.shape[1])[None, :],
                                     b.shape)
                return counts.at[u, b].add(mask[:, None])

            _accum_jit = obs_compile.instrument_jit(
                "quality.window_accum", _body)
        return _accum_jit


class QualityMonitor:
    """Windowed serving-input monitor bound to one model's quantizer
    grid and (optionally) a training-time :class:`ReferenceProfile`.

    Dispatch threads call :meth:`accumulate` per chunk — an explicit
    ``device_put`` of arrays the dispatch already staged plus one
    scatter-add on device, nothing read back. All replicas share ONE
    monitor: the device window state is a dict keyed by device, guarded
    by one lock (the PR 11 shared-bucket pattern), so the per-replica
    predictors never race and a drain never tears a window.

    :meth:`drain` (called from the exporter tick, the refresh loop, and
    tests) reads the window back ONCE, resets it, scores PSI/JS per
    feature against the serving-projected reference, and publishes the
    ``quality/...`` gauges obs/export.py and obs/health.py consume."""

    def __init__(self, forest, profile: Optional[ReferenceProfile] = None,
                 name: str = "serve",
                 min_window_rows: int = 0) -> None:
        import jax

        self.name = name
        self.profile = profile
        # a window with too few rows scores sampling noise as drift (a
        # 64-row window over ~255 bins has expected PSI ~ bins/rows ≈ 4
        # against an identical distribution) — below this floor drain()
        # CARRIES the window forward instead of scoring it
        self.min_window_rows = max(int(min_window_rows), 0)
        self._pending_rows = 0
        # pin the grid: monitoring stays on ONE grid across refresh
        # publishes, so drift numbers are never an artifact of a swap
        # (one-shot host snapshot of the quantizer tables)
        qt = jax.device_get(forest._qt)
        self._qt_host = qt
        self._used = np.asarray(qt.used, dtype=np.int64)
        thr = np.asarray(qt.thresholds, dtype=np.float32)
        self._n_thr = np.isfinite(thr).sum(axis=1).astype(np.int64)
        vmax = int(qt.vmax)
        m_pad = thr.shape[1] if thr.size else 1
        # serving-grid width: every regular bin + the two sentinel
        # lanes, the same W the LUT node encoding uses
        self._width = max(m_pad + 1, vmax + 2) + 2
        self._vmax = vmax
        self._thr_rows = [thr[u][np.isfinite(thr[u])]
                          for u in range(thr.shape[0])]
        self._is_cat = np.asarray(qt.is_cat, dtype=bool)
        self._nan_feat = np.asarray(qt.nan_feat, dtype=bool)
        self._zero_feat = np.asarray(qt.zero_feat, dtype=bool)

        self._ref, self._ref_valid = self._project_profile()

        self._lock = threading.Lock()
        self._state: Dict = {}        # device -> [U, W] i32 window
        self._qt_placed: Dict = {}    # device -> QuantizerTables
        self._zero_window: Dict = {}  # device -> cached [U, W] zeros
        self._score_edges = None
        self._score_ref = None
        self._score_counts = None
        if profile is not None and profile.score_hist:
            self._score_edges = np.asarray(
                profile.score_hist["edges"], dtype=np.float64)
            self._score_ref = np.asarray(
                profile.score_hist["counts"], dtype=np.int64)
            self._score_counts = np.zeros_like(self._score_ref)
        self._label_edges = None
        self._label_ref = None
        self._label_counts = None
        if profile is not None and profile.label_hist:
            self._label_edges = np.asarray(
                profile.label_hist["edges"], dtype=np.float64)
            self._label_ref = np.asarray(
                profile.label_hist["counts"], dtype=np.int64)
            self._label_counts = np.zeros_like(self._label_ref)
        self.last = {}               # most recent drain report

    # -- reference projection ------------------------------------------
    def _project_profile(self):
        u_cnt = len(self._used)
        ref = np.zeros((u_cnt, self._width), dtype=np.int64)
        valid = np.zeros(u_cnt, dtype=bool)
        if self.profile is None:
            return ref, valid
        by_raw = {f: j for j, f in enumerate(self.profile.used)}
        for u in range(u_cnt):
            j = by_raw.get(int(self._used[u]))
            if j is None:
                continue
            ref[u] = _project_feature(
                self.profile.mappers_meta[j], self.profile.counts[j],
                self._thr_rows[u], bool(self._is_cat[u]),
                bool(self._nan_feat[u]), bool(self._zero_feat[u]),
                self._vmax, self._width)
            valid[u] = ref[u].sum() > 0
        return ref, valid

    # -- hot path -------------------------------------------------------
    def _placed_qt(self, device):
        qt = self._qt_placed.get(device)
        if qt is None:
            import jax
            qt = type(self._qt_host)(
                *[jax.device_put(a, device) for a in self._qt_host])
            self._qt_placed[device] = qt
        return qt

    def accumulate(self, chunk: np.ndarray, n_valid: int,
                   device=None) -> None:
        """One dispatched chunk (host rows, zero-padded to its bucket;
        ``n_valid`` real rows) into the device window. Explicit
        transfers only; nothing comes back — the read-back happens once
        per window, in :meth:`drain`."""
        import jax

        u_cnt = len(self._used)
        if u_cnt == 0 or n_valid <= 0:
            return
        x = jax.device_put(
            np.ascontiguousarray(chunk, dtype=np.float32), device)
        nv = jax.device_put(np.int32(n_valid), device)
        with self._lock:
            self._pending_rows += int(n_valid)
            qt = self._placed_qt(device)
            counts = self._state.get(device)
            if counts is None:
                # fresh window: seed from a cached device-resident zero
                # block (one explicit put per device, ever — jnp.zeros
                # here would be an IMPLICIT transfer on the first chunk
                # of every window and trip the serve transfer guard)
                counts = self._zero_window.get(device)
                if counts is None:
                    counts = jax.device_put(
                        np.zeros((u_cnt, self._width), dtype=np.int32),
                        device)
                    self._zero_window[device] = counts
            self._state[device] = _quality_accum_jit()(x, qt, nv, counts)

    def observe_scores(self, y: np.ndarray) -> None:
        """Host-side prediction-score accumulation (the scores are
        already on the host on their way back to the caller)."""
        if self._score_edges is None:
            return
        y = np.asarray(y)
        if y.ndim > 1:
            y = y[:, 0]
        h = fixed_histogram(y, self._score_edges)
        with self._lock:
            self._score_counts += h

    def observe_labels(self, y: np.ndarray) -> None:
        """Label histogram per refresh window (refresh windows carry
        labels; the serving path does not)."""
        if self._label_edges is None:
            return
        h = fixed_histogram(np.asarray(y), self._label_edges)
        with self._lock:
            self._label_counts += h

    # -- window drain + scoring ----------------------------------------
    def drain(self, reg=None) -> dict:
        """Read the window back (one transfer per device), reset it,
        score drift vs the projected reference, publish gauges. Safe to
        call concurrently with accumulation: the swap happens under the
        same lock the accumulators hold, so a window is always a whole
        number of chunks."""
        import jax

        reg = reg if reg is not None else obs_registry
        with self._lock:
            if 0 < self._pending_rows < self.min_window_rows:
                # under-filled window: leave the device state in place
                # and score it on a later tick, once it holds enough
                # rows that PSI is signal rather than sampling noise
                return {"rows": 0, "carried": True,
                        "pending_rows": self._pending_rows,
                        "psi": {}, "js": {}, "psi_max": 0.0,
                        "js_max": 0.0, "edge_mass": 0.0,
                        "score_psi": None, "label_psi": None,
                        "worst_feature": None}
            self._pending_rows = 0
            states = list(self._state.items())
            self._state = {}
            score_counts = self._score_counts
            if score_counts is not None:
                self._score_counts = np.zeros_like(score_counts)
            label_counts = self._label_counts
            if label_counts is not None:
                self._label_counts = np.zeros_like(label_counts)
        live = np.zeros((len(self._used), self._width), dtype=np.int64)
        for _, counts in states:
            # the window boundary: one [U, W] read-back per device
            # per exporter tick
            live += np.asarray(jax.device_get(counts), dtype=np.int64)

        rows = int(live[0].sum()) if len(self._used) else 0
        report = {"rows": rows, "carried": False, "psi": {}, "js": {},
                  "psi_max": 0.0, "js_max": 0.0, "edge_mass": 0.0,
                  "score_psi": None, "label_psi": None,
                  "worst_feature": None}
        if rows > 0:
            for u in range(len(self._used)):
                if not self._ref_valid[u]:
                    continue
                raw = int(self._used[u])
                fp = psi(self._ref[u], live[u])
                fj = js_divergence(self._ref[u], live[u])
                report["psi"][raw] = fp
                report["js"][raw] = fj
                if fp >= report["psi_max"]:
                    report["psi_max"] = fp
                    report["worst_feature"] = raw
                report["js_max"] = max(report["js_max"], fj)
                reg.gauge("quality/psi/feature/%d" % raw, fp)
                reg.gauge("quality/js/feature/%d" % raw, fj)
                if not self._is_cat[u]:
                    report["edge_mass"] = max(
                        report["edge_mass"],
                        self._edge_mass(u, live[u]))
            if score_counts is not None and self._score_ref is not None:
                report["score_psi"] = psi(self._score_ref, score_counts)
                reg.gauge("quality/score_psi", report["score_psi"])
            if label_counts is not None and label_counts.sum() > 0 \
                    and self._label_ref is not None:
                report["label_psi"] = psi(self._label_ref, label_counts)
                reg.gauge("quality/label_psi", report["label_psi"])
            reg.gauge("quality/psi_max", report["psi_max"])
            reg.gauge("quality/js_max", report["js_max"])
            reg.gauge("quality/edge_mass", report["edge_mass"])
            reg.inc("quality/windows")
        reg.gauge("quality/window_rows", rows)
        reg.inc("quality/rows", rows)
        self.last = report
        return report

    def _edge_mass(self, u: int, live_u: np.ndarray) -> float:
        """Excess live mass in the grid's catch-all edge bins (below
        the first / beyond the last threshold) over the reference's —
        the signal that the bin boundaries themselves no longer cover
        the data (frozen-splits invalidation -> retrain_required)."""
        total = live_u.sum()
        if total <= 0:
            return 0.0
        hi = int(self._n_thr[u])           # beyond-last-threshold bin
        lanes = [0, hi] if hi > 0 else [0]
        ref_total = max(self._ref[u].sum(), 1)
        excess = 0.0
        for b in lanes:
            live_frac = live_u[b] / total
            ref_frac = self._ref[u][b] / ref_total
            excess = max(excess, float(live_frac - ref_frac))
        return excess


# ---------------------------------------------------------------------------
# module-level monitor registration (the exporter tick drains these)
# ---------------------------------------------------------------------------

_monitors_lock = threading.Lock()
_monitors: List[QualityMonitor] = []


def register_monitor(m: QualityMonitor) -> QualityMonitor:
    with _monitors_lock:
        if m not in _monitors:
            _monitors.append(m)
    return m


def unregister_monitor(m: QualityMonitor) -> None:
    with _monitors_lock:
        if m in _monitors:
            _monitors.remove(m)


def drain_all(reg=None) -> List[dict]:
    """Drain every registered monitor (SnapshotExporter.dump_now calls
    this right before it snapshots, so each exporter tick is exactly
    one drift window). Monitor failures degrade to a perf_warning — a
    broken drift score must never take the exporter down."""
    with _monitors_lock:
        monitors = list(_monitors)
    reports = []
    for m in monitors:
        try:
            reports.append(m.drain(reg))
        except Exception as e:  # pragma: no cover - defensive
            obs_events.emit("perf_warning", component="obs.quality",
                            message="quality drain failed: %r" % e)
    return reports
