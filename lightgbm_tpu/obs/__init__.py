"""Structured telemetry for the whole training pipeline.

The reference builds per-stage observability directly into the trainer
(``Common::Timer``/``FunctionTimer`` RAII scopes around every pipeline
stage, include/LightGBM/utils/common.h:973,1037, aggregated table printed
at exit under -DUSE_TIMETAG). This package is the TPU-native superset:

- :mod:`registry`  — counters, gauges, and the stage timer (absorbs the
  old ``utils/timer.py``; scopes still open
  ``jax.profiler.TraceAnnotation`` ranges so stages are attributable in
  TensorBoard/perfetto device traces).
- :mod:`events`    — a JSON-lines event sink (``LIGHTGBM_TPU_EVENT_LOG``
  env var or a programmatic callback mirroring
  ``log.register_log_callback``).
- :mod:`compile`   — XLA compile/retrace tracking per jitted function,
  plus opt-in ``lower().cost_analysis()`` capture (FLOPs / bytes / HLO
  size on the ``jit_trace`` event).
- :mod:`health`    — backend selection / fallback events, plus the SLO
  :class:`~lightgbm_tpu.obs.health.Watchdog` (threshold rules over the
  snapshot stream, one ``health`` event per breach).
- :mod:`export`    — OpenMetrics-style snapshot rendering: periodic
  file dumps (``LIGHTGBM_TPU_METRICS=path``) and the HTTP ``/metrics``
  listener the serving plane mounts (text-format primitives live in
  the stdlib-pure :mod:`openmetrics`).
- :mod:`gateway`   — the FLEET plane: per-process
  :class:`~lightgbm_tpu.obs.gateway.SnapshotPusher` POSTs
  (``LIGHTGBM_TPU_METRICS_GATEWAY=url``) into one
  :class:`~lightgbm_tpu.obs.gateway.MetricsGateway` serving aggregated
  ``{rank=,process=}`` metrics + per-rank push staleness, watched by
  ``health.fleet_rules`` (rank_skew / dead_rank / fleet_shed_rate).
- :mod:`trace`     — span tracing layered onto the scopes and events
  above, exported as Chrome-trace/Perfetto JSON
  (``LIGHTGBM_TPU_TRACE=path.json``), with the async readiness drainer
  that replaces stage fences under ``LIGHTGBM_TPU_TIMETAG=sample``;
  streaming runs can write the compact binary segment format of
  :mod:`trace_compact` (``LIGHTGBM_TPU_TRACE_FORMAT=compact``).

Enable stage timing with ``LIGHTGBM_TPU_TIMETAG=1`` (the analogue of
-DUSE_TIMETAG; fencing) or ``=sample`` (non-perturbing) or
``registry.enable()``; route events to a file with
``LIGHTGBM_TPU_EVENT_LOG=path`` or ``events.register_event_callback``.
See docs/OBSERVABILITY.md for the event schema and trace format.
"""
from __future__ import annotations

from . import compile as compile_tracking  # noqa: F401
from . import events, faults, health  # noqa: F401
from . import openmetrics, trace_compact  # noqa: F401  (stdlib-pure)
from .registry import MetricsRegistry, StageTimer, registry  # noqa: F401
from . import trace  # noqa: F401  (installs the span hooks/taps)
from . import export  # noqa: F401  (OpenMetrics snapshots + /metrics)
from . import gateway  # noqa: F401  (fleet push gateway)

scope = registry.scope
counter = registry.inc
gauge = registry.gauge
observe = registry.observe
watch_ready = registry.watch_ready

__all__ = [
    "MetricsRegistry", "StageTimer", "registry", "events", "health",
    "compile_tracking", "trace", "trace_compact", "openmetrics",
    "export", "gateway", "scope", "counter", "gauge",
    "observe", "watch_ready",
]
