"""OpenMetrics text-format primitives: escaping, sample lines, parsing.

Split out of :mod:`obs.export` so the format layer is importable with
ZERO package dependencies — no jax, no registry, no relative imports.
Two consumers need exactly that:

- ``tools/trace_report.py`` / ``tools/tpu_phase_timer.py
  --from-metrics`` load this file by PATH (importlib) to join gateway
  metrics dumps with trace segments without dragging jax into a
  report subprocess;
- :mod:`obs.gateway` re-renders pushed snapshots with injected
  ``{rank=,process=}`` labels and must share one escaping/parsing
  contract with :func:`obs.export.render_openmetrics` (which re-exports
  everything here, so existing ``from obs.export import
  parse_openmetrics`` call sites are unchanged).

The format is the OpenMetrics-style subset the exporter emits:
``# TYPE`` headers, ``name{label="value"} number`` sample lines, a
``# EOF`` terminator. :func:`parse_openmetrics` is strict (raises
ValueError on a malformed sample line) — the round-trip tests depend
on malformed text failing loudly, and the gateway turns that ValueError
into an HTTP 400 instead of silently aggregating garbage.
"""
from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

kPrefix = "lightgbm_tpu_"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _san(name: str) -> str:
    s = _NAME_RE.sub("_", name)
    if not s or s[0].isdigit():
        s = "_" + s
    return s


def _esc(label_value) -> str:
    return (str(label_value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _lbl(labels, extra=()) -> str:
    """Render a ``{k="v",...}`` label block (empty string when there
    are no labels)."""
    pairs = list(labels or ()) + list(extra)
    if not pairs:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _esc(v)) for k, v in pairs)


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_][a-zA-Z0-9_]*)(?:\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_TYPE_RE = re.compile(r'^#\s*TYPE\s+(\S+)\s+(\S+)\s*$')

Sample = Tuple[str, Tuple[Tuple[str, str], ...]]


def parse_openmetrics(text: str) -> Dict[Sample, float]:
    """Parse OpenMetrics-style text back into
    ``{(name, ((label, value), ...)): float}``. Raises ValueError on a
    malformed sample line — the round-trip tests depend on strictness."""
    out: Dict[Sample, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError("malformed sample line: %r" % line)
        name, labels_raw, value = m.groups()
        labels = []
        if labels_raw:
            matched = _LABEL_RE.findall(labels_raw)
            stripped = _LABEL_RE.sub("", labels_raw).replace(",", "").strip()
            if stripped:
                raise ValueError("malformed labels: %r" % labels_raw)
            # single left-to-right scan: sequential .replace() passes
            # would let an escaped backslash donate its second half to
            # a following 'n' or '"' (r'C:\\nightly' -> 'C:\' + \n)
            unesc = re.compile(r"\\(.)")
            labels = [(k, unesc.sub(
                lambda m: "\n" if m.group(1) == "n" else m.group(1), v))
                for k, v in matched]
        out[(name, tuple(sorted(labels)))] = float(value)
    return out


def parse_type_headers(text: str) -> Dict[str, str]:
    """``# TYPE name kind`` headers of an OpenMetrics document —
    the family metadata :func:`parse_openmetrics` deliberately skips.
    The gateway carries these through aggregation so a re-rendered
    family keeps its original kind."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        m = _TYPE_RE.match(line.strip())
        if m is not None:
            out[m.group(1)] = m.group(2)
    return out


def metric_value(parsed: Dict[Sample, float], name: str,
                 **labels) -> Optional[float]:
    """Convenience lookup into :func:`parse_openmetrics` output."""
    key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
    return parsed.get(key)


def sum_metric(parsed: Dict[Sample, float], name: str,
               **labels) -> float:
    """Sum every sample of ``name`` whose labels INCLUDE the given
    pairs (a family-level aggregate where :func:`metric_value` is an
    exact-key lookup) — e.g. total stage seconds of one rank across
    all its stages."""
    want = set((k, str(v)) for k, v in labels.items())
    total = 0.0
    for (n, lbls), v in parsed.items():
        if n == name and want.issubset(set(lbls)):
            total += v
    return total
