"""Metrics snapshot export: OpenMetrics text, periodic dumps, /metrics.

The registry (counters / gauges / histograms / stage timer) is a pull
model — everything this module does is *render* one consistent
``registry.snapshot()`` into the OpenMetrics-style text format and move
it somewhere a consumer can reach:

- :func:`render_openmetrics` — the serializer (stdlib-only, no jax);
  `serve/latency_ms` style histogram names come out as summary
  families with ``quantile`` labels, per-function compile/retrace
  telemetry as labeled families (``...jit_traces_total{fn="..."}``),
  the stage timer as ``stage_seconds_total{stage="..."}``.
- :func:`parse_openmetrics` — the matching reader (round-trip tested;
  also what the watchdog tests use to assert the exported numbers).
- :func:`dump_metrics` — one-shot ATOMIC file dump (tmp + rename), for
  training runs that want snapshots without an HTTP listener.
- :class:`SnapshotExporter` — a daemon thread re-dumping every
  ``interval`` seconds and running the SLO watchdog
  (:class:`obs.health.Watchdog`) over each snapshot. Enabled by
  ``LIGHTGBM_TPU_METRICS=path`` (+ ``LIGHTGBM_TPU_METRICS_INTERVAL``,
  seconds, default 10) via :func:`tick`, which the boosting drivers
  call once per iteration (`obs/trace.sample_iteration`).
- :class:`MetricsHTTPServer` — a ``/metrics`` (+ ``/healthz``) HTTP
  listener over the same renderer; ``serve/server.py PredictServer``
  mounts it with ``metrics_port=...`` so a serving fleet is scrapable
  under load.

Multi-process fleets push instead of being scraped per process:
``LIGHTGBM_TPU_METRICS_GATEWAY=url`` makes :func:`tick` start one
:class:`obs.gateway.SnapshotPusher` POSTing this renderer's text to a
:class:`obs.gateway.MetricsGateway`, which serves the whole fleet as
ONE aggregated ``/metrics`` with ``{rank=,process=}`` labels.

Everything here is best-effort and never raises into the caller:
telemetry must not take training or serving down.
"""
from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time
from typing import Dict, Optional

from . import events as _events
from .registry import registry
from ..utils import log
# the text-format layer (escaping, sample lines, strict parsing) lives
# in obs/openmetrics.py — stdlib-pure so the gateway and the no-jax
# tools share it; re-exported here so existing `from obs.export import
# parse_openmetrics` call sites are unchanged
from .openmetrics import (  # noqa: F401  (re-exports)
    Sample, kPrefix, metric_value, parse_openmetrics,
    parse_type_headers, _esc, _fmt, _lbl, _san)

_ENV_PATH = "LIGHTGBM_TPU_METRICS"
_ENV_INTERVAL = "LIGHTGBM_TPU_METRICS_INTERVAL"
_ENV_WATCHDOG = "LIGHTGBM_TPU_WATCHDOG"

kDefaultIntervalS = 10.0

_LABEL_RE = re.compile(
    r"^(.*)/(replica|feature)/([^/]+)(?:/model/(.+))?$")


def _split_labels(name: str):
    """Generic ``<base>/<label>/<k>[/model/<m>]`` → labeled-family
    folding, ONE code path for every labeled registry series:

    - ``serve/latency_ms/replica/3/model/m`` →
      (``serve/latency_ms``, (("model", "m"), ("replica", "3"))) — a
      serving fleet's per-replica series render as ONE family, so a
      single scrape target covers all replicas of every server in the
      process (the per-process /metrics gap from the ROADMAP);
    - ``quality/psi/feature/7`` →
      (``quality/psi``, (("feature", "7"),)) — the drift plane's
      per-feature scores render as one ``{feature=}``-labeled family.
    """
    m = _LABEL_RE.match(name)
    if m is None:
        return name, None
    labels = [(m.group(2), m.group(3))]
    if m.group(4) is not None:
        labels.append(("model", m.group(4)))
    return m.group(1), tuple(sorted(labels))


# PR 11 name kept alive for callers/tests of the replica folding
_split_replica = _split_labels


def render_openmetrics(reg=registry) -> str:
    """Serialize one consistent registry snapshot as OpenMetrics-style
    text (``# TYPE`` headers, ``{label="..."}`` pairs, ``# EOF``
    terminator). Families:

    - counters → ``<name>_total`` (``jit_trace/<fn>`` folds into one
      ``jit_traces_total{fn="..."}`` family);
    - numeric gauges → gauges (``compile/<fn>/<metric>`` folds into
      ``compile_<metric>{fn="..."}``); non-numeric gauges (``backend``)
      → ``<name>_info{value="..."} 1``;
    - histograms (``registry.observe``) → summary families with
      ``quantile="0.5"/"0.99"`` samples + ``_count``;
    - per-replica serving series (``<base>/replica/<k>`` counters and
      histograms, e.g. ``serve/latency_ms/replica/0``) fold into ONE
      family carrying a ``replica="k"`` label, so one scrape target
      covers a whole replicated serving fleet;
    - the stage timer → ``stage_seconds_total{stage=...}`` /
      ``stage_calls_total{stage=...}`` /
      ``stage_duration_ms{stage=...,quantile=...}``.
    """
    snap = reg.snapshot()
    out = []

    counters = snap.get("counters", {})
    plain = {k: v for k, v in counters.items()
             if not k.startswith("jit_trace/")}
    jit = {k[len("jit_trace/"):]: v for k, v in counters.items()
           if k.startswith("jit_trace/")}
    # fold per-replica counters into one labeled family per base name
    # (the samples of a family must stay contiguous under one # TYPE)
    families: Dict[str, list] = {}
    for name, v in plain.items():
        base, labels = _split_labels(name)
        families.setdefault(base, []).append((labels, v))
    for base in sorted(families):
        m = kPrefix + _san(base) + "_total"
        out.append("# TYPE %s counter" % m)
        for labels, v in sorted(families[base],
                                key=lambda lv: lv[0] or ()):
            out.append("%s%s %s" % (m, _lbl(labels), _fmt(v)))
    if jit:
        m = kPrefix + "jit_traces_total"
        out.append("# TYPE %s counter" % m)
        for fn, v in sorted(jit.items()):
            out.append('%s{fn="%s"} %s' % (m, _esc(fn), _fmt(v)))

    gauges = snap.get("gauges", {})
    compile_g: Dict[str, Dict[str, float]] = {}
    # numeric gauges fold through the SAME labeled-family path as the
    # counters/histograms (quality/psi/feature/<k> → {feature="k"})
    gfams: Dict[str, list] = {}
    for name, v in sorted(gauges.items()):
        if name.startswith("compile/"):
            parts = name.split("/")
            if len(parts) == 3:
                compile_g.setdefault(parts[2], {})[parts[1]] = v
                continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            base, labels = _split_labels(name)
            gfams.setdefault(base, []).append((labels, v))
        else:
            m = kPrefix + _san(name) + "_info"
            out.append("# TYPE %s gauge" % m)
            out.append('%s{value="%s"} 1' % (m, _esc(v)))
    for base in sorted(gfams):
        m = kPrefix + _san(base)
        out.append("# TYPE %s gauge" % m)
        for labels, v in sorted(gfams[base], key=lambda lv: lv[0] or ()):
            out.append("%s%s %s" % (m, _lbl(labels), _fmt(v)))
    for metric, by_fn in sorted(compile_g.items()):
        m = kPrefix + "compile_" + _san(metric)
        out.append("# TYPE %s gauge" % m)
        for fn, v in sorted(by_fn.items()):
            out.append('%s{fn="%s"} %s' % (m, _esc(fn), _fmt(v)))

    hfams: Dict[str, list] = {}
    for name, h in snap.get("hists", {}).items():
        base, labels = _split_labels(name)
        hfams.setdefault(base, []).append((labels, h))
    for base in sorted(hfams):
        m = kPrefix + _san(base)
        out.append("# TYPE %s summary" % m)
        for labels, h in sorted(hfams[base],
                                key=lambda lh: lh[0] or ()):
            out.append("%s%s %s" % (m, _lbl(labels, [("quantile", "0.5")]),
                                    _fmt(h["p50"])))
            out.append("%s%s %s" % (m, _lbl(labels, [("quantile", "0.99")]),
                                    _fmt(h["p99"])))
            out.append("%s_count%s %s" % (m, _lbl(labels),
                                          _fmt(h["count"])))

    phases = snap.get("phases", {})
    if phases:
        sec = kPrefix + "stage_seconds_total"
        calls = kPrefix + "stage_calls_total"
        dur = kPrefix + "stage_duration_ms"
        out.append("# TYPE %s counter" % sec)
        for stage, e in sorted(phases.items()):
            out.append('%s{stage="%s"} %s'
                       % (sec, _esc(stage), _fmt(e["seconds"])))
        out.append("# TYPE %s counter" % calls)
        for stage, e in sorted(phases.items()):
            out.append('%s{stage="%s"} %s'
                       % (calls, _esc(stage), _fmt(e["calls"])))
        out.append("# TYPE %s summary" % dur)
        for stage, e in sorted(phases.items()):
            if "p50_ms" in e:
                out.append('%s{stage="%s",quantile="0.5"} %s'
                           % (dur, _esc(stage), _fmt(e["p50_ms"])))
                out.append('%s{stage="%s",quantile="0.99"} %s'
                           % (dur, _esc(stage), _fmt(e["p99_ms"])))
    out.append("# EOF")
    return "\n".join(out) + "\n"


def dump_metrics(path: str, reg=registry) -> None:
    """One-shot atomic snapshot dump. Never raises: transient write
    failures retry with bounded backoff (utils/retry.py), and a dump
    that still fails is SKIPPED with a counter + warning (the next
    tick dumps again) — degradation, never a crash or a torn file."""
    try:
        text = render_openmetrics(reg)

        def _write():
            from . import faults
            faults.check("metrics_dump", path=path)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)

        from ..utils.retry import retry_call
        retry_call(_write, site="metrics_dump", reg=reg)
    except Exception as e:
        reg.inc("ft/metrics_dump_failed")
        log.warning("metrics snapshot dump to %s failed: %r"
                    % (path, e))


# ----------------------------------------------------------------------
# periodic exporter + watchdog tick
# ----------------------------------------------------------------------

class SnapshotExporter:
    """Daemon thread: every ``interval`` seconds, atomically rewrite
    ``path`` with the current OpenMetrics text and run the SLO watchdog
    over the same snapshot. ``interval=0`` disables the thread — dumps
    then happen only on :meth:`dump_now` / atexit."""

    def __init__(self, path: str, interval: float = kDefaultIntervalS,
                 reg=registry, watchdog=None) -> None:
        from .health import Watchdog
        self.path = path
        self.interval = max(float(interval), 0.0)
        self.reg = reg
        self.watchdog = watchdog if watchdog is not None else Watchdog(reg)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._atexit_registered = False

    def start(self) -> "SnapshotExporter":
        if self.interval > 0 and (self._thread is None
                                  or not self._thread.is_alive()):
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="obs-metrics-exporter", daemon=True)
            self._thread.start()
        if not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(self.dump_now)
        return self

    def stop(self) -> None:
        """Stop the thread AND detach the atexit dump — a stopped
        (replaced) exporter must not re-dump post-stop registry state
        over its old path at interpreter exit."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._atexit_registered:
            self._atexit_registered = False
            try:
                atexit.unregister(self.dump_now)
            except Exception:
                pass

    def dump_now(self) -> None:
        try:
            # each exporter tick is one drift window: drain the
            # registered quality monitors FIRST so the snapshot (and
            # the watchdog pass over it) sees this window's scores
            from . import quality as _quality
            _quality.drain_all(self.reg)
        except Exception:
            pass
        try:
            snap = self.reg.snapshot()
            self.watchdog.evaluate(snap)
        except Exception:
            pass
        dump_metrics(self.path, self.reg)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.dump_now()


_exporter: Optional[SnapshotExporter] = None
_pusher = None  # gateway SnapshotPusher singleton (obs/gateway.py)
_inline_watchdog = None
_lock = threading.Lock()


def tick(reg=registry) -> None:
    """Per-iteration hook (called from ``obs/trace.sample_iteration``):
    starts the env-configured exporter once, the env-configured fleet
    gateway pusher once (``LIGHTGBM_TPU_METRICS_GATEWAY=url`` — the
    training-side half of the obs/gateway.py plane), and — when no file
    exporter is running but ``LIGHTGBM_TPU_WATCHDOG`` asks for it —
    evaluates the default watchdog inline so event-log-only runs still
    get ``health`` events. Cheap when none of the env vars is set."""
    global _exporter, _pusher, _inline_watchdog
    path = os.environ.get(_ENV_PATH)
    if path and _exporter is None:
        with _lock:
            if _exporter is None:
                try:
                    interval = float(os.environ.get(
                        _ENV_INTERVAL, kDefaultIntervalS))
                except ValueError:
                    interval = kDefaultIntervalS
                _exporter = SnapshotExporter(path, interval,
                                             reg).start()
    gw_url = os.environ.get("LIGHTGBM_TPU_METRICS_GATEWAY")
    if gw_url and _pusher is None:
        with _lock:
            if _pusher is None:
                from .gateway import SnapshotPusher
                _pusher = SnapshotPusher(gw_url, reg=reg,
                                         role="train").start()
    if _exporter is not None:
        return
    wd = os.environ.get(_ENV_WATCHDOG, "")
    if wd.strip().lower() in ("", "0", "false", "off"):
        return
    if _inline_watchdog is None:
        with _lock:
            if _inline_watchdog is None:
                from .health import Watchdog
                _inline_watchdog = Watchdog(reg)
    try:
        _inline_watchdog.evaluate()
    except Exception:
        pass


def reset_exporter() -> None:
    """Detach the env-driven exporter/pusher/watchdog singletons
    (tests)."""
    global _exporter, _pusher, _inline_watchdog
    with _lock:
        if _exporter is not None:
            _exporter.stop()
        if _pusher is not None:
            _pusher.stop()
        _exporter = None
        _pusher = None
        _inline_watchdog = None


# ----------------------------------------------------------------------
# /metrics HTTP listener
# ----------------------------------------------------------------------

class MetricsHTTPServer:
    """Minimal stdlib HTTP listener for scraping:

    - ``GET /metrics``  → OpenMetrics text (the renderer above);
    - ``GET /healthz``  → JSON ``registry.snapshot()`` plus the
      watchdog's currently-breached rules, plus — when the mounting
      server provides a ``readiness`` callable — a readiness field
      (``readiness``: ``ready``/``draining``/``stopped``, and the
      boolean ``ready``) DISTINCT from liveness: a draining
      PredictServer still answers scrapes while an external balancer
      rotates it out.

    Binds ``host:port`` (``port=0`` picks a free ephemeral port —
    read it back from ``.port``); serves from a daemon thread. The
    request handler reads ONE consistent snapshot per request and
    never raises into the socket loop."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 reg=registry, watchdog=None, readiness=None) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        self.reg = reg
        self.watchdog = watchdog
        self.readiness = readiness
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = render_openmetrics(outer.reg).encode()
                        ctype = "text/plain; charset=utf-8"
                    elif self.path.split("?")[0] == "/healthz":
                        doc = {"snapshot": outer.reg.snapshot()}
                        if outer.watchdog is not None:
                            doc["breached"] = outer.watchdog.breached()
                        if outer.readiness is not None:
                            state = outer.readiness()
                            doc["readiness"] = state
                            doc["ready"] = state == "ready"
                        body = (json.dumps(doc) + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception:
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-metrics-http", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return "http://%s:%d" % (self.host, self.port)

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
