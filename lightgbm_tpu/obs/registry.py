"""Metrics registry: counters, gauges, stage timers.

Absorbs the old ``utils/timer.py`` ``Timer`` (reference:
``Common::Timer``/``FunctionTimer``, include/LightGBM/utils/common.h:973,
1037 — RAII scopes around every pipeline stage, aggregated table printed
at exit when built with USE_TIMETAG). The TPU twist: enabled scopes also
open ``jax.profiler.TraceAnnotation`` ranges so the same stage names show
up in TensorBoard/perfetto device traces.

``jax.profiler`` is resolved ONCE at first use and the failure cached —
per-leaf scopes in the hot tree-growth loop must not pay Python
import-machinery overhead on every entry.

Timing modes (``LIGHTGBM_TPU_TIMETAG``):

- ``1``      — fencing mode: stage boundaries ``block_until_ready`` the
  stage's output so async dispatch cannot smear one stage into the next.
  Exact per-stage device attribution, but it SERIALIZES dispatch — the
  measured hot path is perturbed.
- ``sample`` — non-perturbing mode: scopes record host/dispatch wall
  time synchronously; device time is attributed asynchronously by a
  readiness drainer thread that ``block_until_ready``s each watched
  stage output off the hot path (recorded under ``<stage>::ready``).
  The training loop itself never fences.

The span-trace layer (``obs/trace.py``) installs hooks here so every
scope doubles as a renderable Perfetto span without touching callers.
"""
from __future__ import annotations

import atexit
import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import log

# jax.profiler, resolved once: None = unresolved, False = unavailable
_profiler_mod = None

# histogram reservoir bound: old samples age out past this many
kHistCap = 4096

# Trace-layer hooks, installed by obs/trace.py (registry stays importable
# standalone; the hook object must expose active()/begin(name)/end(token),
# ready_span(name, t0_perf, t1_perf, queued_s, for_span) and
# current_span() — the span id open on the calling thread, the token
# that lets the readiness drainer land device time on the exact
# emitting span).
_trace_hooks = None

# Reset hooks: callables run on MetricsRegistry.reset() so module-global
# state elsewhere (obs/compile.py's retrace-warning dedup) follows the
# registry's lifecycle instead of living forever.
_reset_hooks: List[Callable[[], None]] = []


def add_reset_hook(fn: Callable[[], None]) -> None:
    _reset_hooks.append(fn)


def install_trace_hooks(hooks) -> None:
    global _trace_hooks
    _trace_hooks = hooks


def _tracing() -> bool:
    h = _trace_hooks
    return h is not None and h.active()


def _parse_timetag(value: Optional[str]) -> Tuple[bool, bool]:
    """``LIGHTGBM_TPU_TIMETAG`` → (enabled, sampling)."""
    v = (value or "0").strip().lower()
    if v == "sample":
        return True, True
    if v in ("", "0", "false", "off", "no"):
        return False, False
    try:
        return bool(int(v)), False
    except ValueError:
        # any other non-empty value: timing on, classic fencing mode
        return True, False


def _get_profiler():
    global _profiler_mod
    if _profiler_mod is None:
        try:
            import jax.profiler as _p
            _profiler_mod = _p
        except Exception:
            _profiler_mod = False
    return _profiler_mod if _profiler_mod is not False else None


class StageTimer:
    """Per-stage wall-time aggregation (reference: FunctionTimer,
    common.h:1037). Enable with ``LIGHTGBM_TPU_TIMETAG=1`` or
    ``enable()``."""

    def __init__(self) -> None:
        self.enabled, self.sampling = _parse_timetag(
            os.environ.get("LIGHTGBM_TPU_TIMETAG"))
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        # per-call duration reservoirs (bounded like registry histograms)
        # backing the p50/p99 columns of phases()
        self.samples: Dict[str, list] = defaultdict(list)
        # record() runs on the caller's thread AND the readiness
        # drainer; readers (phases/print_summary) must not race a
        # first-time key insertion
        self._lock = threading.Lock()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def record(self, name: str, seconds: float) -> None:
        """Aggregate one completed stage call (totals + count + the
        bounded per-call sample reservoir). Thread-safe."""
        with self._lock:
            self.totals[name] += seconds
            self.counts[name] += 1
            vals = self.samples[name]
            vals.append(seconds)
            if len(vals) > kHistCap:
                del vals[:len(vals) - kHistCap]

    def stats(self) -> Dict[str, Tuple[float, int, list]]:
        """Consistent (total, calls, samples) snapshot per stage."""
        with self._lock:
            return {name: (self.totals[name], self.counts[name],
                           list(self.samples.get(name, ())))
                    for name in self.totals}

    @contextmanager
    def scope(self, name: str):
        """RAII stage scope (reference: FunctionTimer, common.h:1037).
        When the span-trace layer is active the scope also opens a span
        — even with aggregate timing disabled — so a trace-only run
        still renders every instrumented stage."""
        tracing = _tracing()
        if not self.enabled and not tracing:
            yield
            return
        annotation = None
        if self.enabled:
            profiler = _get_profiler()
            if profiler is not None:
                try:
                    annotation = profiler.TraceAnnotation(name)
                    annotation.__enter__()
                except Exception:
                    annotation = None
        token = _trace_hooks.begin(name) if tracing else None
        start = time.perf_counter()
        try:
            yield
        finally:
            if self.enabled:
                self.record(name, time.perf_counter() - start)
            if token is not None:
                _trace_hooks.end(token)
            if annotation is not None:
                annotation.__exit__(None, None, None)

    def print_summary(self) -> None:
        """reference: Timer::Print (common.h:1006) — per-stage totals.
        Prints regardless of verbosity: timing was explicitly enabled,
        exactly like a -DUSE_TIMETAG build's exit dump."""
        stats = self.stats()
        if not stats:
            return
        width = max(len(k) for k in stats)
        log.always("%s" % ("-" * (width + 30)))
        log.always("%-*s %12s %8s" % (width, "stage", "seconds", "calls"))
        for name in sorted(stats, key=lambda k: -stats[k][0]):
            log.always("%-*s %12.6f %8d"
                       % (width, name, stats[name][0], stats[name][1]))

    def reset(self) -> None:
        with self._lock:
            self.totals.clear()
            self.counts.clear()
            self.samples.clear()


class _ReadyWatcher:
    """Async stage-output readiness drainer (the non-perturbing
    replacement for TIMETAG's fences): the hot path enqueues a stage's
    output array and keeps dispatching; a daemon thread
    ``block_until_ready``s it off the hot path and attributes the
    remaining device time under ``<stage>::ready`` (plus a span on the
    trace's device-readiness lane).

    Attribution is PER STREAM: each watched stage name gets its own
    drainer thread, so two stages whose outputs are in flight
    concurrently (serve worker vs trainer, or overlapped pipeline
    stages) each measure ONLY their own readiness — the old single
    FIFO thread serialized the waits, folding stage A's wait into
    stage B's span whenever B finished first. Each watch also carries
    the span id that was open at submit time, so the ``::ready`` span
    parent-links to the exact emitting span instead of whichever span
    the FIFO happened to pair it with.

    At most ONE watch per stage name is in flight: a queued watch pins
    its output buffer alive (at Higgs scale the gh matrix alone is
    ~170 MB), so when the host runs ahead of the device further watches
    of the same stage are coalesced — counted under
    ``trace/ready_coalesced`` — rather than accumulating buffer
    references (total pinned = one buffer per distinct watched stage).
    Readiness is therefore a SAMPLE of iterations, which is exactly the
    mode's contract; the hot path never blocks."""

    kMaxStreams = 64

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._inflight = set()
        self._streams: Dict[str, object] = {}  # name -> queue.Queue
        self._unfinished = 0

    def _stream(self, name: str):
        q = self._streams.get(name)
        if q is None:
            with self._lock:
                q = self._streams.get(name)
                if q is None:
                    import queue
                    if len(self._streams) >= self.kMaxStreams:
                        # runaway distinct names (a bug upstream) must
                        # not spawn unbounded threads: fold the excess
                        # into one shared overflow stream
                        q = self._streams.get("<overflow>")
                        if q is None:
                            q = self._spawn("<overflow>")
                            self._streams["<overflow>"] = q
                        self._streams[name] = q
                    else:
                        q = self._spawn(name)
                        self._streams[name] = q
        return q

    def _spawn(self, name: str):
        import queue
        q = queue.Queue()
        t = threading.Thread(target=self._run, args=(q,),
                             name="obs-ready-drainer:" + name,
                             daemon=True)
        t.start()
        return q

    def submit(self, name: str, value, reg: "MetricsRegistry",
               span_id: int = 0) -> None:
        q = self._stream(name)
        with self._lock:
            if name in self._inflight:
                reg.inc("trace/ready_coalesced")
                return
            self._inflight.add(name)
            self._unfinished += 1
        q.put((name, value, time.perf_counter(), reg, span_id))

    def _run(self, q) -> None:
        while True:
            name, value, t_submit, reg, span_id = q.get()
            try:
                import jax
                t_wait0 = time.perf_counter()
                jax.block_until_ready(value)
                t_ready = time.perf_counter()
                if reg.timer.enabled:
                    reg.timer.record(name + "::ready", t_ready - t_submit)
                h = _trace_hooks
                if h is not None and h.active():
                    # span from wait-start (not submit): per-stream
                    # threads keep each lane's spans disjoint; the
                    # queue delay rides along as an arg
                    h.ready_span(name, t_wait0, t_ready,
                                 queued_s=t_wait0 - t_submit,
                                 for_span=span_id)
            except Exception:
                # a donated/deleted buffer or backend error must never
                # kill telemetry
                pass
            finally:
                del value
                with self._lock:
                    self._inflight.discard(name)
                    self._unfinished -= 1

    def drain(self, timeout: float = 10.0) -> bool:
        """Best-effort wait for all watched outputs to resolve (used
        before trace export / summary printing). Returns False on
        timeout — a wedged device must not wedge telemetry too."""
        deadline = time.perf_counter() + timeout
        while True:
            with self._lock:
                if self._unfinished == 0:
                    return True
            if time.perf_counter() >= deadline:
                return False
            time.sleep(0.001)


_ready_watcher = _ReadyWatcher()


class MetricsRegistry:
    """Counters + gauges + the stage timer, one process-wide instance.

    Counters and gauges are always live (they back compile/health
    tracking and cost single dict writes); stage timing is gated on the
    timer's ``enabled`` flag like the reference's USE_TIMETAG build."""

    def __init__(self) -> None:
        self.timer = StageTimer()
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        # histograms: bounded value reservoirs (last kHistCap samples)
        # + an unbounded observation counter — what the serving layer's
        # p50/p99 latency reporting reads
        self.hist_values: Dict[str, list] = defaultdict(list)
        self.hist_counts: Dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()
        # Profiling mode: fence (block_until_ready) at stage boundaries
        # so async dispatch can't smear one stage into the next. On only
        # under an explicit LIGHTGBM_TPU_TIMETAG=1 ask — programmatic
        # enable() (the bench) keeps aggregate timing WITHOUT fences,
        # since fencing perturbs the very throughput being measured, and
        # LIGHTGBM_TPU_TIMETAG=sample attributes device time through the
        # async readiness drainer instead of fencing.
        self.fences = self.timer.enabled and not self.timer.sampling

    # -- stage timers ---------------------------------------------------
    def scope(self, name: str):
        return self.timer.scope(name)

    def enable(self, sampling: Optional[bool] = None) -> None:
        self.timer.enable()
        if sampling is not None:
            self.timer.sampling = bool(sampling)
            if sampling:
                self.fences = False

    def disable(self) -> None:
        self.timer.disable()

    @property
    def enabled(self) -> bool:
        return self.timer.enabled

    @property
    def sampling(self) -> bool:
        return self.timer.sampling

    def fence(self) -> bool:
        """True when stage boundaries should block_until_ready."""
        return (self.timer.enabled and self.fences
                and not self.timer.sampling)

    def watch_ready(self, name: str, value) -> None:
        """Stage-output readiness attribution, three modes:

        - fencing (``LIGHTGBM_TPU_TIMETAG=1``): block inline — exact
          per-stage device time, serialized dispatch (legacy behavior);
        - sampling (``=sample``) or an active trace: hand the output to
          the async drainer — the hot path never blocks, device time
          lands under ``<name>::ready`` / the trace's readiness lane;
        - otherwise: no-op (a few attribute reads).
        """
        tracing = _tracing()
        if not self.timer.enabled and not tracing:
            return
        if self.fence():
            import jax
            jax.block_until_ready(value)
            return
        if self.timer.sampling or tracing:
            span_id = 0
            if tracing:
                try:
                    span_id = _trace_hooks.current_span()
                except Exception:
                    span_id = 0
            _ready_watcher.submit(name, value, self, span_id=span_id)

    def drain_ready(self, timeout: float = 10.0) -> bool:
        """Wait for the readiness drainer's queue to empty."""
        return _ready_watcher.drain(timeout)

    # -- counters / gauges ---------------------------------------------
    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self.counters[name] += n
            return self.counters[name]

    def count(self, name: str) -> int:
        return self.counters.get(name, 0)

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    # -- histograms -----------------------------------------------------
    def observe(self, name: str, value: float) -> None:
        """Record one sample into a bounded histogram reservoir."""
        with self._lock:
            self.hist_counts[name] += 1
            vals = self.hist_values[name]
            vals.append(float(value))
            if len(vals) > kHistCap:
                del vals[:len(vals) - kHistCap]

    def percentile(self, name: str, q: float) -> float:
        """Linear-interpolated percentile over the reservoir (numpy's
        default method); 0.0 when nothing was observed."""
        with self._lock:
            vals = sorted(self.hist_values.get(name, ()))
        return self._percentile_of(vals, q)

    @staticmethod
    def _percentile_of(vals: list, q: float) -> float:
        if not vals:
            return 0.0
        k = (len(vals) - 1) * (q / 100.0)
        f = int(k)
        c = min(f + 1, len(vals) - 1)
        return vals[f] + (vals[c] - vals[f]) * (k - f)

    # -- aggregation ----------------------------------------------------
    def phases(self) -> Dict[str, Dict[str, float]]:
        """Machine-readable stage table: {stage: {seconds, calls,
        p50_ms, p99_ms}} — what BENCH JSON publishes as its ``phases``
        dict. The percentile columns come from the bounded per-call
        sample reservoir, so BENCH records latency distributions, not
        just means."""
        out: Dict[str, Dict[str, float]] = {}
        for name, (total, calls, vals) in self.timer.stats().items():
            entry = {"seconds": round(total, 6), "calls": calls}
            if vals:
                sv = sorted(vals)
                entry["p50_ms"] = round(
                    self._percentile_of(sv, 50) * 1e3, 3)
                entry["p99_ms"] = round(
                    self._percentile_of(sv, 99) * 1e3, 3)
            out[name] = entry
        return out

    def snapshot(self) -> Dict:
        # histograms snapshot under the lock: a serving worker's first
        # observe() of a new name must not resize the dict mid-iteration
        with self._lock:
            hist_data = {name: (self.hist_counts[name], sorted(vals))
                         for name, vals in self.hist_values.items()}
            counters = dict(self.counters)
        return {"phases": self.phases(),
                "counters": counters,
                "gauges": dict(self.gauges),
                "hists": {name: {
                    "count": count,
                    "p50": round(self._percentile_of(vals, 50), 6),
                    "p99": round(self._percentile_of(vals, 99), 6)}
                    for name, (count, vals) in hist_data.items()}}

    def print_summary(self) -> None:
        self.timer.print_summary()

    def reset(self) -> None:
        self.timer.reset()
        with self._lock:
            self.counters.clear()
            self.hist_values.clear()
            self.hist_counts.clear()
        self.gauges.clear()
        for fn in _reset_hooks:
            try:
                fn()
            except Exception:
                pass


registry = MetricsRegistry()


def scoped(name: str):
    """Decorator form of ``registry.scope`` — the FunctionTimer analogue
    for whole functions."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with registry.scope(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco


@atexit.register
def _print_at_exit() -> None:
    if registry.timer.enabled:
        # sample mode: let in-flight readiness watches land first so the
        # ::ready rows are complete in the exit table
        _ready_watcher.drain(timeout=5.0)
        registry.timer.print_summary()


def start_device_trace(logdir: str) -> None:
    """Start a jax profiler trace (device timeline → TensorBoard)."""
    import jax.profiler
    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax.profiler
    jax.profiler.stop_trace()
