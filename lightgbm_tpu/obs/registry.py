"""Metrics registry: counters, gauges, stage timers.

Absorbs the old ``utils/timer.py`` ``Timer`` (reference:
``Common::Timer``/``FunctionTimer``, include/LightGBM/utils/common.h:973,
1037 — RAII scopes around every pipeline stage, aggregated table printed
at exit when built with USE_TIMETAG). The TPU twist: enabled scopes also
open ``jax.profiler.TraceAnnotation`` ranges so the same stage names show
up in TensorBoard/perfetto device traces.

``jax.profiler`` is resolved ONCE at first use and the failure cached —
per-leaf scopes in the hot tree-growth loop must not pay Python
import-machinery overhead on every entry.
"""
from __future__ import annotations

import atexit
import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional

from ..utils import log

# jax.profiler, resolved once: None = unresolved, False = unavailable
_profiler_mod = None

# histogram reservoir bound: old samples age out past this many
kHistCap = 4096


def _get_profiler():
    global _profiler_mod
    if _profiler_mod is None:
        try:
            import jax.profiler as _p
            _profiler_mod = _p
        except Exception:
            _profiler_mod = False
    return _profiler_mod if _profiler_mod is not False else None


class StageTimer:
    """Per-stage wall-time aggregation (reference: FunctionTimer,
    common.h:1037). Enable with ``LIGHTGBM_TPU_TIMETAG=1`` or
    ``enable()``."""

    def __init__(self) -> None:
        self.enabled = bool(int(os.environ.get("LIGHTGBM_TPU_TIMETAG",
                                               "0")))
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    @contextmanager
    def scope(self, name: str):
        """RAII stage scope (reference: FunctionTimer, common.h:1037)."""
        if not self.enabled:
            yield
            return
        annotation = None
        profiler = _get_profiler()
        if profiler is not None:
            try:
                annotation = profiler.TraceAnnotation(name)
                annotation.__enter__()
            except Exception:
                annotation = None
        start = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - start
            self.counts[name] += 1
            if annotation is not None:
                annotation.__exit__(None, None, None)

    def print_summary(self) -> None:
        """reference: Timer::Print (common.h:1006) — per-stage totals.
        Prints regardless of verbosity: timing was explicitly enabled,
        exactly like a -DUSE_TIMETAG build's exit dump."""
        if not self.totals:
            return
        width = max(len(k) for k in self.totals)
        log.always("%s" % ("-" * (width + 30)))
        log.always("%-*s %12s %8s" % (width, "stage", "seconds", "calls"))
        for name in sorted(self.totals, key=lambda k: -self.totals[k]):
            log.always("%-*s %12.6f %8d"
                       % (width, name, self.totals[name],
                          self.counts[name]))

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


class MetricsRegistry:
    """Counters + gauges + the stage timer, one process-wide instance.

    Counters and gauges are always live (they back compile/health
    tracking and cost single dict writes); stage timing is gated on the
    timer's ``enabled`` flag like the reference's USE_TIMETAG build."""

    def __init__(self) -> None:
        self.timer = StageTimer()
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        # histograms: bounded value reservoirs (last kHistCap samples)
        # + an unbounded observation counter — what the serving layer's
        # p50/p99 latency reporting reads
        self.hist_values: Dict[str, list] = defaultdict(list)
        self.hist_counts: Dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()
        # Profiling mode: fence (block_until_ready) at stage boundaries
        # so async dispatch can't smear one stage into the next. On only
        # under an explicit LIGHTGBM_TPU_TIMETAG ask — programmatic
        # enable() (the bench) keeps aggregate timing WITHOUT fences,
        # since fencing perturbs the very throughput being measured.
        self.fences = self.timer.enabled

    # -- stage timers ---------------------------------------------------
    def scope(self, name: str):
        return self.timer.scope(name)

    def enable(self) -> None:
        self.timer.enable()

    def disable(self) -> None:
        self.timer.disable()

    @property
    def enabled(self) -> bool:
        return self.timer.enabled

    def fence(self) -> bool:
        """True when stage boundaries should block_until_ready."""
        return self.timer.enabled and self.fences

    # -- counters / gauges ---------------------------------------------
    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self.counters[name] += n
            return self.counters[name]

    def count(self, name: str) -> int:
        return self.counters.get(name, 0)

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    # -- histograms -----------------------------------------------------
    def observe(self, name: str, value: float) -> None:
        """Record one sample into a bounded histogram reservoir."""
        with self._lock:
            self.hist_counts[name] += 1
            vals = self.hist_values[name]
            vals.append(float(value))
            if len(vals) > kHistCap:
                del vals[:len(vals) - kHistCap]

    def percentile(self, name: str, q: float) -> float:
        """Linear-interpolated percentile over the reservoir (numpy's
        default method); 0.0 when nothing was observed."""
        with self._lock:
            vals = sorted(self.hist_values.get(name, ()))
        return self._percentile_of(vals, q)

    @staticmethod
    def _percentile_of(vals: list, q: float) -> float:
        if not vals:
            return 0.0
        k = (len(vals) - 1) * (q / 100.0)
        f = int(k)
        c = min(f + 1, len(vals) - 1)
        return vals[f] + (vals[c] - vals[f]) * (k - f)

    # -- aggregation ----------------------------------------------------
    def phases(self) -> Dict[str, Dict[str, float]]:
        """Machine-readable stage table: {stage: {seconds, calls}} —
        what BENCH JSON publishes as its ``phases`` dict."""
        return {name: {"seconds": round(self.timer.totals[name], 6),
                       "calls": self.timer.counts[name]}
                for name in self.timer.totals}

    def snapshot(self) -> Dict:
        # histograms snapshot under the lock: a serving worker's first
        # observe() of a new name must not resize the dict mid-iteration
        with self._lock:
            hist_data = {name: (self.hist_counts[name], sorted(vals))
                         for name, vals in self.hist_values.items()}
            counters = dict(self.counters)
        return {"phases": self.phases(),
                "counters": counters,
                "gauges": dict(self.gauges),
                "hists": {name: {
                    "count": count,
                    "p50": round(self._percentile_of(vals, 50), 6),
                    "p99": round(self._percentile_of(vals, 99), 6)}
                    for name, (count, vals) in hist_data.items()}}

    def print_summary(self) -> None:
        self.timer.print_summary()

    def reset(self) -> None:
        self.timer.reset()
        with self._lock:
            self.counters.clear()
            self.hist_values.clear()
            self.hist_counts.clear()
        self.gauges.clear()


registry = MetricsRegistry()


def scoped(name: str):
    """Decorator form of ``registry.scope`` — the FunctionTimer analogue
    for whole functions."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with registry.scope(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco


@atexit.register
def _print_at_exit() -> None:
    if registry.timer.enabled:
        registry.timer.print_summary()


def start_device_trace(logdir: str) -> None:
    """Start a jax profiler trace (device timeline → TensorBoard)."""
    import jax.profiler
    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax.profiler
    jax.profiler.stop_trace()
