"""Compact binary trace segment format (``.ctrace``).

Chrome-trace JSON spends most of a segment's bytes repeating the same
strings: every span re-spells its stage name, every event re-spells
``"ph"``/``"pid"``/``"args"``/the trace id. For a week-long streaming
run (ROADMAP: a Perfetto-protobuf-like format would shrink disk 3-5x)
that redundancy is the disk bill. This codec removes it while staying
LOSSLESS for the JSON-able event dicts the spool writes:

- every distinct string (keys and values alike) is interned ONCE per
  segment, in first-use order, as an inline string-definition record —
  the decoder rebuilds the table by reading records in order, so there
  is no separate table section to seek to and a truncated file is
  still detectable;
- integers are LEB128 varints (zigzag for negatives), floats are raw
  IEEE-754 doubles (8 bytes, exact round-trip), bools/None are single
  tags, dicts/lists recurse;
- the file is self-describing: an 8-byte magic+version, a varint-length
  JSON header (the segment's ``otherData`` — trace_id, rank, run_id,
  counts), then a varint event count followed by exactly that many
  event records. A reader that hits EOF early, or a header promising
  more events than the records deliver, reports truncation instead of
  returning a silently short trace.

Stdlib-only with NO package-relative imports, for the same reason as
:mod:`obs.openmetrics`: ``tools/trace_report.py`` loads this file by
path to ``convert``/``validate``/``merge``/``tail`` compact segments
without importing jax. The streaming writer side lives in
:mod:`obs.trace` (``LIGHTGBM_TPU_TRACE_FORMAT=compact``), which feeds
:class:`SegmentEncoder` incrementally so memory stays bounded at the
encoded bytes of the open segment — same contract as the JSON spool.
"""
from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional, Tuple

MAGIC = b"LGTPUCT1"
EXTENSION = ".ctrace"
# record kinds
kRecString = 0x01   # varint len + utf-8 bytes; defines the next id
kRecEvent = 0x02    # one tagged value (the event dict)
# value tags
kTagStr = 0x10      # varint interned-string id
kTagInt = 0x11      # zigzag varint
kTagF64 = 0x12      # 8 raw little-endian IEEE-754 bytes
kTagTrue = 0x13
kTagFalse = 0x14
kTagNull = 0x15
kTagDict = 0x16     # varint n + n * (varint key-string-id, value)
kTagList = 0x17     # varint n + n * value

_pack_f64 = struct.Struct("<d").pack
_unpack_f64 = struct.Struct("<d").unpack_from


def _normalize(v):
    """Canonicalize to the JSON value model (what json.dumps would
    have written): dict keys become strings, tuples become lists,
    anything exotic degrades to ``str(v)`` — the spool only carries
    JSON-able dicts (events.py coerces), so this is a safety net, not
    a fidelity loss vs the JSON format."""
    if isinstance(v, bool) or v is None \
            or isinstance(v, (int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _normalize(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_normalize(x) for x in v]
    return str(v)


def _write_varint(buf: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else (n << 1)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class SegmentEncoder:
    """Incremental event-stream encoder for one segment.

    ``add_event`` appends records to an internal buffer;
    ``segment_bytes(header)`` assembles the final file image. The
    string table is embedded in the record stream, so the buffer is
    already its final on-disk form — ``encoded_size`` is the exact
    byte cost so far, which is what the spool's size-based rotation
    check needs (the JSON spool sums serialized line lengths the same
    way)."""

    def __init__(self) -> None:
        self._strings: Dict[str, int] = {}
        self._buf = bytearray()
        self.n_events = 0

    @property
    def encoded_size(self) -> int:
        return len(self._buf)

    def _intern(self, s: str) -> int:
        sid = self._strings.get(s)
        if sid is None:
            sid = len(self._strings)
            self._strings[s] = sid
            raw = s.encode("utf-8")
            self._buf.append(kRecString)
            _write_varint(self._buf, len(raw))
            self._buf += raw
        return sid

    def _intern_strings(self, v) -> None:
        """Pre-pass: define every string of ``v`` BEFORE the event
        record opens. Definition records may only sit at record
        boundaries — a definition interleaved inside a dict/list body
        would land where the decoder expects a value tag."""
        if isinstance(v, str):
            self._intern(v)
        elif isinstance(v, dict):
            for k, x in v.items():
                self._intern(k)
                self._intern_strings(x)
        elif isinstance(v, list):
            for x in v:
                self._intern_strings(x)

    def _value(self, v) -> None:
        buf = self._buf
        # bool before int: isinstance(True, int) is True
        if isinstance(v, bool):
            buf.append(kTagTrue if v else kTagFalse)
        elif isinstance(v, str):
            sid = self._strings[v]  # pre-interned
            buf.append(kTagStr)
            _write_varint(buf, sid)
        elif isinstance(v, int):
            buf.append(kTagInt)
            _write_varint(buf, _zigzag(v))
        elif isinstance(v, float):
            buf.append(kTagF64)
            buf += _pack_f64(v)
        elif v is None:
            buf.append(kTagNull)
        elif isinstance(v, dict):
            buf.append(kTagDict)
            _write_varint(buf, len(v))
            for k, x in v.items():
                _write_varint(buf, self._strings[k])
                self._value(x)
        else:  # list (normalized)
            buf.append(kTagList)
            _write_varint(buf, len(v))
            for x in v:
                self._value(x)

    def add_event(self, ev: dict) -> None:
        ev = _normalize(ev)
        self._intern_strings(ev)
        self._buf.append(kRecEvent)
        self._value(ev)
        self.n_events += 1

    def segment_bytes(self, header: dict) -> bytes:
        """The complete self-describing file image: magic, header JSON,
        event count, records."""
        hdr = json.dumps(header, sort_keys=True).encode("utf-8")
        out = bytearray(MAGIC)
        _write_varint(out, len(hdr))
        out += hdr
        _write_varint(out, self.n_events)
        out += self._buf
        return bytes(out)


def encode_events(events: List[dict],
                  header: Optional[dict] = None) -> bytes:
    """One-shot encode (bench shrink measurement, tests)."""
    enc = SegmentEncoder()
    for ev in events:
        enc.add_event(ev)
    return enc.segment_bytes(header or {})


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0) -> None:
        self.data = data
        self.pos = pos

    def byte(self) -> int:
        if self.pos >= len(self.data):
            raise ValueError("truncated compact segment "
                             "(unexpected EOF at byte %d)" % self.pos)
        b = self.data[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        shift = 0
        n = 0
        while True:
            b = self.byte()
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7
            if shift > 63:
                raise ValueError("varint overflow at byte %d" % self.pos)

    def raw(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("truncated compact segment "
                             "(unexpected EOF at byte %d)" % self.pos)
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out


def _read_value(r: _Reader, strings: List[str]):
    tag = r.byte()
    if tag == kTagStr:
        sid = r.varint()
        if sid >= len(strings):
            raise ValueError("undefined string id %d at byte %d"
                             % (sid, r.pos))
        return strings[sid]
    if tag == kTagInt:
        return _unzigzag(r.varint())
    if tag == kTagF64:
        return _unpack_f64(r.raw(8))[0]
    if tag == kTagTrue:
        return True
    if tag == kTagFalse:
        return False
    if tag == kTagNull:
        return None
    if tag == kTagDict:
        n = r.varint()
        out = {}
        for _ in range(n):
            sid = r.varint()
            if sid >= len(strings):
                raise ValueError("undefined string id %d at byte %d"
                                 % (sid, r.pos))
            out[strings[sid]] = _read_value(r, strings)
        return out
    if tag == kTagList:
        n = r.varint()
        return [_read_value(r, strings) for _ in range(n)]
    raise ValueError("unknown value tag 0x%02x at byte %d"
                     % (tag, r.pos - 1))


def decode_segment(data: bytes) -> Tuple[dict, List[dict]]:
    """``(header, events)`` from one compact segment image. Raises
    ValueError on a bad magic, a truncated stream, or an event count
    mismatch — a crash mid-write must be DETECTED, not silently
    shortened (the atomic tmp+rename finalize means a finalized
    ``.ctrace`` never trips this; only a torn copy does)."""
    if data[:len(MAGIC)] != MAGIC:
        raise ValueError("not a compact trace segment "
                         "(bad magic %r)" % data[:len(MAGIC)])
    r = _Reader(data, len(MAGIC))
    hdr_len = r.varint()
    try:
        header = json.loads(r.raw(hdr_len).decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError("corrupt compact segment header: %s" % e)
    n_events = r.varint()
    strings: List[str] = []
    events: List[dict] = []
    while len(events) < n_events:
        kind = r.byte()
        if kind == kRecString:
            n = r.varint()
            strings.append(r.raw(n).decode("utf-8"))
        elif kind == kRecEvent:
            events.append(_read_value(r, strings))
        else:
            raise ValueError("unknown record kind 0x%02x at byte %d"
                             % (kind, r.pos - 1))
    if r.pos != len(r.data):
        raise ValueError("trailing garbage after %d events (%d bytes)"
                         % (n_events, len(r.data) - r.pos))
    return header, events


def read_segment(path: str) -> dict:
    """Load one ``.ctrace`` file as the SAME Chrome-trace document the
    JSON spool writes (metadata events first, ``otherData`` = header):
    the lossless convert target, and what trace_report's validate /
    merge / summarize consume without knowing the format exists."""
    with open(path, "rb") as f:
        data = f.read()
    header, events = decode_segment(data)
    # the JSON writer puts lane-metadata events first; the incremental
    # encoder appends them at finalize (lanes are only known then), so
    # restore the convention here — consumers dedupe metadata by value,
    # not position, but byte-for-byte doc parity keeps convert trivial
    meta = [e for e in events if isinstance(e, dict) and e.get("ph") == "M"]
    rest = [e for e in events
            if not (isinstance(e, dict) and e.get("ph") == "M")]
    return {"traceEvents": meta + rest, "displayTimeUnit": "ms",
            "otherData": header}


def is_compact_file(path: str) -> bool:
    if path.endswith(EXTENSION):
        return True
    try:
        with open(path, "rb") as f:
            return f.read(len(MAGIC)) == MAGIC
    except OSError:
        return False
