"""Fleet metrics gateway: one scrape target for every rank and replica.

A multi-process run (parallel/dtrain.py ranks, a serving fleet of
PredictServers) has no single process that can answer ``/metrics`` for
the whole job — each process owns only its registry. Pull-per-process
does not compose: ranks live on different hosts behind a scheduler, and
the ROADMAP flags exactly this gap ("multi-process dtrain RANKS:
per-rank listeners or a push gateway"). This module is the push half:

- :class:`SnapshotPusher` — a per-process daemon thread that renders
  the local registry (``obs.export.render_openmetrics``) and POSTs it
  to the gateway every ``interval`` seconds (plus once at exit).
  Transient failures retry via ``utils/retry.retry_call`` (site
  ``gateway_push``, fault-injectable); a DEAD gateway degrades to
  skip + ``ft/gateway_push_failed`` counter — training never blocks
  on telemetry, same contract as every other sink.
- :class:`MetricsGateway` — a stdlib ThreadingHTTPServer accepting
  ``POST /push?rank=R&process=P&run_id=I`` (OpenMetrics text body,
  parsed STRICTLY — malformed pushes get HTTP 400, not silent
  aggregation) and serving:

  - ``GET /metrics``  — every push re-rendered as ONE document, each
    sample tagged ``{rank="R",process="P"}``, families contiguous
    under one ``# TYPE``, plus gateway-own families (push ages,
    push counts, ``run_info``). Round-trips through
    ``parse_openmetrics`` — the fleet tests and
    ``tools/tpu_phase_timer.py --from-metrics`` read it back.
  - ``GET /healthz``  — per-source push staleness (``age_s`` vs
    ``stale_after_s``), run ids, and the fleet watchdog's currently
    breached rules.

  Every push and every ``/healthz`` evaluates the FLEET watchdog
  (``obs.health.fleet_rules``: ``rank_skew``, ``dead_rank``,
  ``fleet_shed_rate``) over a snapshot synthesized from the aggregated
  pushes — same once-per-breach + re-arm contract as the per-process
  rules, with ``health`` events emitted at the gateway process where
  an operator's event log actually is.

Run correlation: the pusher stamps ``obs.events.run_id()`` (the
``LIGHTGBM_TPU_RUN_ID`` value, generated once and exported to the
environment so spawned ranks inherit it) into every push;
``tools/trace_report.py fleet`` joins a gateway metrics dump with a
trace-segment directory into one per-rank run report.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from . import events as _events
from . import faults
from .openmetrics import (kPrefix, parse_openmetrics, parse_type_headers,
                          _esc, _fmt, _lbl)
from .registry import registry
from ..utils import log

_ENV_GATEWAY = "LIGHTGBM_TPU_METRICS_GATEWAY"
_ENV_PUSH_INTERVAL = "LIGHTGBM_TPU_METRICS_PUSH_INTERVAL"
_ENV_PUSH_TIMEOUT = "LIGHTGBM_TPU_GATEWAY_TIMEOUT_S"
_ENV_STALE = "LIGHTGBM_TPU_WATCH_PUSH_STALE_S"

kDefaultPushIntervalS = 5.0
kDefaultPushTimeoutS = 5.0
kDefaultStaleS = 30.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _Push:
    """One source's latest push (the gateway keeps last-value-wins per
    (rank, process) — OpenMetrics counters are cumulative, so history
    lives in the samples, not in the gateway)."""

    __slots__ = ("text", "parsed", "types", "ts", "run_id", "pushes")

    def __init__(self, text: str, parsed: dict, types: dict,
                 run_id: str) -> None:
        self.text = text
        self.parsed = parsed
        self.types = types
        self.ts = time.time()
        self.run_id = run_id
        self.pushes = 1


class MetricsGateway:
    """Aggregating push endpoint + fleet watchdog host. ``port=0``
    binds an ephemeral port (read ``.port`` / ``.url`` back); serves
    from daemon threads; handlers never raise into the socket loop."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 reg=registry, watchdog=None,
                 stale_after_s: Optional[float] = None) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        if watchdog is None:
            from .health import Watchdog, fleet_rules
            watchdog = Watchdog(reg, rules=fleet_rules())
        self.reg = reg
        self.watchdog = watchdog
        self.stale_after_s = (stale_after_s if stale_after_s is not None
                              else _env_float(_ENV_STALE, kDefaultStaleS))
        self._pushes: Dict[Tuple[str, str], _Push] = {}
        self._lock = threading.Lock()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — http.server API
                try:
                    if self.path.split("?")[0] != "/push":
                        self.send_error(404)
                        return
                    import urllib.parse
                    q = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n).decode("utf-8",
                                                     errors="replace")
                    status, msg = outer.accept_push(
                        body,
                        rank=q.get("rank", ["0"])[0],
                        process=q.get("process", ["?"])[0],
                        run_id=q.get("run_id", [""])[0])
                except Exception:
                    self.send_error(500)
                    return
                out = (msg + "\n").encode()
                self.send_response(status)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    route = self.path.split("?")[0]
                    if route == "/metrics":
                        body = outer.render().encode()
                        ctype = "text/plain; charset=utf-8"
                    elif route == "/healthz":
                        body = (json.dumps(outer.healthz())
                                + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception:
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # pushes must not spam stderr
                pass

        # LOCKTRACE hook: wrap _lock before the serving thread exists
        from ..utils import locktrace
        locktrace.maybe_trace(self)
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-metrics-gateway", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return "http://%s:%d" % (self.host, self.port)

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- ingestion ------------------------------------------------------
    def accept_push(self, text: str, rank: str, process: str,
                    run_id: str = "") -> Tuple[int, str]:
        """Validate + store one push; returns (http_status, message).
        Strict parse: a malformed body is the PUSHER's bug and must
        surface as a 400 at push time, not as garbage in every
        subsequent scrape."""
        try:
            parsed = parse_openmetrics(text)
        except ValueError as e:
            self.reg.inc("gateway/rejected")
            return 400, "malformed OpenMetrics body: %s" % e
        types = parse_type_headers(text)
        key = (str(rank), str(process))
        with self._lock:
            prev = self._pushes.get(key)
            push = _Push(text, parsed, types, run_id)
            if prev is not None:
                push.pushes = prev.pushes + 1
            self._pushes[key] = push
        self.reg.inc("gateway/pushes")
        self.reg.inc("gateway/push_bytes", len(text))
        self._evaluate()
        return 200, "ok"

    # -- fleet snapshot + watchdog --------------------------------------
    def fleet_snapshot(self) -> dict:
        """The synthetic snapshot ``obs.health.fleet_rules`` evaluates:
        one entry per push source with its age and the fleet-relevant
        aggregates pre-extracted from the parsed samples."""
        now = time.time()
        with self._lock:
            items = sorted(self._pushes.items())
        ranks: Dict[str, dict] = {}
        for (rank, process), p in items:
            stage_s = sum(v for (n, _l), v in p.parsed.items()
                          if n == kPrefix + "stage_seconds_total")
            shed = sum(v for (n, _l), v in p.parsed.items()
                       if n == kPrefix + "serve_shed_total")
            reqs = sum(v for (n, _l), v in p.parsed.items()
                       if n == kPrefix + "serve_requests_total")
            ranks["%s/%s" % (rank, process)] = {
                "rank": rank, "process": process,
                "age_s": max(now - p.ts, 0.0),
                "stage_seconds": stage_s,
                "shed_total": shed, "requests": reqs,
                "run_id": p.run_id, "pushes": p.pushes,
            }
        return {"fleet": {"ranks": ranks,
                          "stale_after_s": self.stale_after_s}}

    def _evaluate(self) -> None:
        try:
            self.watchdog.evaluate(self.fleet_snapshot())
        except Exception:
            pass

    # -- rendering ------------------------------------------------------
    def render(self) -> str:
        """ONE OpenMetrics document for the whole fleet: every pushed
        sample re-rendered with ``{rank=,process=}`` injected (pushed
        rank/process labels, if any, are superseded — the gateway's
        source identity wins), one contiguous family per name, plus
        gateway-own families."""
        now = time.time()
        with self._lock:
            items = sorted(self._pushes.items())
        fams: Dict[str, dict] = {}
        for (rank, process), p in items:
            extra = (("process", process), ("rank", rank))
            for (name, labels), v in sorted(p.parsed.items()):
                kept = tuple((k, x) for k, x in labels
                             if k not in ("rank", "process"))
                fam = fams.setdefault(name, {"type": None, "samples": []})
                if p.types.get(name):
                    fam["type"] = p.types[name]
                fam["samples"].append(
                    (tuple(sorted(kept + extra)), v))
        out = []
        for name in sorted(fams):
            fam = fams[name]
            if fam["type"]:
                out.append("# TYPE %s %s" % (name, fam["type"]))
            for labels, v in fam["samples"]:
                out.append("%s%s %s" % (name, _lbl(labels), _fmt(v)))
        # gateway-own families: per-source freshness + run correlation
        if items:
            m = kPrefix + "gateway_push_age_seconds"
            out.append("# TYPE %s gauge" % m)
            for (rank, process), p in items:
                out.append("%s%s %s" % (
                    m, _lbl((("process", process), ("rank", rank))),
                    _fmt(round(max(now - p.ts, 0.0), 3))))
            m = kPrefix + "gateway_pushes_total"
            out.append("# TYPE %s counter" % m)
            for (rank, process), p in items:
                out.append("%s%s %s" % (
                    m, _lbl((("process", process), ("rank", rank))),
                    _fmt(p.pushes)))
            m = kPrefix + "gateway_sources"
            out.append("# TYPE %s gauge" % m)
            out.append("%s %d" % (m, len(items)))
            m = kPrefix + "run_info"
            out.append("# TYPE %s gauge" % m)
            for rid in sorted({p.run_id for _k, p in items if p.run_id}):
                out.append('%s{run_id="%s"} 1' % (m, _esc(rid)))
        out.append("# EOF")
        return "\n".join(out) + "\n"

    def healthz(self) -> dict:
        """Fleet liveness: per-source staleness + breached rules. A
        scrape is also a watchdog tick — ``dead_rank`` must fire even
        when the dead rank (by definition) stops pushing."""
        self._evaluate()
        snap = self.fleet_snapshot()["fleet"]
        stale = sorted(k for k, e in snap["ranks"].items()
                       if e["age_s"] >= self.stale_after_s)
        for e in snap["ranks"].values():
            e["age_s"] = round(e["age_s"], 3)
            e["stale"] = e["age_s"] >= self.stale_after_s
        return {"ranks": snap["ranks"], "stale": stale,
                "num_sources": len(snap["ranks"]),
                "stale_after_s": self.stale_after_s,
                "run_ids": sorted({e["run_id"]
                                   for e in snap["ranks"].values()
                                   if e["run_id"]}),
                "breached": self.watchdog.breached()}


# ----------------------------------------------------------------------
# push side
# ----------------------------------------------------------------------

class SnapshotPusher:
    """Per-process push loop: render the local registry, POST it to the
    gateway, repeat every ``interval`` seconds (``interval=0`` disables
    the thread — pushes then happen only via :meth:`push_now` and the
    atexit final push).

    The POST goes through ``retry_call(site="gateway_push")`` —
    bounded attempts, seeded backoff, ``ft/retries/gateway_push``
    accounting, and the ``gateway_push`` fault-injection gate. A push
    that still fails is SKIPPED with ``ft/gateway_push_failed`` + one
    warning per outage (not one per interval): the next tick pushes a
    fresh snapshot anyway, because counters are cumulative — a lost
    push costs staleness, never correctness, and training NEVER blocks
    on the gateway (the loop runs on a daemon thread and push_now's
    wall time is bounded by attempts x timeout)."""

    def __init__(self, url: str, interval: Optional[float] = None,
                 reg=registry, rank: Optional[int] = None,
                 role: str = "proc",
                 timeout_s: Optional[float] = None) -> None:
        self.url = url.rstrip("/")
        self.interval = (interval if interval is not None
                         else _env_float(_ENV_PUSH_INTERVAL,
                                         kDefaultPushIntervalS))
        self.interval = max(float(self.interval), 0.0)
        self.reg = reg
        self.rank = rank
        self.process = "%s:%d" % (role, os.getpid())
        self.timeout_s = (timeout_s if timeout_s is not None
                          else _env_float(_ENV_PUSH_TIMEOUT,
                                          kDefaultPushTimeoutS))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._atexit_registered = False
        self._warned = False

    def _resolve_rank(self) -> int:
        """The rank label: explicit, else the trace layer's process
        index (dtrain pins it; jax.process_index when initialized)."""
        if self.rank is not None:
            return int(self.rank)
        from . import trace as _trace
        return _trace.process_index()

    def start(self) -> "SnapshotPusher":
        if self.interval > 0 and (self._thread is None
                                  or not self._thread.is_alive()):
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="obs-gateway-pusher", daemon=True)
            self._thread.start()
        if not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(self.push_now)
        return self

    def stop(self) -> None:
        """Stop the loop AND detach the atexit push — a stopped
        (replaced) pusher must not report post-stop registry state as
        this process's final word."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._atexit_registered:
            self._atexit_registered = False
            try:
                atexit.unregister(self.push_now)
            except Exception:
                pass

    def push_now(self) -> bool:
        """One render + POST through the retry/fault plane; True on
        success. Never raises."""
        try:
            import http.client
            import urllib.parse
            import urllib.request

            from .export import render_openmetrics
            from ..utils.retry import retry_call
            text = render_openmetrics(self.reg).encode("utf-8")
            rank = self._resolve_rank()
            full = "%s/push?%s" % (self.url, urllib.parse.urlencode(
                {"rank": rank, "process": self.process,
                 "run_id": _events.run_id()}))

            def _post():
                faults.check("gateway_push", url=self.url, rank=rank)
                req = urllib.request.Request(
                    full, data=text, method="POST",
                    headers={"Content-Type":
                             "application/openmetrics-text"})
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    resp.read()

            # HTTPException (torn response) is not an OSError but is
            # just as transient; URLError already subclasses OSError
            retry_call(_post, site="gateway_push", reg=self.reg,
                       retry_on=(OSError, http.client.HTTPException))
            self.reg.inc("gateway/pushes_sent")
            self._warned = False
            return True
        except Exception as e:
            self.reg.inc("ft/gateway_push_failed")
            if not self._warned:
                self._warned = True
                log.warning("metrics push to %s failed (%r) — skipping "
                            "until the gateway recovers" % (self.url, e))
            return False

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.push_now()
