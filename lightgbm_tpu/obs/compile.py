"""XLA compile / retrace tracking.

``jax.jit`` re-runs the wrapped Python body once per new static
signature — every execution of the body IS a trace (and, absent a
compilation-cache hit, a compile). Wrapping the body with
:func:`traced` therefore counts compilations per function without
reaching into jax internals, and surfaces unexpected retraces: a
function that keeps re-tracing is burning compile time the device
trace will never show. The recorded seconds cover the Python trace
only — XLA lowering + backend compilation happen after the body
returns, so ``trace_seconds`` is a lower bound / proxy, not the full
compile cost (which on a remote TPU can be 100x the trace).

The per-name counters live in the metrics registry under
``jit_trace/<name>``; each trace also emits a ``jit_trace`` event.
The learners legitimately compile several shape variants (the serial
learner's ~log2(N) gather buckets), so the retrace warning fires only
past ``LIGHTGBM_TPU_RETRACE_WARN`` traces of one name (default 32;
0 disables).
"""
from __future__ import annotations

import functools
import os
import time
from typing import Callable

from ..utils import log
from . import events
from .registry import registry

_WARNED = set()


def _warn_threshold() -> int:
    try:
        return int(os.environ.get("LIGHTGBM_TPU_RETRACE_WARN", "32"))
    except ValueError:
        return 32


def record_trace(name: str, seconds: float = 0.0) -> int:
    """Count one trace/compile of ``name``; returns the cumulative
    count. ``seconds`` is the Python-trace wall time (a lower bound on
    the compile cost — see module docstring); it aggregates under the
    ``jit::<name>`` stage regardless of the TIMETAG gate so the retrace
    evidence survives into BENCH phases."""
    n = registry.inc("jit_trace/" + name)
    registry.timer.totals["jit::" + name] += seconds
    registry.timer.counts["jit::" + name] += 1
    events.emit("jit_trace", fn=name, count=n,
                trace_seconds=round(seconds, 6))
    thr = _warn_threshold()
    if thr and n == thr + 1 and name not in _WARNED:
        _WARNED.add(name)
        log.warning("jit function %r traced %d times — unexpected "
                    "retraces? (threshold LIGHTGBM_TPU_RETRACE_WARN=%d)"
                    % (name, n, thr))
    return n


def traced(name: str) -> Callable:
    """Decorator for a function about to be ``jax.jit``-ed: the wrapper
    records a trace each time the Python body runs (i.e. each
    compilation), timing the trace itself. Positional-argument
    passthrough keeps ``donate_argnums``/``static_argnums`` indices
    valid."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                record_trace(name, time.perf_counter() - t0)
        return wrapper
    return deco


def trace_count(name: str) -> int:
    return registry.count("jit_trace/" + name)


def trace_counts() -> dict:
    prefix = "jit_trace/"
    return {k[len(prefix):]: v for k, v in registry.counters.items()
            if k.startswith(prefix)}
