"""XLA compile / retrace tracking + compile-cost capture.

``jax.jit`` re-runs the wrapped Python body once per new static
signature — every execution of the body IS a trace (and, absent a
compilation-cache hit, a compile). Wrapping the body with
:func:`traced` therefore counts compilations per function without
reaching into jax internals, and surfaces unexpected retraces: a
function that keeps re-tracing is burning compile time the device
trace will never show. The recorded seconds cover the Python trace
only — XLA lowering + backend compilation happen after the body
returns, so ``trace_seconds`` is a lower bound / proxy, not the full
compile cost (which on a remote TPU can be 100x the trace).

:func:`instrument_jit` goes further: it owns the ``jax.jit`` call and,
when cost capture is on (``LIGHTGBM_TPU_COMPILE_COST=1`` or an active
span trace), runs ``jit(...).lower(args).cost_analysis()`` for every
call that actually compiled — the ``jit_trace`` event then carries
FLOPs, bytes accessed, and the HLO module text size, so the compile
boundary is costed, not just counted. Compiles are detected by the
deferred trace records the call itself produced, so steady-state
(cache-hit) dispatches pay no signature hashing; the explicit
re-lowering hits jax's shared jaxpr cache and re-runs nothing.

The per-name counters live in the metrics registry under
``jit_trace/<name>``; each trace also emits a ``jit_trace`` event.
The learners legitimately compile several shape variants (the serial
learner's ~log2(N) gather buckets), so the retrace warning fires only
past ``LIGHTGBM_TPU_RETRACE_WARN`` traces of one name (default 32;
0 disables). The warned-name dedup set resets with ``registry.reset()``
so repeated runs in one process warn again.
"""
from __future__ import annotations

import functools
import os
import threading
import time
from typing import Callable, Dict

from ..utils import log
from . import events
from .registry import add_reset_hook, registry

_WARNED = set()


def reset_warned() -> None:
    """Clear the retrace-warning dedup set (also wired into
    ``registry.reset()`` below)."""
    _WARNED.clear()


add_reset_hook(reset_warned)

# While instrument_jit lowers explicitly for cost analysis, trace
# records are DEFERRED (stashed on _tls.defer) and replayed once the
# cost is known — the lowering IS the trace (jax shares the jaxpr cache
# between .lower() and the call), so counting it twice or before the
# cost exists would both be wrong. The captured cost_analysis results
# hand off through _tls.pending (capture and replay happen on the SAME
# thread; a shared name-keyed dict would let two threads compiling the
# same fn swap each other's FLOPs).
_tls = threading.local()


def _pending(create: bool = False) -> Dict[str, dict]:
    pending = getattr(_tls, "pending", None)
    if pending is None:
        pending = {}
        if create:
            _tls.pending = pending
    return pending


def _warn_threshold() -> int:
    try:
        return int(os.environ.get("LIGHTGBM_TPU_RETRACE_WARN", "32"))
    except ValueError:
        return 32


def record_trace(name: str, seconds: float = 0.0,
                 ended_at: float = None) -> int:
    """Count one trace/compile of ``name``; returns the cumulative
    count. ``seconds`` is the Python-trace wall time (a lower bound on
    the compile cost — see module docstring); it aggregates under the
    ``jit::<name>`` stage regardless of the TIMETAG gate so the retrace
    evidence survives into BENCH phases. ``ended_at`` (unix seconds) is
    set on deferred replays: the trace actually finished back then, and
    the span exporter must place the compile span at its true time, not
    at replay time."""
    deferred = getattr(_tls, "defer", None)
    if deferred is not None:
        deferred.append((name, seconds, time.time()))
        return registry.count("jit_trace/" + name)
    n = registry.inc("jit_trace/" + name)
    registry.timer.record("jit::" + name, seconds)
    extra = _pending(create=False).pop(name, None) or {}
    if ended_at is not None:
        extra["ended_ts"] = round(ended_at, 6)
    events.emit("jit_trace", fn=name, count=n,
                trace_seconds=round(seconds, 6), **extra)
    thr = _warn_threshold()
    if thr and n == thr + 1 and name not in _WARNED:
        _WARNED.add(name)
        log.warning("jit function %r traced %d times — unexpected "
                    "retraces? (threshold LIGHTGBM_TPU_RETRACE_WARN=%d)"
                    % (name, n, thr))
    return n


def traced(name: str) -> Callable:
    """Decorator for a function about to be ``jax.jit``-ed: the wrapper
    records a trace each time the Python body runs (i.e. each
    compilation), timing the trace itself. Positional-argument
    passthrough keeps ``donate_argnums``/``static_argnums`` indices
    valid."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                record_trace(name, time.perf_counter() - t0)
        return wrapper
    return deco


# ----------------------------------------------------------------------
# compile-cost capture
# ----------------------------------------------------------------------

# obs.trace resolved once (same rule as registry's jax.profiler):
# cost_capture_enabled sits on every instrumented dispatch and must not
# pay import machinery per call
_trace_mod = None


def _get_trace():
    global _trace_mod
    if _trace_mod is None:
        from . import trace
        _trace_mod = trace
    return _trace_mod


def cost_capture_enabled() -> bool:
    """On under ``LIGHTGBM_TPU_COMPILE_COST`` (1/0 wins outright) or
    whenever the span trace is active — traces should cost their
    compile boundaries."""
    v = os.environ.get("LIGHTGBM_TPU_COMPILE_COST")
    if v is not None:
        return v.strip().lower() not in ("", "0", "false", "off")
    return _get_trace().active()


def _extract_cost(lowered) -> dict:
    cost: dict = {}
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            if "flops" in ca:
                cost["flops"] = float(ca["flops"])
            if "bytes accessed" in ca:
                cost["bytes_accessed"] = float(ca["bytes accessed"])
            # bytes/FLOP roofline position: > the hardware's balance
            # point means the program is bandwidth-bound — exactly what
            # the quantized histogram mode attacks (fewer bytes, same
            # one-hot FLOPs), so the ratio is the direct evidence of
            # the bytes moving
            if cost.get("flops", 0) > 0 and "bytes_accessed" in cost:
                cost["bytes_per_flop"] = round(
                    cost["bytes_accessed"] / cost["flops"], 6)
    except Exception:
        pass
    try:
        cost["hlo_bytes"] = len(lowered.as_text())
    except Exception:
        pass
    return cost


def _capture_cost(name: str, jitted, args, kwargs, deferred) -> None:
    """A compiling call just happened (``deferred`` holds its stashed
    trace records): re-lower — jax shares the jaxpr cache between the
    call and ``.lower()``, so this re-runs nothing — extract FLOPs /
    bytes accessed / HLO size, and replay the trace records so the
    ``jit_trace`` event carries the cost of the very compile it
    counts."""
    cost: dict = {}
    prev = getattr(_tls, "defer", None)
    _tls.defer = []  # swallow any re-trace from an older jax
    try:
        cost = _extract_cost(jitted.lower(*args, **kwargs))
    except Exception:
        pass
    finally:
        _tls.defer = prev
    if cost:
        _pending(create=True)[name] = cost
        if "flops" in cost:
            registry.gauge("compile/%s/flops" % name, cost["flops"])
        if "bytes_accessed" in cost:
            registry.gauge("compile/%s/bytes_accessed" % name,
                           cost["bytes_accessed"])
        if "hlo_bytes" in cost:
            registry.gauge("compile/%s/hlo_bytes" % name,
                           float(cost["hlo_bytes"]))
    for deferred_name, seconds, t_end in deferred:
        record_trace(deferred_name, seconds, ended_at=t_end)


def instrument_jit(name: str, fun: Callable, **jit_kwargs) -> Callable:
    """``jax.jit(traced(name)(fun), **jit_kwargs)`` plus opt-in compile
    cost capture. Drop-in replacement for the bare composition at every
    learner/serving jit site: same call signature, same donation /
    static-argument semantics (positional passthrough).

    Hot-path cost: with capture off, two env lookups per dispatch; with
    capture on, one thread-local set/restore per dispatch — the
    expensive lowering runs ONLY on calls that actually compiled (a
    fresh trace was observed), so steady-state dispatches stay
    unperturbed even while profiling."""
    import jax
    jitted = jax.jit(traced(name)(fun), **jit_kwargs)

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        if not cost_capture_enabled():
            return jitted(*args, **kwargs)
        prev = getattr(_tls, "defer", None)
        _tls.defer = deferred = []
        try:
            out = jitted(*args, **kwargs)
        except BaseException:
            _tls.defer = prev
            # the failing dispatch may be the very compile being
            # diagnosed: replay its trace records (without the cost
            # re-lowering) so the jit_trace evidence survives the crash
            try:
                for deferred_name, seconds, t_end in deferred:
                    record_trace(deferred_name, seconds, ended_at=t_end)
            except Exception:
                pass
            raise
        _tls.defer = prev
        if deferred:
            _capture_cost(name, jitted, args, kwargs, deferred)
        return out

    # AOT passthroughs: callers lower/inspect the jitted object through
    # the wrapper (tests/test_hlo_size.py lowers the learner programs at
    # synthetic scale)
    wrapper.lower = jitted.lower
    wrapper._jitted = jitted
    return wrapper


def instrument_jit_method(name: str, **jit_kwargs) -> Callable:
    """Decorator twin of :func:`instrument_jit` for methods whose
    ``self`` is the static argument — the objectives' former
    ``@partial(jax.jit, static_argnums=0)`` pattern::

        @obs_compile.instrument_jit_method("obj.binary.grads")
        def _grads(self, score, label, weights): ...

    The returned wrapper is a plain function, so class-attribute access
    still binds ``self`` (which jax then treats as the static arg);
    each objective instance compiles once per score signature and its
    compiles surface as ``jit_trace`` events like every learner site."""
    def deco(fn):
        return instrument_jit(name, fn, static_argnums=0, **jit_kwargs)
    return deco


def trace_count(name: str) -> int:
    return registry.count("jit_trace/" + name)


def trace_counts() -> dict:
    prefix = "jit_trace/"
    return {k[len(prefix):]: v for k, v in registry.counters.items()
            if k.startswith(prefix)}
