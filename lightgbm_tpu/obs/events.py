"""Structured JSON-lines event sink.

Every event is one JSON object per line::

    {"ts": 1722700000.123, "event": "train_iter",
     "run_id": "18f2a-4c1", "iter": 4, ...}

(``run_id`` — see :func:`run_id` — correlates every record of one run
across processes: ranks inherit ``LIGHTGBM_TPU_RUN_ID``.)

Two sinks, both optional and independent:

- a file, named by ``LIGHTGBM_TPU_EVENT_LOG=path`` (read per emit, so a
  late ``os.environ`` assignment still takes effect) or pinned
  programmatically with :func:`configure`;
- a Python callback registered via :func:`register_event_callback` —
  the event-stream mirror of ``log.register_log_callback``
  (reference: LGBM_RegisterLogCallback, src/c_api.cpp:904).

Emission with no sink configured is a few dict lookups — cheap enough
to leave the call sites unconditional.

File writes are BUFFERED: emits append serialized lines to an in-memory
buffer that flushes on overflow (``LIGHTGBM_TPU_EVENT_BUFFER`` lines,
default 64; 0 = write-through), at process exit (atexit), on
:func:`configure`, and on explicit :func:`flush`. This replaces the old
per-emit open/append/close (one syscall trio per event — measurable in
tight iteration loops at Higgs scale). Each buffered record remembers
the sink path active when it was emitted, so late env-var changes keep
exact per-file ordering and content; :func:`read_jsonl` flushes first,
so readers never race the buffer. Callbacks still fire synchronously
per emit — only the file sink is deferred.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

_ENV_VAR = "LIGHTGBM_TPU_EVENT_LOG"
_ENV_BUFFER = "LIGHTGBM_TPU_EVENT_BUFFER"
_ENV_RUN_ID = "LIGHTGBM_TPU_RUN_ID"

_run_id: Optional[str] = None
_run_id_env: Optional[str] = None

_callback: Optional[Callable[[Dict], None]] = None
_path_override: Optional[str] = None
_lock = threading.Lock()
_buffer: List[Tuple[str, str]] = []  # (sink path at emit time, json line)

# span-trace tap (obs/trace.py): (active_predicate, note_fn). When the
# trace sink is live every emitted event also lands in the trace as an
# instant/compile span — the trace layer rides the existing emit call
# sites without any caller changes.
_trace_tap: Optional[Tuple[Callable[[], bool],
                           Callable[[Dict], None]]] = None


def install_trace_tap(active_fn: Callable[[], bool],
                      note_fn: Callable[[Dict], None]) -> None:
    global _trace_tap
    _trace_tap = (active_fn, note_fn)


def run_id() -> str:
    """The run-correlation id stamped into every event record, trace
    segment header, and gateway push. ``LIGHTGBM_TPU_RUN_ID`` wins when
    set (re-read per call, so a late assignment — or a test
    monkeypatch — takes effect); otherwise one id is generated on
    first use and WRITTEN BACK to the environment, so subprocesses
    spawned after that point (dtrain ranks, serve workers) inherit the
    parent's id and the whole fleet's telemetry joins on one key
    (``tools/trace_report.py fleet``)."""
    global _run_id, _run_id_env
    env = os.environ.get(_ENV_RUN_ID)
    if env:
        if env != _run_id_env:
            _run_id_env = env
            _run_id = env
        return env
    if _run_id is None:
        _run_id = "%x-%x" % (int(time.time() * 1e3) & 0xFFFFFFFFFF,
                             os.getpid())
        _run_id_env = _run_id
        os.environ[_ENV_RUN_ID] = _run_id
    return _run_id


def _buffer_limit() -> int:
    try:
        return max(int(os.environ.get(_ENV_BUFFER, "64")), 1)
    except ValueError:
        return 64


def configure(path: Optional[str]) -> None:
    """Pin the event-log path programmatically (overrides the env var;
    pass None to fall back to ``LIGHTGBM_TPU_EVENT_LOG``). Flushes any
    buffered events first so readers of the previous sink are current."""
    global _path_override
    flush()
    _path_override = path


def register_event_callback(fn: Optional[Callable[[Dict], None]]) -> None:
    """Route every event dict through ``fn`` (None unregisters)."""
    global _callback
    _callback = fn


def sink_path() -> Optional[str]:
    return _path_override or os.environ.get(_ENV_VAR) or None


def _tap_active() -> bool:
    tap = _trace_tap
    if tap is None:
        return False
    try:
        return tap[0]()
    except Exception:
        return False


def enabled() -> bool:
    return (_callback is not None or sink_path() is not None
            or _tap_active())


def _jsonable(v):
    """Coerce numpy scalars / odd payloads into JSON-native types."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        import numpy as np
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.ndarray):
            return v.tolist()
    except Exception:
        pass
    return str(v)


def emit(event: str, **fields) -> Optional[Dict]:
    """Emit one structured event to every configured sink. Returns the
    event dict (or None when no sink is active). Never raises: telemetry
    must not take training down."""
    if not enabled():
        return None
    rec = {"ts": round(time.time(), 6), "event": event,
           "run_id": run_id()}
    for k, v in fields.items():
        rec[k] = _jsonable(v)
    cb = _callback
    if cb is not None:
        try:
            cb(rec)
        except Exception:
            pass
    tap = _trace_tap
    if tap is not None and _tap_active():
        try:
            tap[1](rec)
        except Exception:
            pass
    path = sink_path()
    if path is not None:
        try:
            line = json.dumps(rec)
            with _lock:
                _buffer.append((path, line))
                if len(_buffer) >= _buffer_limit():
                    _flush_locked()
        except Exception:
            pass
    return rec


def flush() -> None:
    """Write every buffered event to its file sink. Never raises —
    telemetry must not take the caller down. Registered atexit; call
    explicitly before handing a log file to an external reader."""
    with _lock:
        _flush_locked()


def _flush_locked() -> None:
    """Drain the buffer grouping CONSECUTIVE same-path records into one
    append each, so per-file line order is exactly emission order even
    when the sink path changed mid-buffer."""
    if not _buffer:
        return
    try:
        i = 0
        while i < len(_buffer):
            path = _buffer[i][0]
            j = i
            while j < len(_buffer) and _buffer[j][0] == path:
                j += 1
            try:
                with open(path, "a") as f:
                    f.write("\n".join(line for _, line in _buffer[i:j])
                            + "\n")
            except Exception:
                pass
            i = j
    finally:
        del _buffer[:]


atexit.register(flush)


def read_jsonl(path: str):
    """Parse an event-log file back into a list of event dicts (raises
    on malformed lines — the test-side round-trip check). Flushes the
    buffer first so in-process readers see everything emitted so far."""
    flush()
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
