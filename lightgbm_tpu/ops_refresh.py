"""Refresh a live learner's device-side split parameters after
``reset_parameter`` (reference: GBDT::ResetConfig →
TreeLearner::ResetConfig, serial_tree_learner.cpp). SplitParams fields are
traced values, so replacing the NamedTuple reuses the compiled kernels."""
from __future__ import annotations

from .ops.split import SplitParams


def refresh_learner_params(learner, config) -> None:
    learner.params = SplitParams.from_config(config)
    learner.max_depth = int(config.max_depth)
    if hasattr(learner, "_fused_growth"):
        # serial learner: the fused/stepped choice is re-readable (the
        # stepped path is the documented bit-parity fallback)
        learner._fused_growth = bool(
            getattr(config, "tpu_fused_tree", True))
    if hasattr(learner, "_K"):
        learner._K = max(1, min(
            int(getattr(config, "tpu_frontier_splits", 8)),
            learner.L - 1))
    if hasattr(learner, "_rebind_compiled"):
        # sharded learner: max_depth and K are STATIC keys of its
        # cached finish/kfinish/spec programs — re-resolve them (a
        # stale binding would keep gating depth at the old max_depth)
        learner._rebind_compiled()
    # jitted step closures bake the old params as constants — drop them
    # so the next tree re-traces with the new values
    if hasattr(learner, "_step_cache"):
        learner._step_cache.clear()
    if hasattr(learner, "_root_impl"):
        # mesh learners: the per-instance jits bake params/max_depth as
        # constants — drop them; train()/the adapters rebuild lazily
        for attr in ("_root_fn", "_tree_fn", "_step_fn", "_cegb_root_fn",
                     "_mono_step_fn", "_mono_root_fn", "_adv_rescan_fn",
                     "_many_fn", "_many_multi_fn"):
            if hasattr(learner, attr):
                setattr(learner, attr, None)
