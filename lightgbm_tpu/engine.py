"""Training entry points: ``train`` and ``cv``.

API-shaped after the reference's python-package/lightgbm/engine.py
(``train`` at :36 — dataset construction, callback orchestration
:204-271, update loop :252, early stop via exception; ``cv`` at :516 with
``CVBooster`` :280 and fold construction ``_make_n_folds`` :432).
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .config import Config
from .utils import log


def _pop_callable_objective(params: Dict[str, Any]):
    """Extract a callable objective from a params dict IN PLACE,
    replacing it with "none" (Config only understands strings); returns
    the callable or None. Callables can arrive via train()'s params or
    ride in on the Dataset's own params (e.g. from the sklearn
    wrapper)."""
    obj = params.get("objective")
    if callable(obj):
        params["objective"] = "none"
        return obj
    return None


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          feval: Optional[Union[Callable, List[Callable]]] = None,
          init_model: Optional[Union[str, Booster]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          checkpoint_dir: Optional[str] = None,
          checkpoint_freq: int = 0,
          resume: bool = False) -> Booster:
    """reference: engine.py:36.

    Fault tolerance (ft/checkpoint.py): with ``checkpoint_dir`` the run
    writes crash-consistent checkpoints — every ``checkpoint_freq``
    iterations when > 0, plus always one at the end — and
    ``resume=True`` restores the newest valid checkpoint before
    training, continuing BIT-identically to an uninterrupted run (same
    trees, same training scores; see docs/RELIABILITY.md for what is
    and is not covered). A killed run is re-invoked with the same
    arguments plus ``resume=True``."""
    params = dict(params or {})
    fobj = _pop_callable_objective(params)
    # num_boost_round may come via params aliases
    cfg = Config.from_params(params)
    if "num_iterations" in params or any(
            k in params for k in ("num_iteration", "n_iter", "num_tree",
                                  "num_trees", "num_round", "num_rounds",
                                  "num_boost_round", "n_estimators")):
        num_boost_round = cfg.num_iterations

    merged = dict(params, **(train_set.params or {}))
    ds_fobj = _pop_callable_objective(merged)  # always pop (Config
    fobj = fobj or ds_fobj                     # can't parse callables)
    train_set.params = merged
    train_set.construct()

    booster = Booster(params=params, train_set=train_set)
    # quality plane: a sharded/spilled dataset carries its training-grid
    # reference profile (obs/quality.py); hand it to the booster so the
    # checkpoint writer persists it (a checkpoint resume below may
    # override with the profile stored alongside the model)
    spill_profile = getattr(booster.inner.train_data,
                            "quality_profile", None)
    if spill_profile is not None:
        booster.inner.quality_profile = spill_profile
    if init_model is not None:
        init_str = (init_model.model_to_string()
                    if isinstance(init_model, Booster)
                    else open(init_model).read())
        base = Booster(params=params, model_str=init_str)
        # continued training: preload trees + replay scores
        booster.inner.models = list(base.inner.models)
        booster.inner.num_init_iteration = base.inner.current_iteration
        # text-loaded trees lost their bin-space fields; re-link them to
        # this training dataset's mappers before binned replay
        booster.inner.align_trees_to_dataset(booster.inner.train_data)
        # replay existing model onto the training scores
        import numpy as _np
        import jax.numpy as jnp
        bins = booster.inner.train_data.feature_bins()
        for i, tree in enumerate(booster.inner.models):
            k = i % booster.inner.num_tree_per_iteration
            leaf = tree.predict_by_bin(bins, *booster.inner._bin_meta)
            booster.inner.train_score = \
                booster.inner.train_score.at[:, k].add(
                    jnp.asarray(tree.leaf_value[leaf].astype(_np.float32)))
        booster.inner._has_init_score = True  # don't re-boost from average

    ckpt_state = None
    if checkpoint_dir and resume:
        from .ft import checkpoint as _ckpt
        ckpt_state = _ckpt.load_latest(booster.inner, checkpoint_dir)
        if ckpt_state is None:
            log.info("resume=True but no valid checkpoint under %s; "
                     "training from scratch" % checkpoint_dir)

    def _maybe_checkpoint(force: bool = False) -> None:
        if not checkpoint_dir:
            return
        it = booster.inner.iter
        if force or (checkpoint_freq > 0 and it > 0
                     and it % checkpoint_freq == 0):
            booster.inner.save_checkpoint(checkpoint_dir)

    def _finish_training() -> None:
        """Terminal bookkeeping shared by every return path: the final
        forced checkpoint, plus dropping the sharded learner's
        cross-iteration sweep stash (it pins one staged shard buffer
        that no further tree will consume)."""
        _maybe_checkpoint(force=True)
        rel = getattr(getattr(booster.inner, "learner", None),
                      "release_prefetch", None)
        if rel is not None:
            rel()

    valid_sets = valid_sets or []
    valid_names = valid_names or []
    for i, vs in enumerate(valid_sets):
        if vs is train_set:
            name = "training"
            continue  # handled via eval_train
        name = valid_names[i] if i < len(valid_names) else "valid_%d" % i
        vs.reference = vs.reference or train_set
        vs.params = dict(params, **(vs.params or {}))
        booster.add_valid(vs, name)
    eval_train_requested = any(vs is train_set for vs in valid_sets)

    if ckpt_state is not None:
        # the per-(valid set, metric) early-stop trackers can only be
        # re-applied once the valid sets above have registered theirs
        from .ft import checkpoint as _ckpt
        _ckpt.restore_early_stop(booster.inner, ckpt_state)
    resume_iter = booster.inner.iter if ckpt_state is not None else 0

    callbacks = list(callbacks or [])
    if cfg.early_stopping_round > 0 and not any(
            getattr(cb, "order", 0) == 30 for cb in callbacks):
        callbacks.append(callback_mod.early_stopping(
            cfg.early_stopping_round,
            first_metric_only=cfg.first_metric_only,
            verbose=cfg.verbosity >= 0))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    # checkpointable engine-level callback state: the early_stopping
    # closure exposes get_state/set_state, so a resumed run continues
    # the SAME patience window (best score/iter) instead of re-arming
    # it from the resume point. The provider rides on the booster — the
    # checkpoint writer (ft/checkpoint.py save) snapshots it under
    # state["engine"] at every checkpoint.
    stateful_cbs = [cb for cb in callbacks_after
                    if hasattr(cb, "get_state")
                    and hasattr(cb, "set_state")]
    if ckpt_state is not None and stateful_cbs:
        saved = (ckpt_state.get("engine") or {}).get("early_stopping")
        if saved:
            for cb, st in zip(stateful_cbs, saved):
                cb.set_state(st)

    def _engine_state():
        states = [cb.get_state() for cb in stateful_cbs]
        if not any(s is not None for s in states):
            return None
        return {"early_stopping": states}

    booster.inner._engine_state_provider = _engine_state

    # tpu_batch_iterations: run N iterations per device dispatch
    # (gbdt.py train_batch). Evaluation and callbacks then fire at
    # BATCH boundaries — early stopping still measures its patience in
    # iterations (env.iteration advances by N), just checked N at a
    # time. Custom objectives are excluded by can_train_batched.
    #
    # tpu_eval_iterations=k hoists evaluation further: eval + the
    # after-iteration callbacks run only when the iteration count
    # crosses a multiple of k (absolute grid, so a checkpoint-resumed
    # run evaluates at the same iterations as an uninterrupted one),
    # plus always at the final/stopping iteration. The early-stopping
    # callback still measures its patience window in iterations — k
    # only coarsens WHERE the check can fire (docs/PERFORMANCE.md
    # "Pipelined boosting" has the tolerance contract).
    eval_k = max(int(cfg.tpu_eval_iterations), 1)
    from .boosting.gbdt import eval_hoist_due
    if eval_k > 1 and (callbacks or valid_sets):
        log.info("tpu_eval_iterations=%d: evaluation/callbacks run "
                 "when the iteration count crosses a multiple of %d"
                 % (eval_k, eval_k))

    batch_n = int(cfg.tpu_batch_iterations)
    if batch_n > 1 and fobj is None:
        if callbacks or valid_sets:
            log.info("tpu_batch_iterations=%d: evaluation/callbacks "
                     "run every %d iterations (batch boundaries)"
                     % (batch_n, batch_n))
        i = resume_iter
        last_eval = resume_iter
        degraded = False
        ran_batched = False
        rechecked = False
        while i < num_boost_round and not degraded:
            for cb in callbacks_before:
                cb(callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=None))
            if (booster.inner.can_train_batched()
                    and num_boost_round - i >= batch_n):
                # full batches only: a shorter tail scan would recompile
                # the whole T-iteration program for a one-off length
                finished = booster.inner.train_batch(batch_n)
                i += batch_n
                ran_batched = True
            else:
                if (ran_batched and not rechecked
                        and cfg.use_quantized_grad):
                    # batched -> per-iteration transition of a
                    # QUANTIZED run: the scan maintained the scores on
                    # device through redrawn stochastic roundings —
                    # re-verify them once against a full tree replay
                    # before the looped path builds on them (emits a
                    # batched_eval_recheck event)
                    booster.inner.recheck_scores(
                        reason="batched_to_looped")
                    rechecked = True
                finished = booster.update(fobj=fobj)
                i += 1
                if not finished and not booster.inner.can_train_batched():
                    # permanently ineligible config: the plain loop
                    # below takes over (per-iteration evaluation) after
                    # this iteration's own evaluation below runs
                    log.warning(
                        "tpu_batch_iterations=%d ignored: the "
                        "configuration needs per-iteration host work "
                        "(per-node masks / feature_fraction / monotone "
                        "/ CEGB / linear / leaf-output renewal, a "
                        "stochastic-gradient objective, DART/RF "
                        "boosting, or a multi-process learner)"
                        % batch_n)
                    degraded = True
            eval_due = eval_hoist_due(
                i, last_eval, eval_k,
                finished or degraded or i >= num_boost_round)
            evaluation_result_list = []
            if eval_due:
                last_eval = i
                if valid_sets or eval_train_requested:
                    if eval_train_requested:
                        evaluation_result_list.extend(
                            booster.eval_train(feval))
                    evaluation_result_list.extend(
                        booster.eval_valid(feval))
                try:
                    for cb in callbacks_after:
                        cb(callback_mod.CallbackEnv(
                            model=booster, params=params,
                            iteration=i - 1,
                            begin_iteration=0,
                            end_iteration=num_boost_round,
                            evaluation_result_list=(
                                evaluation_result_list)))
                except callback_mod.EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    for item in (e.best_score or []):
                        booster.best_score.setdefault(
                            item[0], {})[item[1]] = item[2]
                    _finish_training()
                    return booster
            # checkpoint AFTER this boundary's eval + callbacks so the
            # captured callback state (early_stopping patience) is
            # exactly "everything through this iteration" — resume
            # continues at the next one
            _maybe_checkpoint()
            if finished:
                break
        if not degraded:
            if booster.best_iteration <= 0:
                booster.best_iteration = booster.current_iteration
                for item in (evaluation_result_list
                             if valid_sets and i > 0 else []):
                    booster.best_score.setdefault(
                        item[0], {})[item[1]] = item[2]
            _finish_training()
            return booster
        # fall through to the plain per-iteration loop from iteration i
        start_i = i
    else:
        start_i = resume_iter
        if batch_n > 1:
            log.warning("tpu_batch_iterations=%d ignored: a custom "
                        "objective needs per-iteration gradients"
                        % batch_n)

    evaluation_result_list = []
    for i in range(start_i, num_boost_round):
        for cb in callbacks_before:
            cb(callback_mod.CallbackEnv(
                model=booster, params=params, iteration=i,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=None))
        finished = booster.update(fobj=fobj)
        # eval hoisting: the absolute every-k grid (+ the final and any
        # stopping iteration), same contract as the batched loop above
        eval_due = eval_hoist_due(
            i + 1, i, eval_k, finished or i == num_boost_round - 1)
        if eval_due:
            evaluation_result_list = []
            if valid_sets or eval_train_requested:
                if eval_train_requested:
                    evaluation_result_list.extend(
                        booster.eval_train(feval))
                evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in callbacks_after:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=0,
                        end_iteration=num_boost_round,
                        evaluation_result_list=evaluation_result_list))
            except callback_mod.EarlyStopException as e:
                booster.best_iteration = e.best_iteration + 1
                for item in (e.best_score or []):
                    booster.best_score.setdefault(
                        item[0], {})[item[1]] = item[2]
                break
        # checkpoint AFTER eval + callbacks: the captured callback
        # state (early_stopping patience) then covers exactly the
        # iterations the resumed run will not replay
        _maybe_checkpoint()
        if finished:
            break
    if booster.best_iteration <= 0:
        booster.best_iteration = booster.current_iteration
        for item in evaluation_result_list if (valid_sets) else []:
            booster.best_score.setdefault(item[0], {})[item[1]] = item[2]
    _finish_training()
    return booster


class CVBooster:
    """Ensemble of per-fold boosters (reference: engine.py:280)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs)
                    for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params,
                  seed: int, stratified: bool, shuffle: bool):
    """reference: engine.py:432."""
    full_data.construct()
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object")
        if hasattr(folds, "split"):
            group = full_data.get_group()
            group_info = None if group is None else np.asarray(group)
            flatted_group = (np.repeat(np.arange(len(group_info)),
                                       group_info)
                             if group_info is not None
                             else np.zeros(num_data, dtype=np.int64))
            folds = folds.split(X=np.empty(num_data),
                                y=full_data.get_label(),
                                groups=flatted_group)
        return list(folds)
    rng = np.random.RandomState(seed)
    group = full_data.get_group()
    if group is not None:
        # group-aware folds: split whole queries
        group = np.asarray(group, dtype=np.int64)
        nq = len(group)
        q_order = rng.permutation(nq) if shuffle else np.arange(nq)
        q_folds = np.array_split(q_order, nfold)
        starts = np.concatenate([[0], np.cumsum(group)])
        out = []
        for qf in q_folds:
            test_idx = np.concatenate(
                [np.arange(starts[q], starts[q + 1]) for q in qf]) \
                if len(qf) else np.array([], dtype=np.int64)
            mask = np.ones(num_data, dtype=bool)
            mask[test_idx] = False
            out.append((np.where(mask)[0], test_idx))
        return out
    if stratified:
        label = np.asarray(full_data.get_label())
        idx_per_class = [np.where(label == c)[0]
                         for c in np.unique(label)]
        folds_idx = [[] for _ in range(nfold)]
        for idxs in idx_per_class:
            if shuffle:
                rng.shuffle(idxs)
            for f, chunk in enumerate(np.array_split(idxs, nfold)):
                folds_idx[f].append(chunk)
        out = []
        for f in range(nfold):
            test_idx = np.concatenate(folds_idx[f])
            mask = np.ones(num_data, dtype=bool)
            mask[test_idx] = False
            out.append((np.where(mask)[0], test_idx))
        return out
    order = rng.permutation(num_data) if shuffle else np.arange(num_data)
    chunks = np.array_split(order, nfold)
    out = []
    for test_idx in chunks:
        mask = np.ones(num_data, dtype=bool)
        mask[test_idx] = False
        out.append((np.where(mask)[0], np.sort(test_idx)))
    return out


def _agg_cv_result(raw_results):
    """reference: engine.py _agg_cv_result — mean/std over folds."""
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = "%s %s" % (one_line[0], one_line[1])
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k],
             float(np.std(v))) for k, v in cvmap.items()]


def cv(params: Dict[str, Any], train_set: Dataset,
       num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True,
       metrics: Optional[Union[str, List[str]]] = None,
       feval=None, init_model=None,
       callbacks: Optional[List[Callable]] = None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, Any]:
    """reference: engine.py:516."""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    fobj = _pop_callable_objective(params)
    cfg = Config.from_params(params)
    if cfg.objective not in ("binary", "multiclass", "multiclassova"):
        stratified = False
    merged = dict(params, **(train_set.params or {}))
    ds_fobj = _pop_callable_objective(merged)  # always pop (Config
    fobj = fobj or ds_fobj                     # can't parse callables)
    train_set.params = merged
    train_set.construct()
    folds_idx = _make_n_folds(train_set, folds, nfold, params,
                              cfg.seed, stratified, shuffle)
    cvbooster = CVBooster()
    fold_data = []
    for train_idx, test_idx in folds_idx:
        tr = train_set.subset(train_idx)
        va = train_set.subset(test_idx)
        bst = Booster(params=params, train_set=tr)
        bst._cv_train = tr
        bst.add_valid(va, "valid")
        cvbooster.append(bst)
        fold_data.append((bst, eval_train_metric))

    callbacks = list(callbacks or [])
    if cfg.early_stopping_round > 0 and not any(
            getattr(cb, "order", 0) == 30 for cb in callbacks):
        callbacks.append(callback_mod.early_stopping(
            cfg.early_stopping_round,
            first_metric_only=cfg.first_metric_only,
            verbose=cfg.verbosity >= 0))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    results = collections.defaultdict(list)
    for i in range(num_boost_round):
        for cb in callbacks_before:
            cb(callback_mod.CallbackEnv(
                model=cvbooster, params=params, iteration=i,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=None))
        raw = []
        for bst, with_train in fold_data:
            bst.update(fobj=fobj)
            one = []
            if with_train:
                one.extend(bst.eval_train(feval))
            one.extend(bst.eval_valid(feval))
            raw.append(one)
        res = _agg_cv_result(raw)
        for _, key, mean, _, std in res:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks_after:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=res))
        except callback_mod.EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for k in list(results.keys()):
                results[k] = results[k][:cvbooster.best_iteration]
            break
    out = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
