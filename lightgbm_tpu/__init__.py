"""lightgbm_tpu — a TPU-native gradient-boosted decision tree framework.

A from-scratch reimplementation of the capabilities of LightGBM
(reference mounted at /root/reference) designed for TPU execution:
histogram construction, split search and partitioning run as XLA/Pallas
programs over device-resident binned data; distributed training shards
rows over a ``jax.sharding.Mesh`` and reduces histograms with ICI
collectives. The Python surface mirrors the reference's
``lightgbm`` package (Dataset/Booster/train/cv/sklearn wrappers).
"""
from . import ft, obs, serve
from .basic import Booster, Dataset, LightGBMError
from .callback import (EarlyStopException, early_stopping, log_evaluation,
                       record_evaluation, reset_parameter)
from .config import Config
from .engine import CVBooster, cv, train
from .plotting import (create_tree_digraph, plot_importance, plot_metric,
                       plot_split_value_histogram, plot_tree)
from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "LightGBMError", "Config",
    "train", "cv", "CVBooster",
    "early_stopping", "log_evaluation", "record_evaluation",
    "reset_parameter", "EarlyStopException",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "plot_importance", "plot_metric", "plot_split_value_histogram",
    "plot_tree", "create_tree_digraph",
    "obs", "serve", "ft",
]
