"""Exclusive Feature Bundling (EFB) — sparse-feature compression.

TPU-native equivalent of the reference's feature bundling
(reference: ``Dataset::FindGroups`` src/io/dataset.cpp:107-200 greedy
conflict-aware graph coloring; ``FeatureGroup`` include/LightGBM/
feature_group.h:25 bin-offset packing). Mutually-(almost-)exclusive sparse
features share one bin column: bundle bin 0 means "every member at its
zero bin"; member j's non-zero bins occupy a contiguous sub-range in
original bin order.

Where the reference's histogram works directly on group columns and scans
per-feature slices, the TPU build keeps the downstream learner unchanged:
the [N, G] bundled matrix is histogrammed on device and the bundle
histogram is *unpacked* back to per-feature [F, B] histograms with a
static gather (ops/histogram.py unpack_bundle_histogram); a member's
zero-bin row is reconstructed as leaf_total − Σ(non-zero bins) — valid
because exclusivity means "some other member is non-zero" ⇒ "this member
is zero" (the reference's FixHistogram plays the same trick,
src/io/dataset.cpp ConstructHistogramsInner).

Only numerical, non-NaN-missing features are bundled; categorical and
NaN-carrying features keep their own columns (single-member groups use
identity mappings so the learner has one uniform code path).
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np


class BundleLayout(NamedTuple):
    """Static description of the bundled bin matrix.

    For every bundle column g, ``member[g, b]`` is the used-feature index
    owning bundle bin b (-1 for bin 0 of a multi-member bundle and for
    padding), and ``unmap[g, b]`` the original bin id of that feature.
    For single-member groups these are identity-like (member = the
    feature for every bin, unmap = b). ``needs_zero_fix[f]`` marks
    features living in multi-member bundles: their zero-bin histogram row
    must be reconstructed as total − Σ(others).
    """
    groups: List[List[int]]          # used-feature indices per bundle
    group_of: np.ndarray             # [F] i32 bundle column per feature
    member: np.ndarray               # [G, Bg] i32
    unmap: np.ndarray                # [G, Bg] i32
    needs_zero_fix: np.ndarray       # [F] bool
    # per-feature gather table into the bundle histogram:
    gidx_g: np.ndarray               # [F, B] i32 bundle column (or -1)
    gidx_b: np.ndarray               # [F, B] i32 bundle bin (or 0)
    num_bundled_bins: int            # Bg

    @property
    def num_groups(self) -> int:
        return len(self.groups)


def find_groups(nonzero_masks: List[Optional[np.ndarray]],
                num_bins: np.ndarray,
                sample_cnt: int,
                max_bundle_bins: int,
                max_conflict_rate: float = 1e-4) -> List[List[int]]:
    """Greedy conflict-aware bundling over sampled non-zero masks
    (reference: Dataset::FindGroups, src/io/dataset.cpp:107: features
    sorted by non-zero count, each placed into the first group whose
    accumulated conflict count stays under the budget).

    ``nonzero_masks[f]`` is a bool[sample_cnt] mask of sampled rows where
    feature f is away from its zero bin, or None if the feature must not
    be bundled (dense/categorical/NaN) — those get singleton groups.
    """
    F = len(nonzero_masks)
    max_conflict = int(max_conflict_rate * sample_cnt)
    candidates = [f for f in range(F) if nonzero_masks[f] is not None]
    # densest first, like the reference's sorted-by-cnt order
    candidates.sort(key=lambda f: -int(nonzero_masks[f].sum()))

    groups: List[List[int]] = []
    group_mask: List[np.ndarray] = []     # union of member non-zero rows
    group_conflicts: List[int] = []
    group_bins: List[int] = []            # 1 (shared zero) + Σ (b_f - 1)
    for f in candidates:
        mask = nonzero_masks[f]
        extra_bins = int(num_bins[f]) - 1
        placed = False
        for gi in range(len(groups)):
            if group_bins[gi] + extra_bins > max_bundle_bins:
                continue
            conflicts = int((group_mask[gi] & mask).sum())
            if group_conflicts[gi] + conflicts <= max_conflict:
                groups[gi].append(f)
                group_mask[gi] |= mask
                group_conflicts[gi] += conflicts
                group_bins[gi] += extra_bins
                placed = True
                break
        if not placed:
            groups.append([f])
            group_mask.append(mask.copy())
            group_conflicts.append(0)
            group_bins.append(1 + extra_bins)
    # non-candidates keep their own columns
    for f in range(F):
        if nonzero_masks[f] is None:
            groups.append([f])
    return groups


def build_layout(groups: List[List[int]], num_bins: np.ndarray,
                 zero_bins: np.ndarray, max_num_bin: int) -> BundleLayout:
    """Assign bundle bin ranges and build the member/unmap/gather
    tables (reference: FeatureGroup bin offsets,
    include/LightGBM/feature_group.h:25)."""
    F = len(num_bins)
    G = len(groups)
    group_of = np.zeros(F, dtype=np.int32)
    needs_zero_fix = np.zeros(F, dtype=bool)
    # width of the bundled matrix's bin axis
    widths = []
    for g, members in enumerate(groups):
        if len(members) == 1:
            widths.append(int(num_bins[members[0]]))
        else:
            widths.append(1 + int(sum(num_bins[f] - 1 for f in members)))
    Bg = max(max(widths), 2)
    member = np.full((G, Bg), -1, dtype=np.int32)
    unmap = np.zeros((G, Bg), dtype=np.int32)
    gidx_g = np.full((F, max_num_bin), -1, dtype=np.int32)
    gidx_b = np.zeros((F, max_num_bin), dtype=np.int32)
    for g, members in enumerate(groups):
        if len(members) == 1:
            f = members[0]
            group_of[f] = g
            b = int(num_bins[f])
            member[g, :b] = f
            unmap[g, :b] = np.arange(b)
            gidx_g[f, :b] = g
            gidx_b[f, :b] = np.arange(b)
            continue
        offset = 1
        for f in members:
            group_of[f] = g
            needs_zero_fix[f] = True
            zb = int(zero_bins[f])
            nonzero = [t for t in range(int(num_bins[f])) if t != zb]
            for k, t in enumerate(nonzero):
                member[g, offset + k] = f
                unmap[g, offset + k] = t
                gidx_g[f, t] = g
                gidx_b[f, t] = offset + k
            offset += len(nonzero)
    return BundleLayout(groups=groups, group_of=group_of, member=member,
                        unmap=unmap, needs_zero_fix=needs_zero_fix,
                        gidx_g=gidx_g, gidx_b=gidx_b,
                        num_bundled_bins=Bg)


def bundle_columns(per_feature_bin_cols, layout: BundleLayout,
                   zero_bins: np.ndarray, n: int,
                   dtype) -> np.ndarray:
    """Pack per-feature bin columns into the bundled [N, G] matrix.
    ``per_feature_bin_cols(f)`` yields the full bin column of used
    feature f. Conflict rows (two members non-zero) keep the later
    member's value, matching the reference's last-write-wins push."""
    G = layout.num_groups
    out = np.zeros((n, G), dtype=dtype)
    for g, members in enumerate(layout.groups):
        if len(members) == 1:
            out[:, g] = per_feature_bin_cols(members[0])
            continue
        col = np.zeros(n, dtype=np.int64)
        offset = 1
        for f in members:
            fb = per_feature_bin_cols(f).astype(np.int64)
            zb = int(zero_bins[f])
            # map original bin t (≠ zero_bin) to its bundle slot
            slot = np.where(fb < zb, fb, fb - 1)
            nz = fb != zb
            col = np.where(nz, offset + slot, col)
            offset += int(np.sum(layout.member[g] == f))
        out[:, g] = col.astype(dtype)
    return out
