"""Feature quantization (BinMapper) for lightgbm_tpu.

Host-side NumPy reimplementation of the reference's per-feature quantizer
(reference: include/LightGBM/bin.h:61 ``BinMapper``; src/io/bin.cpp:78
``GreedyFindBin``, :256 ``FindBinWithZeroAsOneBin``, :336 ``BinMapper::FindBin``,
include/LightGBM/bin.h:492 ``ValueToBin``). Binning is a one-shot load-time
operation, so it runs on host; the resulting integer bin matrix is what lives
in TPU HBM.

Semantics intentionally preserved:
- greedy equal-count binning with "big count" values forced into their own bins
- zero treated as its own bin boundary (FindBinWithZeroAsOneBin)
- missing types None / Zero / NaN; NaN gets the last bin
- categorical bins sorted by count with 99% coverage cutoff; bin 0 = NaN/other
- trivial-feature and pre-filter detection (NeedFilter, src/io/bin.cpp:55)
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ..obs.registry import scoped as _scoped

# reference: include/LightGBM/meta.h:56
kZeroThreshold = 1e-35
# reference: include/LightGBM/bin.h:39
kSparseThreshold = 0.7


class MissingType:
    NONE = 0
    ZERO = 1
    NAN = 2

    _NAMES = {0: "none", 1: "zero", 2: "nan"}

    @staticmethod
    def name(v: int) -> str:
        return MissingType._NAMES[v]


class BinType:
    NUMERICAL = 0
    CATEGORICAL = 1


def _next_after_up(a: np.ndarray | float):
    """Common::GetDoubleUpperBound (reference: utils/common.h:850)."""
    return np.nextafter(a, np.inf)


def _greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int,
                     min_data_in_bin: int) -> List[float]:
    """Greedy equal-count bin boundary search
    (reference: src/io/bin.cpp:78-152). Dispatches to the native C++
    implementation when available — this Python loop over distinct
    values dominates BinnedDataset construction otherwise (~80 ms per
    continuous feature at a 200k sample)."""
    assert max_bin > 0
    if len(distinct_values) > 512:  # native pays off past trivial sizes
        from ..native import greedy_find_bin
        bounds = greedy_find_bin(distinct_values, counts, max_bin,
                                 total_cnt, min_data_in_bin)
        if bounds is not None:
            return [float(v) for v in bounds]
    num_distinct = len(distinct_values)
    bin_upper_bound: List[float] = []
    if num_distinct <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct - 1):
            cur_cnt_inbin += int(counts[i])
            if cur_cnt_inbin >= min_data_in_bin:
                val = _next_after_up((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bin_upper_bound or val > _next_after_up(bin_upper_bound[-1]):
                    bin_upper_bound.append(float(val))
                    cur_cnt_inbin = 0
        bin_upper_bound.append(math.inf)
        return bin_upper_bound

    if min_data_in_bin > 0:
        max_bin = min(max_bin, int(total_cnt // min_data_in_bin))
        max_bin = max(max_bin, 1)
    mean_bin_size = total_cnt / max_bin

    # values with count >= mean get a dedicated bin (bin.cpp:105-116)
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = int(total_cnt - counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    upper_bounds = [math.inf] * max_bin
    lower_bounds = [math.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = float(distinct_values[0])
    cur_cnt_inbin = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur_cnt_inbin += int(counts[i])
        if (is_big[i] or cur_cnt_inbin >= mean_bin_size or
                (is_big[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = float(distinct_values[i])
            bin_cnt += 1
            lower_bounds[bin_cnt] = float(distinct_values[i + 1])
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _next_after_up((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bin_upper_bound or val > _next_after_up(bin_upper_bound[-1]):
            bin_upper_bound.append(float(val))
    bin_upper_bound.append(math.inf)
    return bin_upper_bound


def _find_bin_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                              max_bin: int, total_sample_cnt: int,
                              min_data_in_bin: int) -> List[float]:
    """Split negative / zero / positive value ranges so that zero sits in its
    own bin (reference: src/io/bin.cpp:256-310 FindBinWithZeroAsOneBin)."""
    num_distinct = len(distinct_values)
    left_cnt_data = int(counts[distinct_values <= -kZeroThreshold].sum())
    cnt_zero = int(counts[(distinct_values > -kZeroThreshold)
                          & (distinct_values <= kZeroThreshold)].sum())
    right_cnt_data = int(counts[distinct_values > kZeroThreshold].sum())

    nonneg = np.nonzero(distinct_values > -kZeroThreshold)[0]
    left_cnt = int(nonneg[0]) if len(nonneg) else num_distinct

    bin_upper_bound: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        denom = max(total_sample_cnt - cnt_zero, 1)
        left_max_bin = int(left_cnt_data / denom * (max_bin - 1))
        left_max_bin = max(1, left_max_bin)
        bin_upper_bound = _greedy_find_bin(
            distinct_values[:left_cnt], counts[:left_cnt], left_max_bin,
            left_cnt_data, min_data_in_bin)
        if bin_upper_bound:
            bin_upper_bound[-1] = -kZeroThreshold

    pos = np.nonzero(distinct_values[left_cnt:] > kZeroThreshold)[0]
    right_start = int(pos[0]) + left_cnt if len(pos) else -1

    right_max_bin = max_bin - 1 - len(bin_upper_bound)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = _greedy_find_bin(
            distinct_values[right_start:], counts[right_start:],
            right_max_bin, right_cnt_data, min_data_in_bin)
        bin_upper_bound.append(kZeroThreshold)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(math.inf)
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def _find_bin_with_predefined(distinct_values: np.ndarray, counts: np.ndarray,
                              max_bin: int, total_sample_cnt: int,
                              min_data_in_bin: int,
                              forced_upper_bounds: List[float]) -> List[float]:
    """Binning with user-forced boundaries
    (reference: src/io/bin.cpp:155-254 FindBinWithPredefinedBin)."""
    num_distinct = len(distinct_values)
    bin_upper_bound: List[float] = []
    nonneg = np.nonzero(distinct_values > -kZeroThreshold)[0]
    left_cnt = int(nonneg[0]) if len(nonneg) else num_distinct
    pos = np.nonzero(distinct_values[left_cnt:] > kZeroThreshold)[0]
    right_start = int(pos[0]) + left_cnt if len(pos) else -1

    if max_bin == 2:
        bin_upper_bound.append(kZeroThreshold if left_cnt == 0 else -kZeroThreshold)
    elif max_bin >= 3:
        if left_cnt > 0:
            bin_upper_bound.append(-kZeroThreshold)
        if right_start >= 0:
            bin_upper_bound.append(kZeroThreshold)
    bin_upper_bound.append(math.inf)

    max_to_insert = max_bin - len(bin_upper_bound)
    num_inserted = 0
    for ub in forced_upper_bounds:
        if num_inserted >= max_to_insert:
            break
        if abs(ub) > kZeroThreshold:
            bin_upper_bound.append(float(ub))
            num_inserted += 1
    bin_upper_bound.sort()

    free_bins = max_bin - len(bin_upper_bound)
    bounds_to_add: List[float] = []
    value_ind = 0
    n_fixed = len(bin_upper_bound)
    for i, ub in enumerate(bin_upper_bound):
        cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < num_distinct and distinct_values[value_ind] < ub:
            cnt_in_bin += int(counts[value_ind])
            value_ind += 1
        bins_remaining = max_bin - n_fixed - len(bounds_to_add)
        num_sub_bins = int(round(cnt_in_bin * free_bins / max(total_sample_cnt, 1)))
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == n_fixed - 1:
            num_sub_bins = bins_remaining + 1
        if value_ind > bin_start:
            new_bounds = _greedy_find_bin(
                distinct_values[bin_start:value_ind], counts[bin_start:value_ind],
                num_sub_bins, cnt_in_bin, min_data_in_bin)
            bounds_to_add.extend(new_bounds[:-1])  # last bound is inf
    bin_upper_bound.extend(bounds_to_add)
    bin_upper_bound.sort()
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def _need_filter(cnt_in_bin: List[int], total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """reference: src/io/bin.cpp:55-76 NeedFilter."""
    if bin_type == BinType.NUMERICAL:
        sum_left = 0
        for i in range(len(cnt_in_bin) - 1):
            sum_left += cnt_in_bin[i]
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return False
        return True
    if len(cnt_in_bin) <= 2:
        for i in range(len(cnt_in_bin) - 1):
            if cnt_in_bin[i] >= filter_cnt and total_cnt - cnt_in_bin[i] >= filter_cnt:
                return False
        return True
    return False


class BinMapper:
    """Per-feature value→bin quantizer (reference: include/LightGBM/bin.h:61)."""

    def __init__(self) -> None:
        self.num_bin: int = 1
        self.missing_type: int = MissingType.NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: int = BinType.NUMERICAL
        self.bin_upper_bound: np.ndarray = np.array([math.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0
        self.most_freq_bin: int = 0

    # ------------------------------------------------------------------
    # manifest (de)serialization: the sharded spill manifest embeds its
    # mappers so a spill dir reopens WITHOUT the source data
    # (ShardedBinnedDataset.attach). JSON-safe: upper bounds serialize
    # repr-exactly as floats (inf/nan ride through Python's json, which
    # emits Infinity/NaN literals and parses them back), categorical
    # keys stringify and convert back on load.
    def to_dict(self) -> dict:
        return {
            "num_bin": int(self.num_bin),
            "missing_type": int(self.missing_type),
            "is_trivial": bool(self.is_trivial),
            "sparse_rate": float(self.sparse_rate),
            "bin_type": int(self.bin_type),
            "bin_upper_bound": [float(v) for v in self.bin_upper_bound],
            "bin_2_categorical": [int(v) for v in self.bin_2_categorical],
            "categorical_2_bin": {str(k): int(v) for k, v
                                  in self.categorical_2_bin.items()},
            "min_val": float(self.min_val),
            "max_val": float(self.max_val),
            "default_bin": int(self.default_bin),
            "most_freq_bin": int(self.most_freq_bin),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = int(d["bin_type"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"],
                                       dtype=np.float64)
        m.bin_2_categorical = [int(v) for v in d["bin_2_categorical"]]
        m.categorical_2_bin = {int(k): int(v) for k, v
                               in d["categorical_2_bin"].items()}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        m.most_freq_bin = int(d["most_freq_bin"])
        return m

    # ------------------------------------------------------------------
    @_scoped("io::find_bin")
    def find_bin(self, sample_values: np.ndarray, total_sample_cnt: int,
                 max_bin: int, min_data_in_bin: int = 3,
                 min_split_data: int = 20, pre_filter: bool = False,
                 bin_type: int = BinType.NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False,
                 forced_upper_bounds: Optional[List[float]] = None) -> None:
        """Build bin boundaries from a sampled column
        (reference: src/io/bin.cpp:336 BinMapper::FindBin).

        ``sample_values`` is the full sampled column *including* zeros and NaNs
        (the reference receives non-zero values plus a zero count; equivalent).
        ``total_sample_cnt`` may exceed ``len(sample_values)`` when the caller
        pre-dropped zeros (sparse input).
        """
        values = np.asarray(sample_values, dtype=np.float64)
        na_mask = np.isnan(values)
        na_cnt = int(na_mask.sum())
        values = values[~na_mask]

        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = MissingType.NAN if na_cnt > 0 else MissingType.NONE
        if self.missing_type != MissingType.NAN:
            # NaN samples count as zeros when not tracked as missing
            # (reference: bin.cpp:356-366 — na_cnt stays 0, so
            # zero_cnt = total - non_na absorbs them)
            na_cnt = 0

        zero_mask = np.abs(values) <= kZeroThreshold
        zero_cnt = int(zero_mask.sum()) + int(
            total_sample_cnt - len(sample_values)) + (int(na_mask.sum()) - na_cnt)
        nonzero = values[~zero_mask]

        self.bin_type = bin_type
        self.default_bin = 0

        # distinct values with zero spliced into sorted position
        # (reference: bin.cpp:371-407)
        if len(nonzero):
            distinct, counts = np.unique(nonzero, return_counts=True)
        else:
            distinct = np.empty(0)
            counts = np.empty(0, dtype=np.int64)
        if zero_cnt > 0 or len(distinct) == 0:
            pos = int(np.searchsorted(distinct, 0.0))
            distinct = np.insert(distinct, pos, 0.0)
            counts = np.insert(counts, pos, zero_cnt)
        self.min_val = float(distinct[0])
        self.max_val = float(distinct[-1])
        counts = counts.astype(np.int64)

        def _find(max_b: int, total: int) -> List[float]:
            # dispatch on forced bounds (reference: bin.cpp:312-322)
            if forced_upper_bounds:
                return _find_bin_with_predefined(
                    distinct, counts, max_b, total, min_data_in_bin,
                    list(forced_upper_bounds))
            return _find_bin_zero_as_one_bin(
                distinct, counts, max_b, total, min_data_in_bin)

        cnt_in_bin: List[int] = []
        if bin_type == BinType.NUMERICAL:
            if self.missing_type == MissingType.NAN:
                bounds = _find(max_bin - 1, total_sample_cnt - na_cnt)
                bounds.append(math.nan)
            else:
                bounds = _find(max_bin, total_sample_cnt)
                if self.missing_type == MissingType.ZERO and len(bounds) == 2:
                    self.missing_type = MissingType.NONE
            self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            # count per bin (bin.cpp:409-422)
            n_search = self.num_bin - (1 if self.missing_type == MissingType.NAN else 0)
            idx = np.searchsorted(self.bin_upper_bound[:max(n_search - 1, 0)],
                                  distinct, side="left")
            cnt_arr = np.zeros(self.num_bin, dtype=np.int64)
            np.add.at(cnt_arr, idx, counts)
            if self.missing_type == MissingType.NAN:
                cnt_arr[self.num_bin - 1] = na_cnt
            cnt_in_bin = cnt_arr.tolist()
        else:
            self._find_bin_categorical(distinct, counts, max_bin,
                                       total_sample_cnt, na_cnt,
                                       min_data_in_bin)
            cnt_in_bin = self._cat_cnt_in_bin

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and _need_filter(
                cnt_in_bin, total_sample_cnt, min_split_data, bin_type):
            self.is_trivial = True

        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            max_sparse_rate = cnt_in_bin[self.most_freq_bin] / max(total_sample_cnt, 1)
            if self.most_freq_bin != self.default_bin and max_sparse_rate < kSparseThreshold:
                self.most_freq_bin = self.default_bin
            self.sparse_rate = cnt_in_bin[self.most_freq_bin] / max(total_sample_cnt, 1)
        else:
            self.sparse_rate = 1.0

    # ------------------------------------------------------------------
    def _find_bin_categorical(self, distinct: np.ndarray, counts: np.ndarray,
                              max_bin: int, total_sample_cnt: int,
                              na_cnt: int, min_data_in_bin: int) -> None:
        """reference: src/io/bin.cpp:424-491 (categorical branch)."""
        vals_int: List[int] = []
        cnts_int: List[int] = []
        for v, c in zip(distinct, counts):
            iv = int(v)
            if iv < 0:
                na_cnt += int(c)
                continue
            if vals_int and iv == vals_int[-1]:
                cnts_int[-1] += int(c)
            else:
                vals_int.append(iv)
                cnts_int.append(int(c))
        self.bin_2_categorical = [-1]
        self.categorical_2_bin = {-1: 0}
        self._cat_cnt_in_bin = [0]
        self.num_bin = 1
        rest_cnt = total_sample_cnt - na_cnt
        if rest_cnt <= 0 or not vals_int:
            return
        # sort by count descending, stable (value-ascending ties)
        order = np.argsort(-np.asarray(cnts_int), kind="stable")
        cut_cnt = int(round((total_sample_cnt - na_cnt) * 0.99))
        distinct_cnt = len(vals_int) + (1 if na_cnt > 0 else 0)
        max_bin = min(distinct_cnt, max_bin)
        used_cnt = 0
        cur = 0
        while cur < len(order) and (used_cnt < cut_cnt or self.num_bin < max_bin):
            i = int(order[cur])
            if cnts_int[i] < min_data_in_bin and cur > 1:
                break
            self.bin_2_categorical.append(vals_int[i])
            self.categorical_2_bin[vals_int[i]] = self.num_bin
            used_cnt += cnts_int[i]
            self._cat_cnt_in_bin.append(cnts_int[i])
            self.num_bin += 1
            cur += 1
        if cur == len(order) and na_cnt == 0:
            self.missing_type = MissingType.NONE
        else:
            self.missing_type = MissingType.NAN
        self._cat_cnt_in_bin[0] = total_sample_cnt - used_cnt

    # ------------------------------------------------------------------
    def value_to_bin(self, value) -> np.ndarray:
        """Vectorized ValueToBin (reference: include/LightGBM/bin.h:492)."""
        v = np.asarray(value, dtype=np.float64)
        scalar = v.ndim == 0
        v = np.atleast_1d(v)
        if self.bin_type == BinType.CATEGORICAL:
            # single-pass lookup over sorted category values
            iv = np.where(np.isnan(v), -1, v).astype(np.int64)
            cats = np.array([c for c in self.categorical_2_bin if c >= 0],
                            dtype=np.int64)
            if len(cats) == 0:
                out = np.zeros(len(iv), dtype=np.int32)
                return out[0] if scalar else out
            cats.sort()
            bins_for_cats = np.array(
                [self.categorical_2_bin[int(c)] for c in cats], dtype=np.int32)
            pos = np.searchsorted(cats, iv)
            pos_clip = np.clip(pos, 0, len(cats) - 1)
            hit = (pos < len(cats)) & (cats[pos_clip] == iv)
            out = np.where(hit & (iv >= 0), bins_for_cats[pos_clip], 0).astype(np.int32)
            return out[0] if scalar else out
        nan_mask = np.isnan(v)
        if self.missing_type != MissingType.NAN:
            v = np.where(nan_mask, 0.0, v)
        n_search = self.num_bin - (1 if self.missing_type == MissingType.NAN else 0)
        out = np.searchsorted(self.bin_upper_bound[:max(n_search - 1, 0)],
                              v, side="left").astype(np.int32)
        if self.missing_type == MissingType.NAN:
            out = np.where(nan_mask, self.num_bin - 1, out)
        return out[0] if scalar else out

    def bin_to_value(self, bin_idx: int) -> float:
        """Upper bound of a bin — the real-valued threshold stored in trees
        (reference: include/LightGBM/bin.h:115 BinToValue)."""
        if self.bin_type == BinType.CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx])
        return float(self.bin_upper_bound[bin_idx])

    def feature_info(self) -> str:
        """String for the model file 'feature_infos' section
        (reference: src/io/dataset.cpp DumpModel feature_infos format)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BinType.CATEGORICAL:
            return ":".join(str(c) for c in self.bin_2_categorical[1:])
        return f"[{self.min_val}:{self.max_val}]"
