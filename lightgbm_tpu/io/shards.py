"""Out-of-core sharded data plane: stream-binned shards + device staging.

Today every dataset must fit twice — the raw f64 matrix in host RAM
(``StreamingDataset.finalize`` coalesces all pushed chunks before
binning) and the full binned matrix in HBM (``BinnedDataset.from_matrix``
stages everything device-resident). Histograms are additive over row
chunks — the streaming decomposition of the integral-histogram work and
the external-memory mode of XGBoost's scalable-GPU design — so neither
materialization is actually required.

:class:`ShardedBinnedDataset` never materializes the full dataset on
either side of the PCIe link:

- **Construction** is two-pass and chunk-at-a-time. Pass 1 feeds chunks
  into streaming bin-mapper construction (a bounded row sample, the same
  ``BinMapper.find_bin`` mappers as the in-memory path). Pass 2 applies
  the mappers per chunk and spills each binned uint8/uint16 shard to a
  file loaded back memory-mapped, plus per-shard label/weight slices —
  peak host RSS is O(chunk + sample), not O(dataset).
- **Training** stages one shard at a time into device memory.
  :class:`ShardPrefetcher` double-buffers: while shard *k* computes, a
  worker thread ``jax.device_put``s shard *k+1* (obs scope
  ``io::shard_stage``; blocked time lands on the
  ``io/prefetch_stall_ms`` counter that the ``prefetch_stall`` watchdog
  rule in obs/health.py monitors). Buffers are dropped after a shard's
  last use each sweep so the allocator recycles them (donate-style
  reuse, at most two shards resident).

The training side lives in treelearner/sharded.py: per-leaf (grad,
hess) histograms accumulate shard-by-shard through an ORDERED
scatter-add, which makes the result bit-identical to the in-memory
serial learner's single-pass segment-sum histogram on scatter backends
(CPU) — and exactly order-invariant under quantized integer gradients
on every backend. Per-row O(1)-width state (scores, gradients, the
row→leaf partition) stays resident: it is O(N) words where the bins
matrix is O(N·F) bytes, and the HBM budget the shard size tunes is the
F-wide bins payload.

On-disk layout under ``spill_dir`` (all files plain ``.npy``)::

    manifest.json             # rows, shard sizes, dtype, feature count
    shard_0000.bins.npy       # [n_0, F_used] uint8/uint16, memmapped
    shard_0000.label.npy      # [n_0] f32 (when labels were provided)
    shard_0000.weight.npy     # [n_0] f32 (when weights were provided)
    shard_0001.bins.npy ...

Not supported on the sharded path (loudly, at construction/learner
setup): EFB bundling, linear trees / raw-data retention, sparse input,
query groups, init scores, and alignment to a reference dataset.
"""
from __future__ import annotations

import concurrent.futures
import errno
import json
import os
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..config import Config
from ..obs import events as obs_events
from ..obs import faults
from ..obs import quality as obs_quality
from ..obs.registry import registry as obs
from ..utils import log
from ..utils.atomic import atomic_write, sha256_file
from ..utils.retry import retry_call
from .binning import BinMapper
from .dataset import (BinnedDataset, Metadata, _resolve_categorical,
                      find_bin_for_feature, load_forced_bounds,
                      validate_max_bin_by_feature)

# default rows per spilled shard when the caller does not size them
DEFAULT_SHARD_ROWS = 1 << 18

# ENOSPC mid-spill falls back to holding the REMAINING shards resident
# in host RAM when they fit this budget (else: fatal, telemetry
# flushed) — a long build survives a full disk at the cost of the
# O(chunk) memory contract for the un-spilled tail
_ENV_RESIDENT_BUDGET = "LIGHTGBM_TPU_SPILL_RESIDENT_BUDGET_MB"
# upper bound on one blocking wait for a staged shard: a wedged device
# runtime must become a fatal health event, not an indefinite hang
_ENV_STAGE_TIMEOUT = "LIGHTGBM_TPU_STAGE_TIMEOUT_S"


def _is_enospc(e: BaseException) -> bool:
    return getattr(e, "errno", None) == errno.ENOSPC


def _device_put(x):
    """THE host→device staging hop of the sharded plane — an explicit
    ``jax.device_put``, kept behind one module function so tests can
    interpose a slow/fake device (prefetcher-ordering test) and so the
    transfer-guard sanitizer has exactly one sanctioned transfer site."""
    import jax
    return jax.device_put(x)


def _normalize_chunk(chunk) -> Tuple[np.ndarray, Optional[np.ndarray],
                                     Optional[np.ndarray]]:
    """A source chunk is ``X`` or ``(X,)`` or ``(X, y)`` or
    ``(X, y, w)``; returns dense f64 X plus optional f32 y/w."""
    if isinstance(chunk, tuple):
        X = chunk[0]
        y = chunk[1] if len(chunk) > 1 else None
        w = chunk[2] if len(chunk) > 2 else None
    else:
        X, y, w = chunk, None, None
    if hasattr(X, "tocsc"):
        log.fatal("sharded construction requires dense chunks")
    X = np.atleast_2d(np.asarray(X, dtype=np.float64))
    if y is not None:
        y = np.asarray(y, dtype=np.float32).reshape(-1)
        if len(y) != X.shape[0]:
            log.fatal("chunk has %d rows but %d labels"
                      % (X.shape[0], len(y)))
    if w is not None:
        w = np.asarray(w, dtype=np.float32).reshape(-1)
        if len(w) != X.shape[0]:
            log.fatal("chunk has %d rows but %d weights"
                      % (X.shape[0], len(w)))
    return X, y, w


class _SampleCollector:
    """Pass-1 row sample for bin-mapper construction, O(sample) memory.

    With ``total_rows`` known up front (the StreamingDataset route) the
    sample replicates ``BinnedDataset.from_matrix`` EXACTLY —
    ``sort(rng.choice(n, sample_cnt))`` on the same
    ``data_random_seed`` — so the mappers (and therefore the binned
    rows and the trained trees) are bit-identical to the in-memory
    path. With unknown ``total_rows`` a uniform reservoir stands in:
    statistically equivalent, and still exactly the full row set (hence
    exactly from_matrix's mappers) whenever ``bin_construct_sample_cnt``
    covers the data."""

    def __init__(self, sample_cnt: int, num_features: int, seed: int,
                 total_rows: Optional[int]):
        self.cap = int(sample_cnt)
        self.rng = np.random.RandomState(seed)
        self.total_rows = total_rows
        # preallocated to the (known) sample bound and filled by slice:
        # per-chunk concatenation would re-copy the whole accumulated
        # sample every chunk — O(num_chunks x sample_bytes) memmove at
        # exactly the scale this module targets
        self.rows = np.empty((self.cap, num_features), dtype=np.float64)
        self.idx = np.empty(self.cap, dtype=np.int64)
        self.fill = 0
        self.seen = 0
        self._target_idx = None
        if total_rows is not None and self.cap < total_rows:
            self._target_idx = np.sort(self.rng.choice(
                total_rows, self.cap, replace=False))

    def add(self, X: np.ndarray) -> None:
        m = X.shape[0]
        lo = self.seen
        self.seen += m
        if self._target_idx is not None:
            # exact from_matrix sample: gather the pre-drawn indices
            # falling inside this chunk
            a = np.searchsorted(self._target_idx, lo)
            b = np.searchsorted(self._target_idx, lo + m)
            if b > a:
                self.rows[self.fill:self.fill + b - a] = \
                    X[self._target_idx[a:b] - lo]
                self.idx[self.fill:self.fill + b - a] = \
                    self._target_idx[a:b]
                self.fill += b - a
            return
        if self.fill < self.cap:
            take = min(self.cap - self.fill, m)
            self.rows[self.fill:self.fill + take] = X[:take]
            self.idx[self.fill:self.fill + take] = \
                np.arange(lo, lo + take)
            self.fill += take
            if take == m:
                return
            X = X[take:]
            lo += take
            m -= take
        # vectorized reservoir tail: row t replaces a random slot with
        # probability cap/t (within-chunk slot collisions keep the
        # later row — still a uniform sample)
        t = np.arange(lo + 1, lo + m + 1, dtype=np.float64)
        slots = (self.rng.rand(m) * t).astype(np.int64)
        hit = slots < self.cap
        if hit.any():
            self.rows[slots[hit]] = X[hit]
            self.idx[slots[hit]] = np.arange(lo, lo + m)[hit]

    def finish(self) -> Tuple[np.ndarray, int]:
        """(sample rows in ascending row order, effective count)."""
        rows, idx = self.rows[:self.fill], self.idx[:self.fill]
        order = np.argsort(idx, kind="stable")
        return rows[order], self.fill


class ShardedBinnedDataset:
    """Binned training data spilled to memory-mapped shards.

    Duck-types the :class:`~.dataset.BinnedDataset` surface the boosting
    and tree-learner layers read (mappers, metadata, feature maps) but
    deliberately has NO ``bins`` attribute: any code path that needs the
    full resident matrix (DART/rollback score recomputation, EFB, linear
    trees) fails loudly instead of silently materializing the dataset.
    """

    def __init__(self) -> None:
        self.bin_mappers: List[BinMapper] = []
        self.used_feature_map: List[int] = []
        self.num_total_features: int = 0
        self.feature_names: List[str] = []
        self.metadata: Metadata = Metadata(0)
        self.max_num_bin: int = 0
        self.num_bin_per_feature: np.ndarray = np.zeros(0, dtype=np.int32)
        self.monotone_constraints: Optional[np.ndarray] = None
        self.feature_penalty: Optional[np.ndarray] = None
        self.bundle = None          # EFB never bundles on this path
        self.raw_data = None        # linear trees unsupported
        self.spill_dir: str = ""
        self.shard_sizes: List[int] = []
        self.shard_offsets: List[int] = []
        self.bins_dtype = np.uint8
        self.has_weights = False
        # ENOSPC degradation: shards the spill could not write stay
        # host-resident here and shard_bins_host serves them directly
        self._resident_shards: Dict[int, np.ndarray] = {}
        # manifest file table (name -> {sha256, bytes}) checked on
        # every reopen: size per open, full content hash on the first
        self._file_meta: Dict[str, dict] = {}
        self._verified_shards: set = set()
        # training-grid reference profile (obs/quality.py) captured at
        # spill time; None on spills written before the quality plane
        self.quality_profile = None

    # ------------------------------------------------------------------
    @classmethod
    def from_chunk_source(cls, source: Callable[[], Iterable],
                          config: Config, spill_dir: str,
                          shard_rows: Optional[int] = None,
                          feature_names: Optional[List[str]] = None,
                          categorical_feature=None,
                          total_rows: Optional[int] = None
                          ) -> "ShardedBinnedDataset":
        """Two-pass, chunk-at-a-time construction.

        Parameters
        ----------
        source : zero-argument callable returning a FRESH iterator of
            chunks — each ``X`` / ``(X, y)`` / ``(X, y, w)`` — called
            exactly twice (pass 1: sampling, pass 2: bin + spill).
        spill_dir : directory for the shard files (created if missing).
        shard_rows : rows per spilled shard; sizes the HBM staging unit.
        total_rows : when known (e.g. the StreamingDataset route), the
            pass-1 sample replicates ``from_matrix`` bit-exactly.
        """
        self = cls()
        self.spill_dir = str(spill_dir)
        os.makedirs(self.spill_dir, exist_ok=True)
        # spilled shards are live training data reopened memmapped on
        # every sweep — refuse to clobber an existing spill (the PR-6
        # trace-segment rule: on-disk artifacts are evidence, never
        # overwritten; stale higher-numbered shards from a previous
        # larger build would also survive next to a fresh manifest)
        existing = [f for f in os.listdir(self.spill_dir)
                    if f == "manifest.json" or f.startswith("shard_")]
        if existing:
            log.fatal("spill_dir %s already holds a spilled dataset "
                      "(%s, ...); use a fresh directory"
                      % (self.spill_dir, sorted(existing)[0]))
        shard_rows = int(shard_rows or DEFAULT_SHARD_ROWS)
        if shard_rows <= 0:
            log.fatal("shard_rows must be positive")

        # ---- pass 1: stream chunks into the mapper sample ------------
        sampler = None
        num_total_features = 0
        with obs.scope("io::find_bins"):
            for chunk in source():
                X, _, _ = _normalize_chunk(chunk)
                if sampler is None:
                    num_total_features = X.shape[1]
                    sampler = _SampleCollector(
                        min(config.bin_construct_sample_cnt,
                            total_rows if total_rows is not None
                            else config.bin_construct_sample_cnt),
                        num_total_features, config.data_random_seed,
                        total_rows)
                elif X.shape[1] != num_total_features:
                    log.fatal("chunk has %d columns, expected %d"
                              % (X.shape[1], num_total_features))
                sampler.add(X)
            if sampler is None or sampler.seen == 0:
                log.fatal("no rows in chunk source")
            if total_rows is not None and sampler.seen != total_rows:
                log.fatal("chunk source yielded %d rows, expected %d"
                          % (sampler.seen, total_rows))
            n = sampler.seen
            sample_X, sample_cnt_eff = sampler.finish()
            self.num_total_features = num_total_features
            self.feature_names = list(feature_names) if feature_names \
                else ["Column_%d" % i for i in range(num_total_features)]
            self._build_mappers(sample_X, sample_cnt_eff, config,
                                categorical_feature)
        if config.enable_bundle and self.num_features > 1:
            log.info("EFB bundling is disabled on the sharded "
                     "out-of-core path (dense shard layout)")

        # ---- pass 2: bin per chunk, spill shard files ----------------
        self.bins_dtype = (np.uint8 if self.max_num_bin <= 256
                           else np.uint16)
        F_used = self.num_features
        buf = np.empty((shard_rows, max(F_used, 1)), dtype=self.bins_dtype)
        lbuf = np.empty(shard_rows, dtype=np.float32)
        wbuf = np.empty(shard_rows, dtype=np.float32)
        fill = 0
        shard_no = 0
        labels: List[np.ndarray] = []
        weights: List[np.ndarray] = []
        any_label = False
        any_weight = False

        try:
            resident_budget_mb = float(os.environ.get(
                _ENV_RESIDENT_BUDGET, 512))
        except ValueError:
            resident_budget_mb = 512.0
        degraded = False
        profiler = obs_quality.ProfileBuilder(
            self.bin_mappers, self.used_feature_map, self.feature_names)

        def _cleanup_partial(k: int) -> None:
            for p in (self._bins_path(k), self._label_path(k),
                      self._weight_path(k)):
                try:
                    os.unlink(p)
                except OSError:
                    pass

        def flush():
            nonlocal fill, shard_no, degraded
            if fill == 0:
                return
            if not degraded:
                def _write():
                    faults.check("spill_write", shard=shard_no)
                    np.save(self._bins_path(shard_no), buf[:fill])
                    if any_label:
                        np.save(self._label_path(shard_no), lbuf[:fill])
                    if any_weight:
                        np.save(self._weight_path(shard_no),
                                wbuf[:fill])
                try:
                    retry_call(_write, site="spill_write",
                               no_retry=_is_enospc)
                    for p in [self._bins_path(shard_no)] \
                            + ([self._label_path(shard_no)]
                               if any_label else []) \
                            + ([self._weight_path(shard_no)]
                               if any_weight else []):
                        self._file_meta[os.path.basename(p)] = {
                            "sha256": sha256_file(p),
                            "bytes": os.path.getsize(p)}
                except OSError as e:
                    # a retried write may have left a truncated file —
                    # never leave it next to the manifest
                    _cleanup_partial(shard_no)
                    if not _is_enospc(e):
                        log.fatal("spilling shard %d under %s failed "
                                  "after retries: %r"
                                  % (shard_no, self.spill_dir, e))
                    # ENOSPC: the disk will not get emptier — degrade
                    # to resident shards when the un-spilled remainder
                    # fits the budget, else die with telemetry flushed
                    remaining = n - sum(self.shard_sizes)
                    row_bytes = (max(F_used, 1) * buf.itemsize
                                 + 4 * (int(any_label)
                                        + int(any_weight)))
                    est_mb = remaining * row_bytes / 2.0**20
                    if est_mb > resident_budget_mb:
                        log.fatal(
                            "disk full spilling shard %d and the "
                            "remaining ~%.0f MB exceed %s=%.0f; free "
                            "space or raise the budget"
                            % (shard_no, est_mb, _ENV_RESIDENT_BUDGET,
                               resident_budget_mb))
                    degraded = True
                    obs.inc("ft/spill_degraded")
                    msg = ("disk full (ENOSPC) spilling shard %d; "
                           "keeping the remaining ~%.0f MB of shards "
                           "resident in host RAM (budget %s=%.0f) — "
                           "the O(chunk) construction-memory contract "
                           "is suspended for this build"
                           % (shard_no, est_mb, _ENV_RESIDENT_BUDGET,
                              resident_budget_mb))
                    obs_events.emit("perf_warning",
                                    component="io.shards", message=msg)
                    obs_events.flush()
                    log.warning_always(msg)
            if degraded:
                self._resident_shards[shard_no] = buf[:fill].copy()
                obs.inc("io/shards_resident")
            else:
                obs.inc("io/shards_spilled")
            if any_label:
                labels.append(lbuf[:fill].copy())
                profiler.add_labels(lbuf[:fill])
            if any_weight:
                weights.append(wbuf[:fill].copy())
            # reference-profile capture rides the spill: one jitted
            # device reduction over the shard buffer already binned
            # above (fixed shape -> one trace for the whole spill)
            profiler.add_block(buf, fill)
            self.shard_sizes.append(fill)
            shard_no += 1
            fill = 0

        with obs.scope("io::apply_bins"):
            first = True
            for chunk in source():
                X, y, w = _normalize_chunk(chunk)
                if first:
                    any_label = y is not None
                    any_weight = w is not None
                    first = False
                if (y is not None) != any_label or \
                        (w is not None) != any_weight:
                    log.fatal("chunk source must carry labels/weights "
                              "on every chunk or on none")
                binned = np.empty((X.shape[0], max(F_used, 1)),
                                  dtype=self.bins_dtype)
                for j in range(F_used):
                    f = self.used_feature_map[j]
                    binned[:, j] = self.bin_mappers[j].value_to_bin(
                        X[:, f]).astype(self.bins_dtype)
                pos = 0
                m = X.shape[0]
                while pos < m:
                    take = min(m - pos, shard_rows - fill)
                    buf[fill:fill + take] = binned[pos:pos + take]
                    if any_label:
                        lbuf[fill:fill + take] = y[pos:pos + take]
                    if any_weight:
                        wbuf[fill:fill + take] = w[pos:pos + take]
                    fill += take
                    pos += take
                    if fill == shard_rows:
                        flush()
            flush()

        if sum(self.shard_sizes) != n:
            log.fatal("pass 2 yielded %d rows, pass 1 saw %d"
                      % (sum(self.shard_sizes), n))
        self.shard_offsets = list(
            np.concatenate([[0], np.cumsum(self.shard_sizes)[:-1]])
            .astype(int))
        self.has_weights = any_weight
        self.metadata = Metadata(n)
        if any_label:
            self.metadata.set_label(np.concatenate(labels))
        if any_weight:
            self.metadata.set_weights(np.concatenate(weights))
        self.quality_profile = profiler.finalize()
        manifest = {
            "num_data": n,
            "num_features_used": F_used,
            "num_total_features": self.num_total_features,
            "shard_sizes": self.shard_sizes,
            "bins_dtype": np.dtype(self.bins_dtype).name,
            "has_label": any_label, "has_weight": any_weight,
            "max_num_bin": self.max_num_bin,
            # per-file content hashes: a truncated or poisoned shard
            # is rejected loudly by name at reopen, never trained on
            "files": self._file_meta,
            "resident_shards": sorted(self._resident_shards),
            # the full quantizer state: attach() reopens this spill
            # without the source data and without re-binning
            "feature_names": self.feature_names,
            "used_feature_map": self.used_feature_map,
            "mappers": [m.to_dict() for m in self.bin_mappers],
            # training-grid reference profile (obs/quality.py): the
            # drift baseline reloads with the spill, no source data
            # needed
            "quality_profile": self.quality_profile.to_dict(),
        }
        try:
            atomic_write(os.path.join(self.spill_dir, "manifest.json"),
                         json.dumps(manifest))
        except OSError as e:
            if not degraded:
                log.fatal("writing spill manifest under %s failed: %r"
                          % (self.spill_dir, e))
            # the degraded (disk-full) build still works from memory;
            # only the on-disk forensics record is lost
            log.warning_always("spill manifest write failed on the "
                               "degraded build: %r" % e)
        obs_events.emit(
            "dataset", num_data=n, num_features=self.num_features,
            num_total_features=self.num_total_features,
            max_num_bin=self.max_num_bin, bundled=False,
            aligned_to_reference=False, sharded=True,
            num_shards=self.num_shards, shard_rows=shard_rows)
        return self

    # ------------------------------------------------------------------
    @classmethod
    def attach(cls, spill_dir: str,
               config: Optional[Config] = None) -> "ShardedBinnedDataset":
        """Reopen an existing spill dir WITHOUT the source data and
        without re-binning: the manifest carries the full quantizer
        state (bin mappers, feature maps), shard files stay on disk and
        reopen memory-mapped exactly as after construction. Labels and
        weights reload from the per-shard aux files, each verified
        against the manifest's content hash before use.

        This is the refresh loop's cheap data plane for cycle N+1 (and
        the elastic-resume primitive): training from an attached
        dataset is bit-identical to training from the dataset that
        spilled it. ``config`` resolves the constraint/penalty vectors
        (monotone_constraints, feature_penalty) — pass the training
        config; defaults to ``Config()`` (no constraints).
        """
        self = cls()
        self.spill_dir = str(spill_dir)
        mpath = os.path.join(self.spill_dir, "manifest.json")
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as e:
            log.fatal("cannot attach spill dir %s: manifest unreadable "
                      "(%r)" % (self.spill_dir, e))
        if "mappers" not in manifest:
            log.fatal("spill manifest under %s predates mapper "
                      "serialization; rebuild via from_chunk_source"
                      % self.spill_dir)
        if manifest.get("resident_shards"):
            # a degraded (ENOSPC) build kept shards in RAM only — they
            # were never written, so this spill cannot be reattached
            log.fatal("spill under %s is degraded (shards %s were "
                      "host-resident, never spilled); it cannot be "
                      "reattached" % (self.spill_dir,
                                      manifest["resident_shards"]))
        n = int(manifest["num_data"])
        self.num_total_features = int(manifest["num_total_features"])
        self.feature_names = list(manifest["feature_names"])
        self.bin_mappers = [BinMapper.from_dict(d)
                            for d in manifest["mappers"]]
        self.used_feature_map = [int(i)
                                 for i in manifest["used_feature_map"]]
        if (len(self.bin_mappers) != int(manifest["num_features_used"])
                or len(self.used_feature_map) != len(self.bin_mappers)):
            log.fatal("spill manifest under %s is inconsistent: %d "
                      "mappers, %d used features, used map of %d"
                      % (self.spill_dir, len(self.bin_mappers),
                         int(manifest["num_features_used"]),
                         len(self.used_feature_map)))
        self.num_bin_per_feature = np.asarray(
            [m.num_bin for m in self.bin_mappers], dtype=np.int32)
        self.max_num_bin = int(manifest["max_num_bin"])
        derived = int(self.num_bin_per_feature.max()) \
            if len(self.num_bin_per_feature) else 1
        if derived != self.max_num_bin:
            log.fatal("spill manifest under %s is inconsistent: "
                      "max_num_bin %d but mappers peak at %d"
                      % (self.spill_dir, self.max_num_bin, derived))
        self.bins_dtype = np.dtype(manifest["bins_dtype"]).type
        self.shard_sizes = [int(s) for s in manifest["shard_sizes"]]
        if sum(self.shard_sizes) != n:
            log.fatal("spill manifest under %s is inconsistent: shard "
                      "sizes sum to %d, num_data is %d"
                      % (self.spill_dir, sum(self.shard_sizes), n))
        self.shard_offsets = list(
            np.concatenate([[0], np.cumsum(self.shard_sizes)[:-1]])
            .astype(int))
        self.has_weights = bool(manifest["has_weight"])
        # drift baseline: absent on spills written before the quality
        # plane (tolerated — drift monitoring is then simply off); a
        # malformed one is rejected loudly like any other manifest rot
        if manifest.get("quality_profile") is not None:
            try:
                self.quality_profile = obs_quality.ReferenceProfile \
                    .from_dict(manifest["quality_profile"])
            except (KeyError, TypeError, ValueError) as e:
                log.fatal("spill manifest under %s carries a malformed "
                          "quality_profile: %r" % (self.spill_dir, e))
        self._file_meta = {str(k): dict(v)
                           for k, v in manifest["files"].items()}
        # every manifest-listed file must exist at its recorded size
        # BEFORE any training starts (content hashes verify lazily on
        # first open, same as after construction)
        for name, meta in self._file_meta.items():
            path = os.path.join(self.spill_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError as e:
                log.fatal("attach: shard file %s under %s is missing "
                          "or unreadable: %r"
                          % (name, self.spill_dir, e))
            if size != int(meta["bytes"]):
                log.fatal("attach: shard file %s is truncated: %d "
                          "bytes on disk, manifest records %d"
                          % (name, size, int(meta["bytes"])))
        BinnedDataset._set_constraints(self, config or Config())
        self.metadata = Metadata(n)
        if manifest["has_label"]:
            self.metadata.set_label(np.concatenate(
                [self._load_aux(self._label_path(k))
                 for k in range(self.num_shards)]))
        if self.has_weights:
            self.metadata.set_weights(np.concatenate(
                [self._load_aux(self._weight_path(k))
                 for k in range(self.num_shards)]))
        obs_events.emit(
            "dataset_attach", spill_dir=self.spill_dir, num_data=n,
            num_features=self.num_features,
            num_shards=self.num_shards,
            max_num_bin=self.max_num_bin)
        return self

    def _load_aux(self, path: str) -> np.ndarray:
        """Load one label/weight shard file, content-verified against
        the manifest hash (aux files are [n_k] f32 — small enough to
        hash eagerly on attach, unlike the lazily-verified bins)."""
        name = os.path.basename(path)
        meta = self._file_meta.get(name)
        if meta is None:
            log.fatal("attach: %s is not in the spill manifest under %s"
                      % (name, self.spill_dir))
        digest = sha256_file(path)
        if digest != meta["sha256"]:
            log.fatal("attach: %s under %s failed content verification "
                      "(sha256 %s..., manifest records %s...)"
                      % (name, self.spill_dir, digest[:12],
                         meta["sha256"][:12]))
        return np.load(path)

    # ------------------------------------------------------------------
    def _build_mappers(self, sample_X: np.ndarray, sample_cnt_eff: int,
                       config: Config, categorical_feature) -> None:
        """Mapper construction over the pass-1 sample — the dense arm of
        ``BinnedDataset.from_matrix``'s sampling pass, same knobs, same
        trivial-feature filtering."""
        if categorical_feature is None and config.categorical_feature:
            categorical_feature = config.categorical_feature
        cat_set = _resolve_categorical(categorical_feature,
                                       self.feature_names)
        max_bin_by_feature = validate_max_bin_by_feature(
            config, self.num_total_features)
        forced_bounds = load_forced_bounds(config)
        mappers: List[BinMapper] = [
            find_bin_for_feature(f, sample_X[:, f], sample_cnt_eff,
                                 config, cat_set, forced_bounds,
                                 max_bin_by_feature)
            for f in range(self.num_total_features)]
        self.bin_mappers = [m for m in mappers if not m.is_trivial]
        self.used_feature_map = [i for i, m in enumerate(mappers)
                                 if not m.is_trivial]
        self.num_bin_per_feature = np.asarray(
            [m.num_bin for m in self.bin_mappers], dtype=np.int32)
        self.max_num_bin = int(self.num_bin_per_feature.max()) \
            if len(self.num_bin_per_feature) else 1
        # constraint/penalty vectors: same resolution as the in-memory
        # dataset (BinnedDataset._set_constraints reads only mappers +
        # used_feature_map, which this class duck-types)
        BinnedDataset._set_constraints(self, config)

    # ------------------------------------------------------------------
    # shard access
    # ------------------------------------------------------------------
    def _bins_path(self, k: int) -> str:
        return os.path.join(self.spill_dir, "shard_%04d.bins.npy" % k)

    def _label_path(self, k: int) -> str:
        return os.path.join(self.spill_dir, "shard_%04d.label.npy" % k)

    def _weight_path(self, k: int) -> str:
        return os.path.join(self.spill_dir, "shard_%04d.weight.npy" % k)

    @property
    def num_shards(self) -> int:
        return len(self.shard_sizes)

    def shard_bins_host(self, k: int) -> np.ndarray:
        """[n_k, F_used] bin matrix of shard ``k``: host-resident when
        the spill degraded on ENOSPC, else memory-mapped (touching it
        faults pages in, it never loads the file whole). Every reopen
        checks the file size against the manifest and the first open
        additionally verifies the content hash — a truncated or
        poisoned shard fails loudly by name instead of silently
        corrupting the run; transient open errors retry with backoff
        (utils/retry.py)."""
        if k in self._resident_shards:
            return self._resident_shards[k]
        path = self._bins_path(k)
        name = os.path.basename(path)
        meta = self._file_meta.get(name)
        if meta is not None:
            try:
                size = os.path.getsize(path)
            except OSError as e:
                log.fatal("shard %s under %s is unreadable: %r"
                          % (name, self.spill_dir, e))
            if size != int(meta["bytes"]):
                log.fatal("shard %s is truncated: %d bytes on disk, "
                          "manifest records %d"
                          % (name, size, int(meta["bytes"])))
            # full content hash once per shard (first open). The hash
            # read costs one pass over bytes the first sweep is about
            # to stage anyway (page-cache warm); very large runs that
            # would rather skip it set LIGHTGBM_TPU_SHARD_VERIFY=0 —
            # the per-open size check above always stays on
            if k not in self._verified_shards \
                    and os.environ.get("LIGHTGBM_TPU_SHARD_VERIFY",
                                       "1") != "0":
                if sha256_file(path) != meta["sha256"]:
                    log.fatal("shard %s fails its manifest content "
                              "hash (truncated or poisoned spill); "
                              "rebuild the spill directory" % name)
                self._verified_shards.add(k)

        def _open():
            faults.check("shard_open", shard=name)
            return np.load(path, mmap_mode="r")

        return retry_call(_open, site="shard_open")

    def assemble_bins(self) -> np.ndarray:
        """Concatenate every shard into one [N, F_used] host matrix.
        O(dataset) memory — for tests and small-data debugging ONLY."""
        return np.concatenate([np.asarray(self.shard_bins_host(k))
                               for k in range(self.num_shards)])

    # ------------------------------------------------------------------
    # BinnedDataset surface (duck-typed subset)
    # ------------------------------------------------------------------
    @property
    def num_data(self) -> int:
        return int(sum(self.shard_sizes))

    @property
    def num_features(self) -> int:
        return len(self.bin_mappers)

    def real_threshold(self, feature: int, bin_idx: int) -> float:
        return self.bin_mappers[feature].bin_to_value(bin_idx)

    def real_feature_index(self, inner_feature: int) -> int:
        return self.used_feature_map[inner_feature]

    def inner_feature_index(self, real_feature: int) -> int:
        try:
            return self.used_feature_map.index(real_feature)
        except ValueError:
            return -1

    def feature_infos(self) -> List[str]:
        infos = ["none"] * self.num_total_features
        for f, bm in zip(self.used_feature_map, self.bin_mappers):
            infos[f] = bm.feature_info()
        return infos


class ShardPrefetcher:
    """Double-buffered shard staging for an ordered shard sweep.

    ``sweep()`` yields ``(k, device_bins)`` for every shard in order.
    While the consumer computes on shard *k*, a single worker thread is
    already loading + padding + ``device_put``-ing shard *k+1*
    (``io::shard_stage`` scope, so the overlap is visible in traces).
    Blocked time in the consumer — the device sat idle waiting for
    bytes — lands on the ``io/prefetch_stall_ms`` counter; the
    ``prefetch_stall`` watchdog rule (obs/health.py) turns a sustained
    stall share into a ``health`` event on day-long runs.

    Shards are padded to ``[n_k + 1, pad_cols]``: the extra all-zero
    row is the nonzero-gather fill target of the sharded learner (its
    gh is zero, so it vanishes from every histogram sum), and the
    column pad mirrors the serial learner's canonical feature padding.

    With ``num_shards <= 2`` both staged buffers fit the double-buffer
    budget anyway, so they are cached across sweeps (no re-staging —
    a single-shard dataset trains at in-memory staging cost). Beyond
    that, references are dropped after each shard's last use so the
    allocator recycles the HBM (donate-style buffer reuse).
    """

    def __init__(self, dataset: ShardedBinnedDataset, pad_cols: int):
        self.dataset = dataset
        self.pad_cols = int(pad_cols)
        self._resident = {} if dataset.num_shards <= 2 else None
        try:
            t = float(os.environ.get(_ENV_STAGE_TIMEOUT, 600))
        except ValueError:
            t = 600.0
        # <= 0 disables the bound (same convention as the dtrain
        # collective timeout); a negative value must never become an
        # instantly-expiring fut.result(timeout<0)
        self._stage_timeout = t if t > 0 else None
        import weakref
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="shard-prefetch")
        # a learner holds its prefetcher for life; reclaim the worker
        # thread when the learner goes away, not at interpreter exit
        self._finalizer = weakref.finalize(self, self._pool.shutdown,
                                           False)

    def _load_and_stage(self, k: int):
        with obs.scope("io::shard_stage"):
            ds = self.dataset
            n_k = ds.shard_sizes[k]

            def _stage():
                faults.check("prefetch_device_put", shard=k)
                host = np.zeros((n_k + 1, self.pad_cols),
                                dtype=ds.bins_dtype)
                host[:n_k, :ds.num_features] = ds.shard_bins_host(k)
                return _device_put(host)

            # transient staging failures (a busy runtime, an I/O
            # hiccup re-reading the memmap) retry with seeded backoff;
            # exhaustion re-raises and sweep() turns the worker's
            # exception into a fatal on the CONSUMER thread
            dev = retry_call(_stage, site="prefetch_device_put",
                             retry_on=(OSError, RuntimeError))
            obs.inc("io/shards_staged")
            return dev

    def _await(self, fut, k: int):
        """Blocking wait for a staged shard, bounded and loud: a worker
        exception re-raises HERE (the consuming thread) as a fatal with
        telemetry flushed, and a wedged staging hop becomes a fatal
        ``health`` event after ``LIGHTGBM_TPU_STAGE_TIMEOUT_S`` instead
        of an indefinite hang."""
        try:
            return fut.result(timeout=self._stage_timeout)
        except concurrent.futures.TimeoutError:
            obs_events.emit("health", rule="prefetch_hang",
                            severity="fatal", shard=k,
                            timeout_s=self._stage_timeout,
                            detail="shard staging did not complete")
            obs_events.flush()
            log.fatal("staging shard %d did not complete within %.0f s "
                      "(%s); the prefetch worker is wedged"
                      % (k, self._stage_timeout, _ENV_STAGE_TIMEOUT))
        except Exception as e:
            log.fatal("staging shard %d failed after retries: %r"
                      % (k, e))

    def _submit(self, k: int):
        if self._resident is not None and k in self._resident:
            return self._resident[k]
        return self._pool.submit(self._load_and_stage, k)

    def sweep(self):
        """Ordered iterator over all shards, prefetching one ahead.
        Staging of shard 0 begins at the CALL, not at the first
        iteration — so a caller can start the next sweep before its
        own device read-back and the worker stages through that sync
        window instead of sitting idle."""
        fut0 = self._submit(0)

        def _iter(fut):
            n = self.dataset.num_shards
            for k in range(n):
                nxt = self._submit(k + 1) if k + 1 < n else None
                if hasattr(fut, "result"):
                    t0 = time.perf_counter()
                    stalled = not fut.done()
                    arr = self._await(fut, k)
                    if stalled:
                        obs.inc("io/prefetch_stall_ms", max(int(
                            (time.perf_counter() - t0) * 1000), 1))
                    if self._resident is not None:
                        self._resident[k] = arr
                else:
                    arr = fut          # resident cache hit
                yield k, arr
                del arr                # drop the consumer-side reference
                fut = nxt

        return _iter(fut0)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        self._resident = {} if self._resident is not None else None
