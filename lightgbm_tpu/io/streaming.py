"""Streaming / chunked dataset construction.

Equivalent of the reference's push/streaming ingestion surface used by
external engines (reference: LGBM_DatasetInitStreaming /
LGBM_DatasetPushRowsWithMetadata, include/LightGBM/c_api.h:176-299;
ChunkedArray, include/LightGBM/utils/chunked_array.hpp): rows arrive in
chunks whose total count may be unknown up front, metadata rides along,
and the binned dataset materializes once at finalize.

TPU-first redesign: the reference pushes rows into pre-built Bin
columns (bin mappers already constructed from a sample). Here chunks
are staged host-side in a ChunkedBuffer (amortized growth, no
reallocation-copy of earlier chunks), the bin mappers are built at
``finalize()`` from a reservoir sample of the streamed rows, and the
one device transfer happens after binning — streaming into HBM row by
row would serialize tiny transfers through the host.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..utils import log
from .dataset import BinnedDataset


class ChunkedBuffer:
    """Append-only chunked 2-D float buffer (reference:
    ChunkedArray<T>, include/LightGBM/utils/chunked_array.hpp — fixed
    chunks, O(1) append, single coalesce at the end)."""

    def __init__(self, num_cols: int, chunk_rows: int = 1 << 16,
                 dtype=np.float64):
        self.num_cols = int(num_cols)
        self.chunk_rows = int(chunk_rows)
        self.dtype = dtype
        self._chunks: List[np.ndarray] = []
        self._fill = 0  # rows used in the last chunk

    def __len__(self) -> int:
        if not self._chunks:
            return 0
        return (len(self._chunks) - 1) * self.chunk_rows + self._fill

    def append_rows(self, rows: np.ndarray) -> None:
        rows = np.atleast_2d(np.asarray(rows, dtype=self.dtype))
        if rows.shape[1] != self.num_cols:
            log.fatal("pushed chunk has %d columns, expected %d"
                      % (rows.shape[1], self.num_cols))
        pos = 0
        n = rows.shape[0]
        while pos < n:
            if not self._chunks or self._fill == self.chunk_rows:
                self._chunks.append(np.empty(
                    (self.chunk_rows, self.num_cols), dtype=self.dtype))
                self._fill = 0
            take = min(n - pos, self.chunk_rows - self._fill)
            self._chunks[-1][self._fill:self._fill + take] = \
                rows[pos:pos + take]
            self._fill += take
            pos += take

    def coalesce(self) -> np.ndarray:
        """One contiguous [n, num_cols] array (reference:
        ChunkedArray::coalesce_to)."""
        if not self._chunks:
            return np.empty((0, self.num_cols), dtype=self.dtype)
        parts = self._chunks[:-1] + [self._chunks[-1][:self._fill]]
        return np.concatenate(parts, axis=0)


class StreamingDataset:
    """Push-mode dataset builder (reference:
    LGBM_DatasetInitStreaming → PushRows*WithMetadata →
    LGBM_DatasetMarkFinished, c_api.h:176-330).

    >>> sd = StreamingDataset(num_features=28, params={...})
    >>> for chunk_X, chunk_y in stream:
    ...     sd.push_rows(chunk_X, label=chunk_y)
    >>> ds = sd.finalize()            # BinnedDataset, device-resident
    """

    def __init__(self, num_features: int,
                 params: Optional[dict] = None,
                 chunk_rows: int = 1 << 16,
                 has_weight: bool = False,
                 has_init_score: bool = False,
                 has_group: bool = False,
                 spill_dir: Optional[str] = None,
                 spill_threshold_rows: Optional[int] = None):
        self.config = Config.from_params(dict(params or {}))
        self.num_features = int(num_features)
        self._X = ChunkedBuffer(num_features, chunk_rows)
        self._label = ChunkedBuffer(1, chunk_rows)
        self._weight = ChunkedBuffer(1, chunk_rows) if has_weight else None
        self._init_score = ChunkedBuffer(1, chunk_rows) \
            if has_init_score else None
        self._group: Optional[List[int]] = [] if has_group else None
        self._finished = False
        # out-of-core spill routing (io/shards.py): with a spill_dir,
        # finalize() bins chunk-by-chunk into memory-mapped shards —
        # the full f64 matrix is NEVER coalesced — returning a
        # ShardedBinnedDataset. spill_threshold_rows gates the routing
        # on size (below it the in-memory path runs as before).
        self.spill_dir = spill_dir
        self.spill_threshold_rows = spill_threshold_rows

    @property
    def num_pushed(self) -> int:
        return len(self._X)

    def push_rows(self, X: np.ndarray,
                  label: Optional[Sequence[float]] = None,
                  weight: Optional[Sequence[float]] = None,
                  init_score: Optional[Sequence[float]] = None,
                  group: Optional[Sequence[int]] = None) -> None:
        """Append a chunk of rows plus aligned metadata (reference:
        LGBM_DatasetPushRowsByCSRWithMetadata semantics — metadata
        arrives with the rows, not afterwards)."""
        if self._finished:
            log.fatal("push_rows after finalize()")
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        self._X.append_rows(X)
        if label is not None:
            self._label.append_rows(
                np.asarray(label, dtype=np.float64).reshape(-1, 1))
        if weight is not None:
            if self._weight is None:
                log.fatal("weight pushed but has_weight=False")
            self._weight.append_rows(
                np.asarray(weight, dtype=np.float64).reshape(-1, 1))
        if init_score is not None:
            if self._init_score is None:
                log.fatal("init_score pushed but has_init_score=False")
            self._init_score.append_rows(
                np.asarray(init_score, dtype=np.float64).reshape(-1, 1))
        if group is not None:
            if self._group is None:
                log.fatal("group pushed but has_group=False")
            self._group.extend(int(g) for g in np.atleast_1d(group))

    def _chunk_source(self):
        """Zero-copy views over the pushed chunks as a re-iterable
        (X, y, w) chunk source for the sharded builder — the X and
        metadata ChunkedBuffers share ``chunk_rows``, so their chunk
        boundaries align row-for-row."""
        n = len(self._X)
        has_label = bool(len(self._label))
        has_weight = self._weight is not None and bool(len(self._weight))
        if has_label and len(self._label) != n:
            log.fatal("pushed %d label values for %d rows"
                      % (len(self._label), n))
        if has_weight and len(self._weight) != n:
            log.fatal("pushed %d weight values for %d rows"
                      % (len(self._weight), n))

        def source():
            chunks = self._X._chunks
            for i, xc in enumerate(chunks):
                hi = self._X._fill if i == len(chunks) - 1 \
                    else self._X.chunk_rows
                y = (self._label._chunks[i][:hi, 0]
                     if has_label else None)
                w = (self._weight._chunks[i][:hi, 0]
                     if has_weight else None)
                yield xc[:hi], y, w
        return source, n

    def finalize(self, reference: Optional[BinnedDataset] = None,
                 spill_dir: Optional[str] = None,
                 shard_rows: Optional[int] = None, **kw):
        """Build bin mappers and bin the pushed rows (reference:
        LGBM_DatasetMarkFinished → FinishLoad). Default: coalesce +
        ``BinnedDataset.from_matrix`` (device-resident). With a
        ``spill_dir`` (here or at construction) — optionally gated on
        ``spill_threshold_rows`` — the rows route through the sharded
        out-of-core builder instead: binned chunk-by-chunk into
        memory-mapped shards, no f64 coalesce, identical mappers (the
        known row count lets the sharded builder replicate
        ``from_matrix``'s exact bin-construction sample), returning a
        :class:`~.shards.ShardedBinnedDataset`."""
        if self._finished:
            log.fatal("finalize() called twice")
        self._finished = True
        n = len(self._X)
        if n == 0:
            log.fatal("no rows pushed before finalize()")
        spill_dir = spill_dir if spill_dir is not None else self.spill_dir
        thr = self.spill_threshold_rows
        if spill_dir is not None and (thr is None or n >= thr):
            if reference is not None:
                log.fatal("sharded finalize cannot align to a "
                          "reference dataset")
            if self._init_score is not None and len(self._init_score):
                log.fatal("init_score is not supported on the sharded "
                          "spill path")
            if self._group:
                log.fatal("query groups are not supported on the "
                          "sharded spill path")
            if kw.get("keep_raw_data"):
                log.fatal("keep_raw_data/linear_tree needs the "
                          "coalesced matrix; not supported on the "
                          "sharded spill path")
            from .shards import ShardedBinnedDataset
            source, total = self._chunk_source()
            return ShardedBinnedDataset.from_chunk_source(
                source, self.config, spill_dir, shard_rows=shard_rows,
                feature_names=kw.get("feature_names"),
                categorical_feature=kw.get("categorical_feature"),
                total_rows=total)
        X = self._X.coalesce()
        def aligned(buf, what):
            if buf is None or not len(buf):
                return None
            vals = buf.coalesce()[:, 0]
            if len(vals) != n:
                log.fatal("pushed %d %s values for %d rows"
                          % (len(vals), what, n))
            return vals

        label = aligned(self._label, "label")
        weight = aligned(self._weight, "weight")
        init_score = aligned(self._init_score, "init_score")
        group = (np.asarray(self._group, dtype=np.int32)
                 if self._group else None)
        if group is not None and int(group.sum()) != n:
            log.fatal("pushed query sizes sum to %d for %d rows"
                      % (int(group.sum()), n))
        return BinnedDataset.from_matrix(
            X, self.config, label=label, weights=weight,
            init_score=init_score, group=group, reference=reference,
            **kw)
