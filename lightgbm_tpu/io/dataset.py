"""Binned dataset + metadata for lightgbm_tpu.

TPU-native analogue of the reference's ``Dataset``/``Metadata``
(reference: include/LightGBM/dataset.h:426,46; src/io/dataset.cpp,
src/io/metadata.cpp). Where the reference keeps per-feature ``Bin`` columns
(dense/sparse, 4/8/16-bit, src/io/dense_bin.hpp) optimized for CPU cache and
histogram prefetch, the TPU build keeps ONE dense row-major uint8/uint16 bin
matrix padded for HBM tiling — the analogue of the CUDA backend's row-wise
``CUDARowData`` (reference: include/LightGBM/cuda/cuda_row_data.hpp:31-89) —
because XLA histogramming wants a single contiguous [rows, features] tensor.

Construction pipeline (reference: DatasetLoader::ConstructFromSampleData,
src/io/dataset_loader.cpp:593):
  sample rows -> BinMapper.find_bin per feature -> value_to_bin over the full
  column -> drop trivial features -> pack.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import Config
from ..obs import events as obs_events
from ..obs.registry import registry as obs
from ..utils import log
from .binning import BinMapper, BinType, MissingType


class Metadata:
    """Labels / weights / query boundaries / init score
    (reference: include/LightGBM/dataset.h:46, src/io/metadata.cpp:26)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label = np.zeros(num_data, dtype=np.float32)
        self.weights: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            log.fatal("Length of label (%d) != num_data (%d)"
                      % (len(label), self.num_data))
        self.label = label

    def set_weights(self, weights: Optional[Sequence[float]]) -> None:
        if weights is None:
            self.weights = None
            return
        weights = np.asarray(weights, dtype=np.float32).reshape(-1)
        if len(weights) != self.num_data:
            log.fatal("Length of weights (%d) != num_data (%d)"
                      % (len(weights), self.num_data))
        self.weights = weights

    def set_group(self, group: Optional[Sequence[int]]) -> None:
        """Group sizes -> query boundaries
        (reference: Metadata::SetQuery, src/io/metadata.cpp:456)."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        if group.sum() != self.num_data:
            log.fatal("Sum of group sizes (%d) != num_data (%d)"
                      % (int(group.sum()), self.num_data))
        self.query_boundaries = np.concatenate(
            [[0], np.cumsum(group)]).astype(np.int32)

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        init_score = np.asarray(init_score, dtype=np.float64).reshape(-1)
        if len(init_score) % max(self.num_data, 1) != 0:
            # len == num_data or num_class * num_data
            # (reference: Metadata::SetInitScore, src/io/metadata.cpp)
            log.fatal("Length of init_score (%d) must be a multiple of "
                      "num_data (%d)" % (len(init_score), self.num_data))
        self.init_score = init_score

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class BinnedDataset:
    """Quantized training data (reference: include/LightGBM/dataset.h:426).

    Attributes
    ----------
    bins : np.ndarray [num_data, num_used_features] uint8/uint16
        Row-major bin matrix; the HBM-resident training payload.
    bin_mappers : list[BinMapper]  (one per *used* feature)
    used_feature_map : original column index per used feature
    num_bin_per_feature / max_num_bin : histogram sizing
    """

    def __init__(self) -> None:
        self.bins: np.ndarray = np.zeros((0, 0), dtype=np.uint8)
        self.bin_mappers: List[BinMapper] = []
        self.used_feature_map: List[int] = []
        self.num_total_features: int = 0
        self.feature_names: List[str] = []
        self.metadata: Metadata = Metadata(0)
        self.max_num_bin: int = 0
        self.num_bin_per_feature: np.ndarray = np.zeros(0, dtype=np.int32)
        self.monotone_constraints: Optional[np.ndarray] = None
        self.feature_penalty: Optional[np.ndarray] = None
        self.raw_data: Optional[np.ndarray] = None  # kept for linear trees
        # EFB: when set, ``bins`` is the bundled [N, G] matrix (io/efb.py)
        self.bundle = None

    # ------------------------------------------------------------------
    @classmethod
    def from_matrix(cls, data: np.ndarray, config: Config,
                    label: Optional[Sequence[float]] = None,
                    weights: Optional[Sequence[float]] = None,
                    group: Optional[Sequence[int]] = None,
                    init_score: Optional[Sequence[float]] = None,
                    feature_names: Optional[List[str]] = None,
                    categorical_feature: Optional[Sequence[Union[int, str]]] = None,
                    reference: Optional["BinnedDataset"] = None,
                    keep_raw_data: bool = False) -> "BinnedDataset":
        """Build from a dense float matrix (reference:
        DatasetLoader::ConstructFromSampleData, src/io/dataset_loader.cpp:593,
        for the sample pass; Dataset::PushRow + FinishLoad for the full pass)."""
        is_sparse = hasattr(data, "tocsc")
        if is_sparse:
            # scipy input stays sparse end-to-end: every per-column pass
            # is O(nnz), never materializing a dense value column
            # (reference analogue: SparseBin, src/io/sparse_bin.hpp —
            # delta-encoded pushes; here CSC slices feed the binner and
            # EFB bundles the exclusive columns)
            data = data.tocsc()
            if not data.has_canonical_format:
                # duplicate (row, col) entries must SUM (dense semantics);
                # copy first — tocsc() may alias the caller's matrix
                data = data.copy()
                data.sum_duplicates()
            if keep_raw_data:
                log.fatal("keep_raw_data/linear_tree requires dense input")
        else:
            data = np.asarray(data)
            if data.dtype not in (np.float32, np.float64):
                data = data.astype(np.float64)
            if data.ndim != 2:
                log.fatal("Training data must be 2-dimensional")
        n, num_total_features = data.shape

        def col_nonzero(f: int):
            """Sparse column f as (row_indices, values) — O(nnz)."""
            sl = slice(int(data.indptr[f]), int(data.indptr[f + 1]))
            return data.indices[sl], np.asarray(data.data[sl],
                                                dtype=np.float64)

        def full_col(f: int) -> np.ndarray:
            return data[:, f]   # dense paths only; sparse uses col_nonzero

        self = cls()
        self.num_total_features = num_total_features
        self.feature_names = list(feature_names) if feature_names else [
            f"Column_{i}" for i in range(num_total_features)]

        if categorical_feature is None and config.categorical_feature:
            categorical_feature = config.categorical_feature
        cat_set = _resolve_categorical(categorical_feature, self.feature_names)

        if reference is not None:
            # validation set aligned with the training set's bin mappers
            # (reference: DatasetLoader::LoadFromFileAlignWithOtherDataset,
            # src/io/dataset_loader.cpp:299)
            self.bin_mappers = reference.bin_mappers
            self.used_feature_map = reference.used_feature_map
            self.num_bin_per_feature = reference.num_bin_per_feature
            self.max_num_bin = reference.max_num_bin
            self.monotone_constraints = reference.monotone_constraints
            self.feature_penalty = reference.feature_penalty
            self.bundle = reference.bundle
        else:
            # --- sampling pass (bin_construct_sample_cnt, config.h:641) ---
            sample_cnt = min(config.bin_construct_sample_cnt, n)
            rng = np.random.RandomState(config.data_random_seed)
            if sample_cnt < n:
                sample_idx = np.sort(rng.choice(n, sample_cnt, replace=False))
            else:
                sample_idx = None
            max_bin_by_feature = validate_max_bin_by_feature(
                config, num_total_features)
            forced_bounds = load_forced_bounds(config)
            mappers: List[BinMapper] = []
            sample_bin_cols: List[np.ndarray] = []
            sample_cnt_eff = sample_cnt if sample_idx is not None else n
            with obs.scope("io::find_bins"):
                for f in range(num_total_features):
                    if is_sparse:
                        # feed the binner only the sampled NON-ZERO values;
                        # total_sample_cnt accounts the zeros (the reference
                        # samples exactly this way —
                        # DatasetLoader::SampleTextData keeps non-zeros +
                        # the global sample count, dataset_loader.cpp:593)
                        rows, vals = col_nonzero(f)
                        if sample_idx is not None:
                            pos = np.searchsorted(sample_idx, rows)
                            pos_ok = pos < len(sample_idx)
                            pos_ok[pos_ok] &= (sample_idx[pos[pos_ok]]
                                               == rows[pos_ok])
                            sample_col = vals[pos_ok]
                            sample_rows = pos[pos_ok]
                        else:
                            sample_col = vals
                            sample_rows = rows
                    else:
                        col = full_col(f)
                        sample_col = (col if sample_idx is None
                                      else col[sample_idx])
                    bm = find_bin_for_feature(
                        f, sample_col, sample_cnt_eff, config, cat_set,
                        forced_bounds, max_bin_by_feature)
                    mappers.append(bm)
                    if not bm.is_trivial:
                        if is_sparse:
                            sb = np.full(sample_cnt_eff, bm.default_bin,
                                         dtype=np.int32)
                            sb[sample_rows] = bm.value_to_bin(sample_col)
                            sample_bin_cols.append(sb)
                        else:
                            sample_bin_cols.append(
                                bm.value_to_bin(sample_col).astype(np.int32))
            self.bin_mappers = [m for m in mappers if not m.is_trivial]
            self.used_feature_map = [i for i, m in enumerate(mappers)
                                     if not m.is_trivial]
            if not self.bin_mappers:
                log.warning("There are no meaningful features which satisfy "
                            "the provided configuration. Decreasing "
                            "Dataset parameters min_data_in_bin or min_data_in_leaf "
                            "and re-constructing Dataset might resolve this warning.")
            self.num_bin_per_feature = np.asarray(
                [m.num_bin for m in self.bin_mappers], dtype=np.int32)
            self.max_num_bin = int(self.num_bin_per_feature.max()) if len(
                self.num_bin_per_feature) else 1
            self._set_constraints(config)
            if config.enable_bundle and len(self.bin_mappers) > 1:
                with obs.scope("io::efb_bundle"):
                    self._find_bundles(sample_bin_cols, config)

        # --- full binning pass (O(nnz) per column on sparse input) ---
        def binned_col(j: int) -> np.ndarray:
            f, bm = self.used_feature_map[j], self.bin_mappers[j]
            if is_sparse:
                rows, vals = col_nonzero(f)
                out = np.full(n, bm.default_bin, dtype=np.int32)
                out[rows] = bm.value_to_bin(vals)
                return out
            return bm.value_to_bin(full_col(f))

        with obs.scope("io::apply_bins"):
            if self.bundle is not None:
                from .efb import bundle_columns
                dtype = (np.uint8 if self.bundle.num_bundled_bins <= 256
                         else np.uint16)
                zero_bins = np.asarray(
                    [m.default_bin for m in self.bin_mappers],
                    dtype=np.int32)
                self.bins = bundle_columns(binned_col, self.bundle,
                                           zero_bins, n, dtype)
            else:
                dtype = np.uint8 if self.max_num_bin <= 256 else np.uint16
                bins = np.empty((n, len(self.bin_mappers)), dtype=dtype)
                for j in range(len(self.bin_mappers)):
                    bins[:, j] = binned_col(j).astype(dtype)
                self.bins = bins
        if keep_raw_data:
            self.raw_data = data

        self.metadata = Metadata(n)
        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weights(weights)
        self.metadata.set_group(group)
        self.metadata.set_init_score(init_score)
        obs_events.emit(
            "dataset", num_data=n, num_features=self.num_features,
            num_total_features=num_total_features,
            max_num_bin=self.max_num_bin,
            bundled=self.bundle is not None,
            aligned_to_reference=reference is not None)
        return self

    # ------------------------------------------------------------------
    def _set_constraints(self, config: Config) -> None:
        if config.monotone_constraints:
            mc = np.zeros(len(self.bin_mappers), dtype=np.int8)
            for j, f in enumerate(self.used_feature_map):
                if f < len(config.monotone_constraints):
                    mc[j] = config.monotone_constraints[f]
            self.monotone_constraints = mc
        if config.feature_contri:
            fp = np.ones(len(self.bin_mappers), dtype=np.float64)
            for j, f in enumerate(self.used_feature_map):
                if f < len(config.feature_contri):
                    fp[j] = config.feature_contri[f]
            self.feature_penalty = fp

    # ------------------------------------------------------------------
    def _find_bundles(self, sample_bin_cols: List[np.ndarray],
                      config: Config) -> None:
        """Greedy EFB over the sampled binned columns (reference:
        Dataset::FindGroups, src/io/dataset.cpp:107). Only numerical,
        non-NaN-missing, mostly-zero features are candidates."""
        from .efb import build_layout, find_groups
        F = len(self.bin_mappers)
        if not sample_bin_cols or F < 2:
            return
        sample_cnt = len(sample_bin_cols[0])
        zero_bins = np.asarray([m.default_bin for m in self.bin_mappers],
                               dtype=np.int32)
        masks: List[Optional[np.ndarray]] = []
        for j, m in enumerate(self.bin_mappers):
            if (m.bin_type == BinType.CATEGORICAL
                    or m.missing_type == MissingType.NAN
                    or m.num_bin < 2):
                masks.append(None)
                continue
            nz = sample_bin_cols[j] != zero_bins[j]
            # bundling only pays off on sparse columns (reference:
            # kSparseThreshold, include/LightGBM/bin.h:39)
            masks.append(nz if nz.mean() <= 0.3 else None)
        if all(mk is None for mk in masks):
            return
        max_bundle_bins = max(self.max_num_bin, min(config.max_bin + 1, 256))
        groups = find_groups(masks, self.num_bin_per_feature, sample_cnt,
                             max_bundle_bins)
        if all(len(g) == 1 for g in groups):
            return
        self.bundle = build_layout(groups, self.num_bin_per_feature,
                                   zero_bins, self.max_num_bin)
        log.info("EFB: bundled %d features into %d columns"
                 % (F, self.bundle.num_groups))

    def feature_bin_column(self, j: int) -> np.ndarray:
        """Per-feature bin column, unbundling if needed (host)."""
        if self.bundle is None:
            return self.bins[:, j]
        lay = self.bundle
        g = int(lay.group_of[j])
        col = self.bins[:, g].astype(np.int64)
        zb = self.bin_mappers[j].default_bin
        return np.where(lay.member[g][col] == j, lay.unmap[g][col],
                        zb).astype(self.bins.dtype)

    def feature_bins(self) -> np.ndarray:
        """[N, F] per-feature bin matrix; materializes when bundled
        (memory-heavy on wide sparse data — only host traversal paths
        need it)."""
        if self.bundle is None:
            return self.bins
        out = np.empty((self.bins.shape[0], len(self.bin_mappers)),
                       dtype=self.bins.dtype)
        for j in range(len(self.bin_mappers)):
            out[:, j] = self.feature_bin_column(j)
        return out

    # ------------------------------------------------------------------
    @property
    def num_data(self) -> int:
        return self.bins.shape[0]

    @property
    def num_features(self) -> int:
        return len(self.bin_mappers)

    def real_threshold(self, feature: int, bin_idx: int) -> float:
        """Bin index -> real-valued split threshold for model storage
        (reference: Tree::Split records RealThreshold via BinToValue)."""
        return self.bin_mappers[feature].bin_to_value(bin_idx)

    def real_feature_index(self, inner_feature: int) -> int:
        return self.used_feature_map[inner_feature]

    def inner_feature_index(self, real_feature: int) -> int:
        try:
            return self.used_feature_map.index(real_feature)
        except ValueError:
            return -1

    def feature_infos(self) -> List[str]:
        infos = ["none"] * self.num_total_features
        for f, bm in zip(self.used_feature_map, self.bin_mappers):
            infos[f] = bm.feature_info()
        return infos


def validate_max_bin_by_feature(config, num_total_features: int) -> list:
    """``max_bin_by_feature`` checks (reference:
    src/io/dataset_loader.cpp:614-616 CHECK_EQ/CHECK_GT); returns the
    (possibly empty) per-feature list. Shared by ``from_matrix`` and
    the sharded builder (io/shards.py)."""
    max_bin_by_feature = config.max_bin_by_feature
    if max_bin_by_feature:
        if len(max_bin_by_feature) != num_total_features:
            log.fatal("Length of max_bin_by_feature (%d) != number of "
                      "features (%d)" % (len(max_bin_by_feature),
                                         num_total_features))
        if min(max_bin_by_feature) <= 1:
            log.fatal("Each entry of max_bin_by_feature must be > 1")
    return max_bin_by_feature or []


def find_bin_for_feature(f: int, sample_col: np.ndarray,
                         total_sample_cnt: int, config: Config,
                         cat_set: set, forced_bounds: dict,
                         max_bin_by_feature: list) -> BinMapper:
    """THE per-feature ``find_bin`` knob set — one definition shared by
    ``from_matrix`` and the sharded out-of-core builder (io/shards.py),
    so the two construction paths cannot drift apart: identical mappers
    over an identical sample are the sharded path's bit-parity
    contract."""
    bm = BinMapper()
    max_bin_f = (max_bin_by_feature[f] if f < len(max_bin_by_feature)
                 else config.max_bin)
    bm.find_bin(
        sample_col, total_sample_cnt=total_sample_cnt,
        max_bin=max_bin_f,
        min_data_in_bin=config.min_data_in_bin,
        min_split_data=config.min_data_in_leaf,
        pre_filter=config.feature_pre_filter,
        bin_type=(BinType.CATEGORICAL if f in cat_set
                  else BinType.NUMERICAL),
        use_missing=config.use_missing,
        zero_as_missing=config.zero_as_missing,
        forced_upper_bounds=forced_bounds.get(f))
    return bm


def load_forced_bounds(config) -> dict:
    """forcedbins_filename (config.h:740): JSON list of
    {"feature": i, "bin_upper_bound": [...]} entries
    (reference: DatasetLoader reads it into forced_bins then
    BinMapper::FindBin applies FindBinWithPredefinedBin). Shared by the
    in-memory construction above and the out-of-core sharded builder
    (io/shards.py)."""
    forced_bounds: dict = {}
    if getattr(config, "forcedbins_filename", ""):
        import json
        try:
            with open(config.forcedbins_filename) as fh:
                for entry in json.load(fh):
                    forced_bounds[int(entry["feature"])] = [
                        float(v) for v in entry["bin_upper_bound"]]
        except (OSError, ValueError, KeyError, TypeError) as e:
            log.warning("Cannot load forced bins from %s: %s"
                        % (config.forcedbins_filename, e))
    return forced_bounds


def _resolve_categorical(categorical_feature, feature_names) -> set:
    cats: set = set()
    if categorical_feature is None or categorical_feature == "auto":
        return cats
    if isinstance(categorical_feature, str):
        categorical_feature = [c for c in categorical_feature.split(",") if c]
    for c in categorical_feature:
        if isinstance(c, str) and not c.lstrip("-").isdigit():
            if c in feature_names:
                cats.add(feature_names.index(c))
            else:
                log.warning("Unknown categorical feature name: %s", c)
        else:
            cats.add(int(c))
    return cats
