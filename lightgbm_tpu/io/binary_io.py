"""Binned-dataset binary cache.

Equivalent of the reference's Dataset binary serialization
(reference: Dataset::SaveBinaryFile, include/LightGBM/dataset.h:623;
DatasetLoader::LoadFromBinFile, src/io/dataset_loader.cpp:417): quantize
once, reload instantly. The format here is npz + a pickled mapper block
(our own container — the capability, not the byte layout, is the parity
target).
"""
from __future__ import annotations

import io
import pickle

import numpy as np

from ..utils import log
from .dataset import BinnedDataset, Metadata

_MAGIC = "lightgbm_tpu.binned.v1"


def save_binary(dataset: BinnedDataset, path: str) -> None:
    meta = {
        "magic": _MAGIC,
        "bin_mappers": dataset.bin_mappers,
        "used_feature_map": dataset.used_feature_map,
        "num_total_features": dataset.num_total_features,
        "feature_names": dataset.feature_names,
        "max_num_bin": dataset.max_num_bin,
        "monotone_constraints": dataset.monotone_constraints,
        "feature_penalty": dataset.feature_penalty,
        "bundle": dataset.bundle,
    }
    md = dataset.metadata
    np.savez_compressed(
        path, bins=dataset.bins,
        num_bin_per_feature=dataset.num_bin_per_feature,
        label=md.label,
        weights=(md.weights if md.weights is not None
                 else np.zeros(0, dtype=np.float32)),
        query_boundaries=(md.query_boundaries
                          if md.query_boundaries is not None
                          else np.zeros(0, dtype=np.int32)),
        init_score=(md.init_score if md.init_score is not None
                    else np.zeros(0)),
        meta=np.frombuffer(pickle.dumps(meta), dtype=np.uint8))


def load_binary(path: str) -> BinnedDataset:
    import os
    if not os.path.exists(path) and os.path.exists(path + ".npz"):
        path = path + ".npz"  # np.savez appends the suffix
    z = np.load(path, allow_pickle=False)
    meta = pickle.loads(z["meta"].tobytes())
    if meta.get("magic") != _MAGIC:
        log.fatal("Not a lightgbm_tpu binary dataset: %s" % path)
    ds = BinnedDataset()
    ds.bins = z["bins"]
    ds.num_bin_per_feature = z["num_bin_per_feature"]
    ds.bin_mappers = meta["bin_mappers"]
    ds.used_feature_map = meta["used_feature_map"]
    ds.num_total_features = meta["num_total_features"]
    ds.feature_names = meta["feature_names"]
    ds.max_num_bin = meta["max_num_bin"]
    ds.monotone_constraints = meta["monotone_constraints"]
    ds.feature_penalty = meta["feature_penalty"]
    ds.bundle = meta.get("bundle")
    n = ds.bins.shape[0]
    md = Metadata(n)
    md.set_label(z["label"])
    if len(z["weights"]):
        md.set_weights(z["weights"])
    if len(z["query_boundaries"]):
        md.query_boundaries = z["query_boundaries"]
    if len(z["init_score"]):
        md.set_init_score(z["init_score"])
    ds.metadata = md
    return ds
