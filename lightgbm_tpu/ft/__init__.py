"""Fault-tolerant training plane.

A production run measured in hours must be restartable by construction
(the premise of XGBoost's scalable-GPU external-memory design and the
reference's ``snapshot_freq``): a SIGKILL, an OOM kill, or a preempted
host must cost at most one checkpoint interval, never the run. This
package owns the crash-consistency layer:

- :mod:`checkpoint` — atomically-finalized checkpoint directories
  capturing the FULL resume state (trees, iteration/early-stop
  bookkeeping, every host+device RNG sequence position, the training
  scores bit-exactly), wired into ``lgb.train(checkpoint_dir=,
  checkpoint_freq=, resume=True)`` and
  ``GBDT.save_checkpoint``/``load_checkpoint``.

Its failure-path siblings live where their call sites are:
``utils/retry.py`` (bounded seeded retry/backoff), ``obs/faults.py``
(deterministic fault injection), and the degradation paths in
``io/shards.py`` (ENOSPC spill fallback, shard hash verification,
prefetcher failure propagation). docs/RELIABILITY.md is the contract.
"""
from . import checkpoint  # noqa: F401

__all__ = ["checkpoint"]
