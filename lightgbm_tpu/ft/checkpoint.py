"""Crash-consistent checkpoint/resume for training runs.

A checkpoint is a DIRECTORY (``ckpt-<iter>/`` under the caller's
checkpoint dir) finalized atomically: every file is written and fsynced
inside a hidden temp directory, a ``manifest.json`` carrying per-file
sha256 + byte counts is written LAST, and one ``os.rename`` publishes
the whole thing — the same tmp+rename discipline as the streaming
trace segments (obs/trace.py). A crash at any instruction leaves either
the previous checkpoints untouched or a ``.ckpt-tmp-*`` directory the
loader never looks at. The loader walks checkpoints newest-first and
takes the first one whose manifest hashes verify; a truncated or
poisoned checkpoint is skipped LOUDLY (``checkpoint_invalid`` event,
warning naming the file) and the run falls back to the previous one.

Resume is BIT-IDENTICAL by construction, not by luck: the state file
captures every stochastic sequence position the training loop consumes

- bagging / GOSS draws are STATELESS since the pipelined-boosting
  refactor (sample_strategy.py): the indicator at iteration *i* is
  ``fold_in(PRNGKey(bagging_seed), draw_index(i))``, a pure function of
  the config and the iteration — nothing to capture, resume recomputes
  the exact bag (the type is still recorded so a config mismatch fails
  loudly; pre-refactor v1 checkpoints carried MT19937 state the device
  draw cannot continue, hence the format-version bump),
- the learner's feature-fraction RNG and tree counter (extra_trees /
  batched-seed derivation),
- the device-side quantize tree counter from PR 8 (restored as a fresh
  ``dev_u32`` so the fold-in sequence continues exactly),
- DART's drop RNG, per-tree weights and weight sum,
- a stochastic objective's key (rank_xendcg),

and the training scores are stored as exact f32 bits (``score.npy``)
rather than recomputed — an incremental score is a specific SEQUENCE of
f32 additions (init consts added separately from tree outputs; see
``GBDT._boost_from_average`` vs ``Tree.add_bias``) that a replay of the
saved trees cannot reproduce bit-for-bit in general. On load the
existing ``GBDT.recheck_scores`` device replay re-derives the scores
from the trees anyway and the checkpoint is rejected if the stored
bits deviate beyond the f32 replay tolerance — corruption that slips
past the hash check (or a dataset that is not the one trained on)
still cannot resume silently.

The engine-level ``early_stopping`` callback's closure state (best
score/iter per metric — the patience counter is implicit in the
absolute best_iter) is captured too, via the
``gbdt._engine_state_provider`` hook ``lgb.train`` installs: resume
continues the SAME patience window instead of re-arming it from the
resume point.

NOT captured (refused or documented in docs/RELIABILITY.md): CEGB's
cross-tree device state, multi-process dtrain runs, and the dataset
itself — the caller re-binns the same rows (deterministic mappers make
the rebuilt dataset, sharded or resident, bit-identical).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import List, Optional, Tuple

import numpy as np

from ..obs import events as obs_events
from ..obs import faults
from ..obs.registry import registry as obs
from ..utils import log
from ..utils.atomic import fsync_dir, sha256_file as _sha256_file
from ..utils.retry import retry_call

# v2: bagging/GOSS became stateless device draws (pipelined boosting) —
# v1 checkpoints carry a host-MT19937 bagging stream position that the
# fold_in keying cannot continue, so the loader refuses them loudly
FORMAT_VERSION = 2
CKPT_PREFIX = "ckpt-"
TMP_PREFIX = ".ckpt-tmp-"
_ENV_KEEP = "LIGHTGBM_TPU_CKPT_KEEP"

REQUIRED_FILES = ("state.json", "model.txt", "score.npy")


class CheckpointError(Exception):
    """One checkpoint directory failed validation (the loader falls
    back to the next-older candidate)."""


# ----------------------------------------------------------------------
# small codecs
# ----------------------------------------------------------------------

def _np_rng_to_json(rng: np.random.RandomState) -> dict:
    name, keys, pos, has_gauss, cached = rng.get_state()
    return {"name": name, "keys": np.asarray(keys).tolist(),
            "pos": int(pos), "has_gauss": int(has_gauss),
            "cached_gaussian": float(cached)}


def _np_rng_from_json(d: dict) -> Tuple:
    return (d["name"], np.asarray(d["keys"], dtype=np.uint32),
            int(d["pos"]), int(d["has_gauss"]),
            float(d["cached_gaussian"]))


def _key_to_json(key) -> Optional[list]:
    """A jax PRNG key as a plain list of uint32 words (None when the
    attribute is absent / not an array). Handles both raw uint32[2]
    keys (what this package's PRNGKey calls produce) and typed keys."""
    if key is None:
        return None
    try:
        arr = np.asarray(key)
        if arr.dtype != np.uint32:
            import jax
            # jaxlint: disable=JLT001 -- checkpoint-time key
            # serialization is a deliberate one-shot sync per save
            arr = np.asarray(jax.random.key_data(key))
        return np.asarray(arr, dtype=np.uint32).reshape(-1).tolist()
    except Exception:
        return None


def _key_from_json(words: list):
    import jax.numpy as jnp
    return jnp.asarray(np.asarray(words, dtype=np.uint32))


# ----------------------------------------------------------------------
# directory scanning / validation
# ----------------------------------------------------------------------

def list_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """(iter, path) of every finalized checkpoint, newest first."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if not name.startswith(CKPT_PREFIX):
            continue
        try:
            it = int(name[len(CKPT_PREFIX):])
        except ValueError:
            continue
        out.append((it, os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def validate_dir(path: str) -> dict:
    """Verify a checkpoint directory against its manifest (presence,
    sizes, sha256 of every listed file); returns the manifest or raises
    :class:`CheckpointError` naming the first offending file."""
    man_path = os.path.join(path, "manifest.json")
    try:
        with open(man_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError("unreadable manifest %s (%s)"
                              % (man_path, e))
    files = manifest.get("files")
    if not isinstance(files, dict):
        raise CheckpointError("manifest %s has no file table" % man_path)
    for req in REQUIRED_FILES:
        if req not in files:
            raise CheckpointError("manifest %s is missing required "
                                  "entry %r" % (man_path, req))
    for name, meta in files.items():
        fp = os.path.join(path, name)
        try:
            size = os.path.getsize(fp)
        except OSError:
            raise CheckpointError("checkpoint file %s is missing" % fp)
        if size != int(meta.get("bytes", -1)):
            raise CheckpointError(
                "checkpoint file %s is truncated (%d bytes, manifest "
                "says %d)" % (fp, size, int(meta.get("bytes", -1))))
        if _sha256_file(fp) != meta.get("sha256"):
            raise CheckpointError(
                "checkpoint file %s fails its content hash" % fp)
    return manifest


# ----------------------------------------------------------------------
# state capture
# ----------------------------------------------------------------------

def _strategy_state(gbdt) -> Tuple[dict, Optional[np.ndarray]]:
    """Sampler draws are stateless (fold_in on the iteration index —
    sample_strategy.py), so only the TYPE is recorded: resume recomputes
    the exact in-bag vector from (bagging_seed, iter); what must fail
    loudly is resuming a bagging checkpoint under a bagging-free config
    (the score bits would silently diverge from the draw sequence)."""
    from ..boosting.sample_strategy import BaggingStrategy, GOSSStrategy
    st = getattr(gbdt, "sample_strategy", None)
    if isinstance(st, BaggingStrategy):
        return {"type": "bagging"}, None
    if isinstance(st, GOSSStrategy):
        return {"type": "goss"}, None
    return {"type": "none"}, None


def _learner_state(gbdt) -> dict:
    learner = getattr(gbdt, "learner", None)
    if learner is None:
        return {}
    out = {"tree_idx": int(getattr(learner, "_tree_idx", 0))}
    ff = getattr(learner, "_ff_rng", None)
    if ff is not None:
        out["ff_rng"] = _np_rng_to_json(ff)
    if getattr(learner, "_quantized", False):
        out["quant_ctr"] = int(getattr(learner, "_quant_ctr_host", 0))
    return out


def _dart_state(gbdt) -> Optional[dict]:
    from ..boosting.dart import DART
    if not isinstance(gbdt, DART):
        return None
    return {"drop_rng": _np_rng_to_json(gbdt.drop_rng),
            "tree_weight": [float(w) for w in gbdt.tree_weight],
            "sum_weight": float(gbdt.sum_weight)}


def _objective_state(gbdt) -> dict:
    obj = getattr(gbdt, "objective", None)
    key = getattr(obj, "_key", None) if obj is not None else None
    words = _key_to_json(key)
    return {"key": words} if words is not None else {}


def _config_fingerprint(gbdt) -> str:
    return hashlib.sha256(
        gbdt.config.to_param_string().encode()).hexdigest()


def _refuse_unsupported(gbdt) -> None:
    learner = getattr(gbdt, "learner", None)
    if getattr(learner, "_cegb_enabled", False):
        log.fatal("checkpointing does not capture CEGB's cross-tree "
                  "device state (used-feature/fetched matrices); "
                  "disable cegb_* to checkpoint this run")
    try:
        import jax
        if jax.process_count() > 1:
            log.fatal("checkpoint/resume is single-process; "
                      "multi-process dtrain runs are not supported")
    except Exception:
        pass


# ----------------------------------------------------------------------
# save
# ----------------------------------------------------------------------

def save(gbdt, directory: str, keep: Optional[int] = None) -> str:
    """Write one atomically-finalized checkpoint of ``gbdt`` under
    ``directory``; returns the finalized path. Idempotent per
    iteration: an existing VALID ``ckpt-<iter>`` is kept as-is."""
    _refuse_unsupported(gbdt)
    directory = str(directory)
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, "%s%08d" % (CKPT_PREFIX, gbdt.iter))
    if os.path.isdir(final):
        try:
            validate_dir(final)
            return final
        except CheckpointError as e:
            log.warning_always("replacing corrupt checkpoint %s (%s)"
                               % (final, e))
            shutil.rmtree(final, ignore_errors=True)

    with obs.scope("ft::checkpoint_save"):
        strategy, bag = _strategy_state(gbdt)
        state = {
            "format_version": FORMAT_VERSION,
            "iter": int(gbdt.iter),
            "num_init_iteration": int(gbdt.num_init_iteration),
            "best_iteration": int(gbdt.best_iteration),
            "num_class": int(gbdt.num_class),
            "num_tree_per_iteration": int(gbdt.num_tree_per_iteration),
            "num_models": len(gbdt.models),
            "boosting": type(gbdt).__name__,
            "has_init_score": bool(getattr(gbdt, "_has_init_score",
                                           False)),
            "config_fingerprint": _config_fingerprint(gbdt),
            "data_fingerprint": {
                "num_data": int(gbdt.train_data.num_data),
                "num_features": int(gbdt.train_data.num_features),
                "max_num_bin": int(gbdt.train_data.max_num_bin)},
            "early_stop": {"best_score": gbdt._best_score,
                           "best_iter": gbdt._best_iter,
                           "best_msg": gbdt._best_msg},
            "strategy": strategy,
            "learner": _learner_state(gbdt),
            "objective": _objective_state(gbdt),
        }
        dart = _dart_state(gbdt)
        if dart is not None:
            state["dart"] = dart
        # engine-level callback state (early_stopping closure): the
        # engine installs a provider returning a JSON-able dict; GBDT
        # API users without one simply skip the section
        provider = getattr(gbdt, "_engine_state_provider", None)
        if provider is not None:
            try:
                engine_state = provider()
            except Exception as e:  # noqa: BLE001 — a state provider
                #                     bug must not void the checkpoint
                log.warning("checkpoint: engine state provider failed "
                            "(%r); callback state not captured" % (e,))
                engine_state = None
            if engine_state:
                state["engine"] = engine_state
        model_text = gbdt.save_model_to_string()
        # deliberate host serialization point: the score bits leave
        # the device exactly once per checkpoint interval, never per
        # iteration (the transfer-guard test pins the iteration clean)
        score = np.asarray(gbdt.train_score, dtype=np.float32)
        # data/model-quality baseline (obs/quality.py): when the
        # booster carries a training-grid reference profile, stamp the
        # prediction-score histogram from the same train_score read and
        # persist the whole profile next to the required files (extra,
        # optional — REQUIRED_FILES is unchanged, old checkpoints load)
        profile_json = None
        profile = getattr(gbdt, "quality_profile", None)
        if profile is not None:
            try:
                profile.attach_scores(
                    score, objective=getattr(gbdt, "objective", None))
                profile_json = json.dumps(profile.to_dict()).encode()
            except Exception as e:  # noqa: BLE001 — a profile bug must
                #                     not void the checkpoint
                log.warning("checkpoint: quality profile not captured "
                            "(%r)" % (e,))

        tmp = os.path.join(directory, "%s%08d-%d"
                           % (TMP_PREFIX, gbdt.iter, os.getpid()))

        def _write_and_finalize() -> None:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            _write_file(tmp, "model.txt", model_text.encode())
            np.save(os.path.join(tmp, "score.npy"), score)
            _fsync_file(os.path.join(tmp, "score.npy"))
            if bag is not None:
                np.save(os.path.join(tmp, "bag.npy"), bag)
                _fsync_file(os.path.join(tmp, "bag.npy"))
            if profile_json is not None:
                _write_file(tmp, "quality_profile.json", profile_json)
            _write_file(tmp, "state.json",
                        json.dumps(state, indent=1).encode())
            files = {}
            for name in sorted(os.listdir(tmp)):
                fp = os.path.join(tmp, name)
                files[name] = {"sha256": _sha256_file(fp),
                               "bytes": os.path.getsize(fp)}
            manifest = {"format_version": FORMAT_VERSION,
                        "iter": int(gbdt.iter),
                        "created": round(time.time(), 3),
                        "files": files}
            _write_file(tmp, "manifest.json",
                        json.dumps(manifest, indent=1).encode())
            fsync_dir(tmp)
            faults.check("checkpoint_finalize", path=final)
            os.rename(tmp, final)
            fsync_dir(directory)

        try:
            retry_call(_write_and_finalize, site="checkpoint_finalize")
        except OSError as e:
            shutil.rmtree(tmp, ignore_errors=True)
            # log.fatal flushes the event buffer + trace spool: the
            # failure evidence lands before the raise
            log.fatal("checkpoint at iteration %d could not be "
                      "finalized under %s: %r"
                      % (gbdt.iter, directory, e))
    obs.inc("ft/checkpoints_saved")
    obs_events.emit("checkpoint_saved", iter=gbdt.iter, path=final,
                    trees=len(gbdt.models))
    obs_events.flush()
    _prune(directory, keep)
    return final


def _write_file(dirpath: str, name: str, data: bytes) -> None:
    fp = os.path.join(dirpath, name)
    with open(fp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def _fsync_file(fp: str) -> None:
    fd = os.open(fp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _prune(directory: str, keep: Optional[int]) -> None:
    """Drop all but the newest ``keep`` checkpoints
    (``LIGHTGBM_TPU_CKPT_KEEP``, default 3; 0 keeps everything) plus
    any stale temp directories from dead runs."""
    if keep is None:
        try:
            keep = int(os.environ.get(_ENV_KEEP, 3))
        except ValueError:
            keep = 3
    try:
        for name in os.listdir(directory):
            if name.startswith(TMP_PREFIX):
                p = os.path.join(directory, name)
                try:
                    pid = int(name.rsplit("-", 1)[-1])
                except ValueError:
                    pid = -1
                if pid != os.getpid():
                    shutil.rmtree(p, ignore_errors=True)
    except OSError:
        pass
    if keep <= 0:
        return
    for _, path in list_checkpoints(directory)[keep:]:
        shutil.rmtree(path, ignore_errors=True)


# ----------------------------------------------------------------------
# load / resume
# ----------------------------------------------------------------------

def _parse_model_trees(s: str) -> list:
    """Tree blocks out of a v3 model text via the SHARED framing
    parser (models/tree.py parse_tree_blocks — the same code
    ``GBDT.load_model_from_string`` runs, minus the header handling
    that would clobber a live training booster's objective/metadata)."""
    from ..models.tree import parse_tree_blocks
    return parse_tree_blocks(s)


def _restore_strategy(gbdt, state: dict, path: str) -> None:
    """Type check only — the draws themselves are stateless (fold_in on
    the iteration index), so the resumed run's first ``bagging`` call at
    iteration *i* recomputes the exact indicator the uninterrupted run
    was using (including mid-``bagging_freq``-window resumes)."""
    from ..boosting.sample_strategy import BaggingStrategy, GOSSStrategy
    spec = state.get("strategy", {"type": "none"})
    st = getattr(gbdt, "sample_strategy", None)
    kind = spec.get("type", "none")
    if kind == "bagging" and not isinstance(st, BaggingStrategy):
        log.fatal("checkpoint %s was written by a bagging run but "
                  "the resuming config has no bagging" % path)
    if kind == "goss" and not isinstance(st, GOSSStrategy):
        log.fatal("checkpoint %s was written by a GOSS run but the "
                  "resuming config has no GOSS" % path)


def _restore_learner(gbdt, state: dict) -> None:
    learner = getattr(gbdt, "learner", None)
    spec = state.get("learner", {})
    if learner is None or not spec:
        return
    learner._tree_idx = int(spec.get("tree_idx", 0))
    if "ff_rng" in spec and getattr(learner, "_ff_rng", None) is not None:
        learner._ff_rng.set_state(_np_rng_from_json(spec["ff_rng"]))
    if "quant_ctr" in spec and getattr(learner, "_quantized", False):
        from ..utils.scalars import dev_u32
        n = int(spec["quant_ctr"])
        learner._quant_ctr_host = n
        # the device-side fold-in counter continues the sequence
        # exactly: tree n+1's stochastic-rounding key is fold_in(base,
        # n+1) in both the interrupted and uninterrupted timelines
        learner._quant_ctr = dev_u32(n)


def _restore_dart(gbdt, state: dict) -> None:
    spec = state.get("dart")
    if spec is None:
        return
    from ..boosting.dart import DART
    if not isinstance(gbdt, DART):
        log.fatal("checkpoint carries DART state but the resuming "
                  "booster is %s" % type(gbdt).__name__)
    gbdt.drop_rng.set_state(_np_rng_from_json(spec["drop_rng"]))
    gbdt.tree_weight = [float(w) for w in spec["tree_weight"]]
    gbdt.sum_weight = float(spec["sum_weight"])


def _restore_objective(gbdt, state: dict) -> None:
    spec = state.get("objective", {})
    obj = getattr(gbdt, "objective", None)
    if obj is not None and spec.get("key") is not None \
            and hasattr(obj, "_key"):
        obj._key = _key_from_json(spec["key"])


def restore_early_stop(gbdt, state: dict) -> None:
    """Re-apply the per-(valid set, metric) early-stop trackers; a
    no-op (with a warning) when the resumed run registered a different
    number of valid sets."""
    es = state.get("early_stop", {})
    best_score = es.get("best_score", [])
    if len(best_score) != len(gbdt._best_score):
        if best_score:
            log.warning("checkpoint early-stop state covers %d valid "
                        "sets, run has %d; early-stop counters start "
                        "fresh" % (len(best_score),
                                   len(gbdt._best_score)))
        return
    gbdt._best_score = [list(v) for v in best_score]
    gbdt._best_iter = [list(v) for v in es.get("best_iter", [])]
    gbdt._best_msg = [list(v) for v in es.get("best_msg", [])]


def load_latest(gbdt, directory: str) -> Optional[dict]:
    """Restore ``gbdt`` from the newest VALID checkpoint under
    ``directory``; returns the state dict (or None when no valid
    checkpoint exists — the caller trains from scratch). Invalid
    candidates are skipped loudly, newest-first."""
    import jax.numpy as jnp
    for it, path in list_checkpoints(directory):
        try:
            validate_dir(path)
        except CheckpointError as e:
            obs.inc("ft/checkpoints_rejected")
            obs_events.emit("checkpoint_invalid", path=path,
                            reason=str(e))
            obs_events.flush()
            log.warning_always("skipping corrupt checkpoint %s: %s"
                               % (path, e))
            continue
        with obs.scope("ft::checkpoint_load"):
            with open(os.path.join(path, "state.json")) as f:
                state = json.load(f)
            if int(state.get("format_version", -1)) != FORMAT_VERSION:
                log.warning_always(
                    "skipping checkpoint %s: format version %s (this "
                    "build reads %d)" % (path,
                                         state.get("format_version"),
                                         FORMAT_VERSION))
                continue
            fp = state.get("data_fingerprint", {})
            if (int(fp.get("num_data", -1)) != gbdt.train_data.num_data
                    or int(fp.get("num_features", -1))
                    != gbdt.train_data.num_features):
                log.fatal("checkpoint %s was written against a "
                          "different dataset (%s rows x %s features; "
                          "this run has %d x %d)"
                          % (path, fp.get("num_data"),
                             fp.get("num_features"),
                             gbdt.train_data.num_data,
                             gbdt.train_data.num_features))
            if state.get("config_fingerprint") \
                    != _config_fingerprint(gbdt):
                log.warning("resuming %s under a different parameter "
                            "set; resumed results are only guaranteed "
                            "bit-identical under the original "
                            "parameters" % path)
            if state.get("boosting") != type(gbdt).__name__:
                log.fatal("checkpoint %s was written by a %s booster, "
                          "resuming as %s" % (path, state.get(
                              "boosting"), type(gbdt).__name__))

            with open(os.path.join(path, "model.txt")) as f:
                model_text = f.read()
            gbdt.models = _parse_model_trees(model_text)
            if len(gbdt.models) != int(state.get("num_models", -1)):
                log.fatal("checkpoint %s: parsed %d trees, state "
                          "records %d" % (path, len(gbdt.models),
                                          state.get("num_models")))
            gbdt.align_trees_to_dataset(gbdt.train_data)
            gbdt.iter = int(state["iter"])
            gbdt.num_init_iteration = int(state["num_init_iteration"])
            gbdt.best_iteration = int(state["best_iteration"])
            gbdt._has_init_score = bool(state.get("has_init_score",
                                                  False))

            score = np.load(os.path.join(path, "score.npy"))
            K = gbdt.num_tree_per_iteration
            if score.shape != (gbdt.train_data.num_data, K):
                log.fatal("checkpoint %s: score shape %s does not "
                          "match [%d, %d]" % (path, score.shape,
                                              gbdt.train_data.num_data,
                                              K))
            gbdt.train_score = jnp.asarray(score)
            gbdt._train_bins_dev = None

            # reload the quality baseline when the checkpoint carries
            # one (optional file; pre-quality-plane checkpoints simply
            # resume without a drift reference)
            qp_path = os.path.join(path, "quality_profile.json")
            if os.path.exists(qp_path):
                from ..obs import quality as obs_quality
                try:
                    gbdt.quality_profile = \
                        obs_quality.ReferenceProfile.load(qp_path)
                except (OSError, KeyError, TypeError, ValueError) as e:
                    log.warning_always("checkpoint %s: unreadable "
                                       "quality_profile.json (%r); "
                                       "resuming without a drift "
                                       "baseline" % (path, e))

            _restore_strategy(gbdt, state, path)
            _restore_learner(gbdt, state)
            _restore_dart(gbdt, state)
            _restore_objective(gbdt, state)
            restore_early_stop(gbdt, state)

            # replay any valid sets that were registered BEFORE the
            # load (the engine loads first, then registers — but the
            # GBDT-level API must work in either order)
            for vd in gbdt.valid_data:
                for i, tree in enumerate(gbdt.models):
                    vd.add_tree(tree, i % K, gbdt._bin_meta)

            # score verification: re-derive the training scores from
            # the restored trees via the existing device replay and
            # compare against the stored bits — a checkpoint whose
            # score and trees disagree (corruption that preserved the
            # hashes, or a subtly different dataset) must not resume.
            # Sharded datasets have no resident bin matrix to replay
            # over, so the replay is honestly SKIPPED there (the event
            # says so; the manifest hashes remain the integrity check)
            can_replay = hasattr(gbdt.train_data, "bins")
            diff = 0.0
            if can_replay:
                diff = gbdt.recheck_scores(reason="checkpoint_resume")
                scale = max(float(np.max(np.abs(score))), 1.0)
                if diff > 1e-3 * scale:
                    log.fatal("checkpoint %s: stored training scores "
                              "deviate from the device replay of its "
                              "own trees by %.3g — refusing to resume"
                              % (path, diff))
        obs.inc("ft/checkpoints_resumed")
        obs_events.emit("checkpoint_resumed", path=path, iter=gbdt.iter,
                        trees=len(gbdt.models),
                        score_replay=("ok" if can_replay
                                      else "skipped_sharded"),
                        score_replay_max_abs_diff=round(float(diff), 9))
        obs_events.flush()
        log.info("resumed from checkpoint %s (iteration %d, %d trees)"
                 % (path, gbdt.iter, len(gbdt.models)))
        return state
    return None
