"""Training callbacks.

API-shaped after the reference's python-package/lightgbm/callback.py:
``CallbackEnv`` namedtuple, ``log_evaluation`` (:81),
``record_evaluation`` (:147), ``reset_parameter`` (:211),
``early_stopping`` (:375, with min_delta support).
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Union

from .utils import log

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    """reference: callback.py EarlyStopException."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return "%s's %s: %g" % (value[0], value[1], value[2])
    # cv case: (name, metric, mean, is_higher, stdv)
    if show_stdv:
        return "%s's %s: %g + %g" % (value[0], value[1], value[2], value[4])
    return "%s's %s: %g" % (value[0], value[1], value[2])


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """reference: callback.py:81."""

    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv)
                for x in env.evaluation_result_list)
            log.info("[%d]\t%s" % (env.iteration + 1, result))

    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]
                      ) -> Callable:
    """reference: callback.py:147."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for item in env.evaluation_result_list:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result[data_name][eval_name].append(result)

    _callback.order = 20
    return _callback


def reset_parameter(**kwargs: Union[list, Callable]) -> Callable:
    """reference: callback.py:211 — per-iteration parameter schedules
    (list indexed by iteration or callable of iteration)."""

    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        "Length of list %r should equal to 'num_boost_round'."
                        % key)
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_param = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are "
                                 "supported as a mapping from boosting round "
                                 "index to new parameter value.")
            new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)

    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True,
                   min_delta: Union[float, List[float]] = 0.0) -> Callable:
    """reference: callback.py:375 — stop when no eval metric improves
    (by at least ``min_delta``) in ``stopping_rounds`` rounds.

    The returned callback is checkpointable: ``get_state()`` /
    ``set_state()`` expose the closure's best score/iteration trackers
    (the patience counter is implicit — patience is measured against
    the absolute ``best_iter``), so a resumed run (ft/checkpoint.py via
    ``lgb.train(resume=True)``) continues the SAME patience window
    instead of re-arming it from the resume point. ``set_state`` is
    applied lazily after the first-callback ``_init`` — the comparison
    ops and metric layout still come from the live evaluation list."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    enabled = [True]
    first_metric = [""]
    pending_state: List[Optional[dict]] = [None]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if verbose:
            log.info("Training until validation scores don't improve for "
                     "%d rounds" % stopping_rounds)

        n_metrics = len({m[1] for m in env.evaluation_result_list})
        n_datasets = len(env.evaluation_result_list) // max(n_metrics, 1)
        if isinstance(min_delta, list):
            if not all(t >= 0 for t in min_delta):
                raise ValueError(
                    "Values for early stopping min_delta must be "
                    "non-negative.")
            if len(min_delta) == 0:
                deltas = [0.0] * n_datasets * n_metrics
            elif len(min_delta) == 1:
                deltas = min_delta * n_datasets * n_metrics
            else:
                if len(min_delta) != n_metrics:
                    raise ValueError(
                        "Must provide a single value for min_delta or as "
                        "many as metrics.")
                if first_metric_only and verbose:
                    log.info("Using only %s for early stopping"
                             % str(min_delta[0]))
                deltas = min_delta * n_datasets
        else:
            if min_delta < 0:
                raise ValueError(
                    "Early stopping min_delta must be non-negative.")
            if min_delta > 0 and n_metrics > 1 and not first_metric_only \
                    and verbose:
                log.info("Using %s as min_delta for all metrics."
                         % str(min_delta))
            deltas = [min_delta] * n_datasets * n_metrics

        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        for eval_ret, delta in zip(env.evaluation_result_list, deltas):
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:  # is_higher_better
                best_score.append(float("-inf"))
                cmp_op.append(
                    lambda cur, best, d=delta: cur > best + d)
            else:
                best_score.append(float("inf"))
                cmp_op.append(
                    lambda cur, best, d=delta: cur < best - d)

    def _final_iteration_check(env, eval_name_splitted, i) -> None:
        if env.iteration == env.end_iteration - 1:
            if verbose:
                log.info("Did not meet early stopping. Best iteration is:"
                         "\n[%d]\t%s" % (
                             best_iter[i] + 1, "\t".join(
                                 _format_eval_result(x)
                                 for x in best_score_list[i])))
                if first_metric_only:
                    log.info("Evaluated only: %s" % eval_name_splitted[-1])
            raise EarlyStopException(best_iter[i], best_score_list[i])

    def _apply_pending_state() -> None:
        s = pending_state[0]
        pending_state[0] = None
        if s is None:
            return
        if len(s.get("best_score", [])) != len(best_score):
            log.warning("checkpointed early-stopping state covers %d "
                        "metrics, run evaluates %d; patience re-arms "
                        "from the resume point"
                        % (len(s.get("best_score", [])), len(best_score)))
            return
        best_score[:] = [float(v) for v in s["best_score"]]
        best_iter[:] = [int(v) for v in s["best_iter"]]
        best_score_list[:] = [
            None if lst is None else [tuple(item) for item in lst]
            for lst in s["best_score_list"]]

    def _get_state() -> Optional[dict]:
        if not best_score:
            return None  # never initialized: nothing to carry over
        return {"best_score": [float(v) for v in best_score],
                "best_iter": [int(v) for v in best_iter],
                "best_score_list": [
                    None if lst is None else [list(item) for item in lst]
                    for lst in best_score_list]}

    def _set_state(state: Optional[dict]) -> None:
        pending_state[0] = state

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
            _apply_pending_state()
        if not enabled[0]:
            return
        for i in range(len(env.evaluation_result_list)):
            score = env.evaluation_result_list[i][2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            eval_name_splitted = \
                env.evaluation_result_list[i][1].split(" ")
            if first_metric_only and first_metric[0] != \
                    eval_name_splitted[-1]:
                continue
            if env.evaluation_result_list[i][0] == "training":
                _final_iteration_check(env, eval_name_splitted, i)
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    log.info("Early stopping, best iteration is:\n[%d]\t%s"
                             % (best_iter[i] + 1, "\t".join(
                                 _format_eval_result(x)
                                 for x in best_score_list[i])))
                    if first_metric_only:
                        log.info("Evaluated only: %s"
                                 % eval_name_splitted[-1])
                raise EarlyStopException(best_iter[i], best_score_list[i])
            _final_iteration_check(env, eval_name_splitted, i)

    _callback.order = 30
    _callback.get_state = _get_state
    _callback.set_state = _set_state
    return _callback
