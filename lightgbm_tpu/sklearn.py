"""scikit-learn estimator wrappers.

API-shaped after the reference's python-package/lightgbm/sklearn.py
(``LGBMModel`` :364, ``LGBMRegressor`` :989, ``LGBMClassifier`` :1035,
``LGBMRanker`` :1212).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .engine import train as _train
from .utils import log

# Inherit scikit-learn's bases when available so the estimators carry
# proper tags/clone semantics and pass sklearn's conformance machinery
# (reference: python-package/lightgbm/compat.py _LGBMModelBase — the
# reference's estimators do exactly this behind a compat shim).
try:  # pragma: no cover - import guard
    from sklearn.base import (BaseEstimator as _LGBMModelBase,
                              ClassifierMixin as _LGBMClassifierBase,
                              RegressorMixin as _LGBMRegressorBase)
except ImportError:  # pragma: no cover
    class _LGBMModelBase:  # type: ignore
        pass

    class _LGBMClassifierBase:  # type: ignore
        pass

    class _LGBMRegressorBase:  # type: ignore
        pass


class LGBMModel(_LGBMModelBase):
    """Base estimator (reference: sklearn.py:364)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight=None, min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3, min_child_samples: int = 20,
                 subsample: float = 1.0, subsample_freq: int = 0,
                 colsample_bytree: float = 1.0, reg_alpha: float = 0.0,
                 reg_lambda: float = 0.0, random_state=None,
                 n_jobs: int = -1, importance_type: str = "split",
                 **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        # sklearn contract: __init__ sets ONLY parameters; fitted state
        # appears in fit() (check_no_attributes_set_in_init)
        self._other_params = dict(kwargs)

    # underscore-prefixed state created lazily (not in __init__)
    _Booster: Optional[Booster] = None
    _evals_result: Optional[Dict] = None
    _best_iteration = -1

    def __sklearn_tags__(self):
        """reference: sklearn.py LGBMModel._more_tags — NaN is a
        first-class missing value and scipy sparse inputs are accepted
        (binned via the sparse-until-binning path)."""
        tags = super().__sklearn_tags__()
        tags.input_tags.allow_nan = True
        tags.input_tags.sparse = True
        tags.non_deterministic = False
        return tags

    # ------------------------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves, "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective,
            "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    # ------------------------------------------------------------------
    def _default_objective(self) -> str:
        return "regression"

    def _process_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        # fit-time overrides (e.g. multiclass promotion) live outside the
        # constructor params so fit never mutates them
        # (check_estimators_overwrite_params)
        override = dict(getattr(self, "_fit_params_override", {}) or {})
        objective = params.pop("objective", None)
        ov_obj = override.pop("objective", None)
        if ov_obj is not None:
            # fit-time promotion (e.g. >2 classes -> multiclass) WINS
            # over the constructor objective, matching the pre-override
            # behavior of forcing multiclass
            objective = ov_obj
        elif objective is None:
            objective = self._default_objective()
        params["objective"] = objective
        params.update(override)
        params["boosting"] = params.pop("boosting_type", "gbdt")
        if params.get("random_state") is None:
            params.pop("random_state", None)
        else:
            params["seed"] = params.pop("random_state")
        params.pop("n_jobs", None)
        params.pop("silent", None)
        params.setdefault("verbosity", -1)
        return params

    @staticmethod
    def _validate_fit_input(X, y, sample_weight=None):
        """Input sanity errors sklearn's conformance machinery expects
        (ValueError on empty / complex / NaN-y / mismatched data)."""
        if y is None:
            raise ValueError(
                "This estimator requires y to be passed, but the "
                "target y is None")
        shape = getattr(X, "shape", None)
        if shape is None:
            X = np.asarray(X)
            shape = X.shape
        if len(shape) != 2:
            raise ValueError(
                "Expected 2D array, got array with shape %s instead"
                % (tuple(shape),))
        if shape[1] == 0:
            raise ValueError(
                "0 feature(s) (shape=(%d, 0)) while a minimum of 1 is "
                "required." % shape[0])
        if shape[0] == 0:
            raise ValueError(
                "0 sample(s) (shape=(0, %d)) while a minimum of 1 is "
                "required." % shape[1])
        if shape[0] == 1:
            raise ValueError(
                "Cannot fit a GBDT on 1 sample; at least 2 samples are "
                "required")
        if np.iscomplexobj(X) or np.iscomplexobj(np.asarray(y)):
            raise ValueError("Complex data not supported")
        y_arr = np.asarray(y)
        if y_arr.dtype.kind not in ("U", "S", "O", "b"):
            # numeric targets must be finite (string/object labels are
            # the classifier's to encode)
            y_num = y_arr.astype(np.float64)
            if not np.all(np.isfinite(y_num)):
                raise ValueError(
                    "Input y contains NaN, infinity or a value too "
                    "large")
        if y_arr.shape[0] != shape[0]:
            raise ValueError(
                "Found input variables with inconsistent numbers of "
                "samples: [%d, %d]" % (shape[0], y_arr.shape[0]))
        if sample_weight is not None:
            w = np.asarray(sample_weight)
            if w.ndim != 1 or w.shape[0] != shape[0]:
                raise ValueError(
                    "sample_weight.shape == %s, expected (%d,)"
                    % (w.shape, shape[0]))
            if w.shape[0] > 0 and not np.any(w > 0):
                raise ValueError(
                    "No training samples: all sample_weight values are "
                    "zero or negative")

    @staticmethod
    def _ensure_1d_y(y):
        """Column-vector y → 1-D with sklearn's conversion warning
        (check_supervised_y_2d contract)."""
        if y is None:
            return None  # the validator raises the requires-y error
        y = np.asarray(y)
        if y.ndim == 2 and y.shape[1] == 1:
            import warnings
            try:
                from sklearn.exceptions import DataConversionWarning
            except ImportError:  # pragma: no cover
                DataConversionWarning = UserWarning
            warnings.warn(
                "A column-vector y was passed when a 1d array was "
                "expected. Please change the shape of y to "
                "(n_samples,), for example using ravel().",
                DataConversionWarning)
            y = y.ravel()
        return y

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            feature_name="auto", categorical_feature="auto",
            callbacks=None) -> "LGBMModel":
        y = self._ensure_1d_y(y)
        self._validate_fit_input(X, y, sample_weight)
        params = self._process_params()
        feval = None
        if eval_metric is not None:
            metrics = (eval_metric if isinstance(eval_metric, (list, tuple))
                       else [eval_metric])
            str_metrics = [m for m in metrics if isinstance(m, str)]
            fn_metrics = [m for m in metrics if callable(m)]
            if str_metrics:
                params["metric"] = str_metrics
            if fn_metrics:
                # sklearn-style callables take (y_true, y_pred)
                # (reference: sklearn.py _EvalFunctionWrapper); adapt to
                # the engine's feval(preds, eval_data) contract. For
                # built-in objectives the reference hands the callable
                # TRANSFORMED predictions (probabilities), raw margins
                # only under a custom objective — mirror that.
                obj = params.get("objective", "")
                transform = None
                if obj and not callable(obj):
                    # use the objective's OWN output transform — the
                    # same one predict()/predict_proba apply — so the
                    # callable sees the model's real predictions
                    # (per-class sigmoid for multiclassova, configured
                    # sigmoid for binary, exp for poisson-family, ...)
                    from .config import Config as _Cfg
                    from .objective import create_objective
                    try:
                        _o = create_objective(str(obj),
                                              _Cfg.from_params(params))
                        transform = _o.convert_output
                    except Exception:
                        transform = None

                def _wrap(fn):
                    def feval_fn(preds, ds):
                        p = transform(preds) if transform is not None \
                            else preds
                        return fn(ds.get_label(), p)
                    return feval_fn
                feval = [_wrap(f) for f in fn_metrics]
        if self.class_weight is not None:
            sample_weight = _apply_class_weight(
                self.class_weight, y, sample_weight)
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = (eval_sample_weight[i]
                      if eval_sample_weight else None)
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                valid_sets.append(Dataset(
                    vx, label=vy, weight=vw, group=vg, init_score=vi,
                    reference=train_set, params=params))
        self._evals_result = {}
        callbacks = list(callbacks or [])
        if valid_sets:
            callbacks.append(
                callback_mod.record_evaluation(self._evals_result))
        self._Booster = _train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=eval_names,
            feval=feval, callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._n_features = train_set.num_feature()
        self.n_features_in_ = self._n_features
        self.fitted_ = True
        return self

    # ------------------------------------------------------------------
    def predict(self, X, raw_score: bool = False,
                start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                pred_leaf: bool = False, pred_contrib: bool = False,
                **kwargs):
        self._check_fitted()
        self._check_n_features(X)
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)

    def _check_fitted(self):
        if not getattr(self, "fitted_", False):
            try:
                from sklearn.exceptions import NotFittedError
            except ImportError:
                NotFittedError = ValueError
            raise NotFittedError(
                "Estimator not fitted, call fit before exploiting the "
                "model.")

    def _check_n_features(self, X):
        shape = getattr(X, "shape", None)
        if shape is None:
            shape = np.asarray(X).shape
        if len(shape) == 1:
            raise ValueError(
                "Expected 2D array, got 1D array instead. Reshape your "
                "data either using array.reshape(-1, 1) or "
                "array.reshape(1, -1).")
        n_in = getattr(self, "n_features_in_", None)
        if len(shape) == 2 and n_in is not None and shape[1] != n_in:
            raise ValueError(
                "X has %d features, but %s is expecting %d features as "
                "input" % (shape[1], type(self).__name__, n_in))

    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._best_iteration

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()


class LGBMRegressor(_LGBMRegressorBase, LGBMModel):
    """reference: sklearn.py:989."""

    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(_LGBMClassifierBase, LGBMModel):
    """reference: sklearn.py:1035."""

    def _default_objective(self) -> str:
        return "binary"

    def fit(self, X, y, **kwargs):
        y = self._ensure_1d_y(y)
        self._validate_fit_input(X, y)
        y = np.asarray(y)
        if y.dtype.kind == "f" and not np.all(y == np.floor(y)):
            raise ValueError(
                "Unknown label type: continuous. Classification targets "
                "must be discrete")
        if y.dtype.kind == "O":
            # normalize MIXED-type object labels to strings so np.unique
            # + searchsorted order deterministically; homogeneous object
            # arrays (e.g. pandas int columns) keep their label type
            if len({type(v) for v in y}) > 1:
                y = y.astype(str)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        self._fit_params_override = {}
        if self._n_classes > 2:
            # promote string objectives to multiclass; custom callable
            # objectives keep supplying their own gradients
            if self.objective is None or (
                    isinstance(self.objective, str)
                    and self.objective not in ("multiclass",
                                               "multiclassova")):
                self._fit_params_override["objective"] = "multiclass"
            self._fit_params_override["num_class"] = self._n_classes
        y_enc = np.searchsorted(self._classes, y).astype(np.float64)
        super().fit(X, y_enc, **kwargs)
        return self

    def _default_objective_multiclass(self):
        return "multiclass"

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim > 1:
            idx = np.argmax(result, axis=1)
        else:
            idx = (result > 0.5).astype(int)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      **kwargs):
        self._check_fitted()
        self._check_n_features(X)
        result = self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes


class LGBMRanker(LGBMModel):
    """reference: sklearn.py:1212."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        return super().fit(X, y, group=group, **kwargs)


def _apply_class_weight(class_weight, y, sample_weight):
    y = np.asarray(y)
    if class_weight == "balanced":
        classes, counts = np.unique(y, return_counts=True)
        weight_map = {c: len(y) / (len(classes) * cnt)
                      for c, cnt in zip(classes, counts)}
    elif isinstance(class_weight, dict):
        weight_map = class_weight
    else:
        raise ValueError("class_weight must be 'balanced' or a dict")
    cw = np.array([weight_map.get(v, 1.0) for v in y])
    if sample_weight is not None:
        cw = cw * np.asarray(sample_weight)
    return cw
