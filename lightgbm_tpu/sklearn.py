"""scikit-learn estimator wrappers.

API-shaped after the reference's python-package/lightgbm/sklearn.py
(``LGBMModel`` :364, ``LGBMRegressor`` :989, ``LGBMClassifier`` :1035,
``LGBMRanker`` :1212).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .engine import train as _train
from .utils import log


class LGBMModel:
    """Base estimator (reference: sklearn.py:364)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight=None, min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3, min_child_samples: int = 20,
                 subsample: float = 1.0, subsample_freq: int = 0,
                 colsample_bytree: float = 1.0, reg_alpha: float = 0.0,
                 reg_lambda: float = 0.0, random_state=None,
                 n_jobs: int = -1, importance_type: str = "split",
                 **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self.fitted_ = False

    # ------------------------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves, "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective,
            "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    # ------------------------------------------------------------------
    def _default_objective(self) -> str:
        return "regression"

    def _process_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        objective = params.pop("objective", None)
        if objective is None:
            objective = self._default_objective()
        params["objective"] = objective
        params["boosting"] = params.pop("boosting_type", "gbdt")
        if params.get("random_state") is None:
            params.pop("random_state", None)
        else:
            params["seed"] = params.pop("random_state")
        params.pop("n_jobs", None)
        params.pop("silent", None)
        params.setdefault("verbosity", -1)
        return params

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            feature_name="auto", categorical_feature="auto",
            callbacks=None) -> "LGBMModel":
        params = self._process_params()
        if eval_metric is not None:
            params["metric"] = eval_metric
        if self.class_weight is not None:
            sample_weight = _apply_class_weight(
                self.class_weight, y, sample_weight)
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = (eval_sample_weight[i]
                      if eval_sample_weight else None)
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                valid_sets.append(Dataset(
                    vx, label=vy, weight=vw, group=vg, init_score=vi,
                    reference=train_set, params=params))
        self._evals_result = {}
        callbacks = list(callbacks or [])
        if valid_sets:
            callbacks.append(
                callback_mod.record_evaluation(self._evals_result))
        self._Booster = _train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=eval_names,
            callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._n_features = train_set.num_feature()
        self.fitted_ = True
        return self

    # ------------------------------------------------------------------
    def predict(self, X, raw_score: bool = False,
                start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                pred_leaf: bool = False, pred_contrib: bool = False,
                **kwargs):
        self._check_fitted()
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)

    def _check_fitted(self):
        if not self.fitted_:
            raise ValueError(
                "Estimator not fitted, call fit before exploiting the model.")

    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._best_iteration

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()


class LGBMRegressor(LGBMModel):
    """reference: sklearn.py:989."""

    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(LGBMModel):
    """reference: sklearn.py:1035."""

    def _default_objective(self) -> str:
        return "binary"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            if not isinstance(self.objective, str) or \
                    self.objective not in ("multiclass", "multiclassova"):
                self.objective = "multiclass"
            self._other_params["num_class"] = self._n_classes
        y_enc = np.searchsorted(self._classes, y).astype(np.float64)
        super().fit(X, y_enc, **kwargs)
        return self

    def _default_objective_multiclass(self):
        return "multiclass"

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim > 1:
            idx = np.argmax(result, axis=1)
        else:
            idx = (result > 0.5).astype(int)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      **kwargs):
        self._check_fitted()
        result = self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes


class LGBMRanker(LGBMModel):
    """reference: sklearn.py:1212."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        return super().fit(X, y, group=group, **kwargs)


def _apply_class_weight(class_weight, y, sample_weight):
    y = np.asarray(y)
    if class_weight == "balanced":
        classes, counts = np.unique(y, return_counts=True)
        weight_map = {c: len(y) / (len(classes) * cnt)
                      for c, cnt in zip(classes, counts)}
    elif isinstance(class_weight, dict):
        weight_map = class_weight
    else:
        raise ValueError("class_weight must be 'balanced' or a dict")
    cw = np.array([weight_map.get(v, 1.0) for v in y])
    if sample_weight is not None:
        cw = cw * np.asarray(sample_weight)
    return cw
