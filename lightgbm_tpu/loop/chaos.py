"""Unified chaos schedule for the closed refresh loop.

The PR 9 fault plane (obs/faults.py) exposes ten injectable sites; the
training-side benches exercised seven of them, the serving plane added
``serve_admit`` / ``serve_dispatch`` / ``gateway_push``. This module is
the ONE place that knows which sites belong to which phase of a refresh
cycle, so the refresh harness (loop/controller.py), ``bench.py chaos``
and ``bench.py refresh`` all drive the same deterministic schedule
instead of each hand-rolling spec strings.

A schedule maps cycle index → a list of :class:`ChaosLeg` entries. Each
leg names the fault spec, the phase it must be armed for (``train``
fires around the attach+resume training step, ``publish`` around the
canary window, ``telemetry`` around the gateway push), and whether the
cycle is expected to END in a rollback (a *poisoned* refresh: the
canary must fail closed while the previous version keeps serving).
"""
from __future__ import annotations

from typing import Dict, List, NamedTuple

from ..obs import faults

# The training-side sites the PR 9 chaos bench exercises.
TRAIN_SITES = ("shard_open", "prefetch_device_put", "spill_write",
               "trace_finalize", "metrics_dump", "registry_swap",
               "checkpoint_finalize")
# The serving-side sites the refresh loop adds to the shared schedule.
SERVE_SITES = ("serve_admit", "serve_dispatch", "gateway_push")


class ChaosLeg(NamedTuple):
    spec: str        # faults.configure() spec, e.g. "serve_dispatch:nth:1"
    phase: str       # "train" | "publish" | "telemetry"
    poison: bool     # True → this cycle's canary MUST roll back


def refresh_schedule(cycles: int) -> Dict[int, List[ChaosLeg]]:
    """The deterministic per-cycle schedule the refresh harness runs.

    Cycle 0 (bootstrap train + first publish) is always clean — it is
    the baseline every later cycle's model and SLO numbers are compared
    against. Refresh cycles then rotate through three legs:

    1. a RETRYABLE train-side fault (``prefetch_device_put``): the
       attach+resume training step absorbs it via the bounded-retry
       plane and the cycle promotes normally;
    2. a POISONED publish (``serve_dispatch`` on the first canary
       batch): the canary window fails closed, the registry rolls back,
       and live traffic keeps being answered by the previous version;
    3. a TELEMETRY fault (``gateway_push``): the snapshot push is
       retried/skipped — a lost push costs staleness, never the loop.

    With fewer than four cycles the rotation truncates (the poisoned
    leg is placed first among the refresh cycles when only one fits,
    because rollback-under-traffic is the property the loop exists to
    prove)."""
    legs = [
        ChaosLeg("serve_dispatch:nth:1", "publish", True),
        ChaosLeg("prefetch_device_put:nth:1", "train", False),
        ChaosLeg("gateway_push:nth:1", "telemetry", False),
    ]
    out: Dict[int, List[ChaosLeg]] = {}
    for cycle in range(1, cycles):
        out[cycle] = [legs[(cycle - 1) % len(legs)]]
    return out


def expected_rollbacks(schedule: Dict[int, List[ChaosLeg]]) -> int:
    return sum(1 for legs in schedule.values()
               for leg in legs if leg.poison)


def validate_schedule(schedule: Dict[int, List[ChaosLeg]]) -> None:
    """Every site in the schedule must be a real injectable site — a
    typo'd spec would silently inject nothing and the bench would
    report a fault 'survived' that never fired."""
    for legs in schedule.values():
        for leg in legs:
            for part in leg.spec.split(";"):
                site = part.split(":", 1)[0].strip()
                if site not in faults.SITES:
                    raise ValueError("unknown fault site %r in chaos "
                                     "schedule (valid: %s)"
                                     % (site, ", ".join(faults.SITES)))
