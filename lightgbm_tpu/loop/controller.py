"""Closed-loop continuous model refresh: train → publish → serve →
retrain, under live traffic, with the fault plane firing mid-loop.

The controller composes subsystems that already exist — the streaming
spill path (io/streaming.py → io/shards.py), checkpointed training
(engine.py + ft/checkpoint.py), the device refit replay
(boosting/refit.py:refit_model_device via ``Booster.refit``), and the
canary-publishing registry + micro-batching server (serve/server.py) —
into ONE loop and asserts the composition's invariants every cycle:

- the serving plane answers throughout (generated traffic never stops;
  a refresh is invisible to callers except as a version bump);
- a poisoned refresh rolls back inside its canary window while the
  previous version keeps serving (fail-closed publish);
- train-side and telemetry-side injected faults are absorbed by the
  retry/degrade machinery without losing the cycle;
- the ``refresh_slo`` watchdog rule (obs/health.py) sees zero breaches
  on a healthy loop: serve p99 under the SLO, rollbacks within budget,
  zero stranded futures at drain.

Data flows in per-cycle *windows* (``data_fn(cycle) -> (X, y[, w])``).
Cycle 0 streams window 0 through the spill path, trains the base model
with checkpoints, and publishes it into a live :class:`PredictServer`.
Every later cycle re-opens the SAME spill directory via
``ShardedBinnedDataset.attach`` (no re-binning), resumes training from
the newest checkpoint for ``extra_rounds`` more iterations, refits the
grown forest's leaf values on the cycle's fresh window entirely on
device, and canary-publishes the refreshed model under traffic.

See docs/REFRESH.md for the SLO contract and what is NOT covered.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..basic import Booster, Dataset
from ..config import Config
from ..engine import train as _train
from ..io.shards import ShardedBinnedDataset
from ..io.streaming import StreamingDataset
from ..obs import events
from ..obs import faults
from ..obs import gateway as obs_gateway
from ..obs import health as obs_health
from ..obs.registry import registry as obs_registry
from ..serve import ModelRegistry, Overloaded, PredictServer, ServeError
from ..utils import log
from . import chaos as chaos_mod


def covariate_shift(block: np.ndarray) -> np.ndarray:
    """Default mid-run covariate-shift transform for
    :class:`TrafficGenerator`: translate every feature by 2.5 of its
    own std (plus a floor for constant columns). The shape and dtype —
    and therefore the serving bucket and trace count — stay identical
    to the unshifted block; only the bin occupancy moves, which is
    exactly what the quality plane's PSI must catch."""
    s = block.std(axis=0, keepdims=True)
    return (block + 2.5 * s + 0.5).astype(block.dtype)


class TrafficGenerator:
    """Sustained synthetic serving load: ``threads`` daemon threads pump
    one block each through ``server.predict`` in a tight loop, counting
    answered rows and TYPED failures (an untyped failure is a bug).

    ``pause()``/``resume()`` quiesce the pumps without stopping the
    server — the poisoned-publish leg needs the NEXT dispatch to be the
    canary's deterministically, which live pumps can't guarantee. Each
    pump is synchronous (``predict`` blocks on its own Future), so once
    every thread reports idle there are zero generator requests in
    flight.

    ``block`` may be a single array or a LIST of equal-shape arrays (a
    pool): the pumps round-robin through the pool, so a drift window
    sees pool_size x block_rows DISTINCT rows instead of one block
    repeated — with a single small block, an identical-distribution
    window still scores PSI ~ bins/distinct_rows of pure sampling
    noise. Equal shapes keep the whole pool in one warmed serve bucket.

    ``shift_after_rows=N`` injects covariate shift mid-run: once the
    pumps have collectively answered N rows, every subsequent request
    uses ``shift_fn(block)`` (default :func:`covariate_shift`) instead
    of the original pool. The shifted blocks keep the original shape
    and dtype, so the swap is invisible to the compile cache — the only
    observable difference is the input distribution, which is the
    quality plane's job to notice."""

    def __init__(self, server: PredictServer, block,
                 threads: int = 2, timeout_s: float = 120.0,
                 shift_after_rows: Optional[int] = None,
                 shift_fn: Optional[Callable] = None) -> None:
        self.server = server
        pool = list(block) if isinstance(block, (list, tuple)) \
            else [block]
        if not pool:
            raise ValueError("need at least one traffic block")
        if any(b.shape != pool[0].shape for b in pool):
            raise ValueError("pool blocks must share one shape (one "
                             "warmed serve bucket)")
        self.pool: List[np.ndarray] = pool
        self.block = pool[0]
        self.timeout_s = float(timeout_s)
        self.n_threads = max(int(threads), 1)
        self.shift_after_rows = (None if shift_after_rows is None
                                 else int(shift_after_rows))
        self._shift_pool: Optional[List[np.ndarray]] = None
        if self.shift_after_rows is not None:
            fn = shift_fn if shift_fn is not None else covariate_shift
            self._shift_pool = []
            for b in pool:
                shifted = np.ascontiguousarray(
                    fn(np.array(b, copy=True)), dtype=b.dtype)
                if shifted.shape != b.shape:
                    raise ValueError(
                        "shift_fn changed the block shape %s -> %s; "
                        "the shifted block must reuse the warmed "
                        "bucket" % (b.shape, shifted.shape))
                self._shift_pool.append(shifted)
        self._shifted = threading.Event()
        self._stop = threading.Event()
        self._pause = threading.Event()
        self._idle = [threading.Event() for _ in range(self.n_threads)]
        self._threads: List[threading.Thread] = []
        # per-thread stats, merged at read time (no locks on the pump)
        self._stats = [{"requests": 0, "rows_ok": 0, "rows_shifted": 0,
                        "shed": 0, "typed": {}, "untyped": []}
                       for _ in range(self.n_threads)]

    def _current_block(self, t: int, seq: int):
        shifted = False
        if self._shift_pool is not None:
            if self._shifted.is_set():
                shifted = True
            else:
                # cross-thread dict reads are GIL-atomic; an
                # off-by-a-block trigger point is fine, a lock on the
                # pump path is not
                total = sum(s["rows_ok"] for s in self._stats)
                if total >= self.shift_after_rows:
                    self._shifted.set()
                    shifted = True
        pool = self._shift_pool if shifted else self.pool
        # stride the threads so N pumps don't walk the pool in lockstep
        return pool[(seq * self.n_threads + t) % len(pool)], shifted

    def _pump(self, t: int) -> None:
        st = self._stats[t]
        while not self._stop.is_set():
            if self._pause.is_set():
                self._idle[t].set()
                time.sleep(0.002)
                continue
            self._idle[t].clear()
            st["requests"] += 1
            blk, shifted = self._current_block(t, st["requests"])
            try:
                self.server.predict(blk, timeout=self.timeout_s)
                st["rows_ok"] += blk.shape[0]
                if shifted:
                    st["rows_shifted"] += blk.shape[0]
            except Overloaded:
                st["shed"] += 1
            except (ServeError, faults.InjectedFault) as e:
                name = type(e).__name__
                st["typed"][name] = st["typed"].get(name, 0) + 1
            except Exception as e:  # noqa: BLE001 — count, never die:
                # a dead pump would silently end "sustained traffic"
                if len(st["untyped"]) < 8:
                    st["untyped"].append("%s: %s" % (type(e).__name__,
                                                     str(e)[:120]))

    def start(self) -> None:
        self._threads = [threading.Thread(target=self._pump, args=(t,),
                                          daemon=True)
                         for t in range(self.n_threads)]
        for th in self._threads:
            th.start()

    def pause(self, timeout_s: float = 30.0) -> bool:
        """Quiesce every pump; True once no generator request is in
        flight (each pump parked in its poll loop)."""
        for ev in self._idle:
            ev.clear()
        self._pause.set()
        deadline = time.time() + timeout_s
        for ev in self._idle:
            if not ev.wait(timeout=max(deadline - time.time(), 0.001)):
                return False
        return True

    def resume(self) -> None:
        self._pause.clear()

    def stats(self) -> Dict:
        out = {"requests": 0, "rows_ok": 0, "rows_shifted": 0,
               "shed": 0, "typed": {}, "untyped": []}
        for st in self._stats:
            out["requests"] += st["requests"]
            out["rows_ok"] += st["rows_ok"]
            out["rows_shifted"] += st["rows_shifted"]
            out["shed"] += st["shed"]
            for k, v in st["typed"].items():
                out["typed"][k] = out["typed"].get(k, 0) + v
            out["untyped"].extend(st["untyped"])
        return out

    def stop(self) -> Dict:
        self._stop.set()
        self._pause.clear()
        for th in self._threads:
            th.join(timeout=max(self.timeout_s, 30.0))
        return self.stats()


class RefreshController:
    """Drive the closed refresh loop; see the module docstring.

    ``data_fn(cycle)`` supplies each cycle's window as ``(X, y)`` or
    ``(X, y, weight)`` host arrays. ``params`` is the ordinary
    ``lgb.train`` params dict (iteration-count aliases must stay out of
    it — the loop owns the round schedule: ``base_rounds`` at
    bootstrap, ``+ extra_rounds`` per refresh cycle, resumed from the
    newest checkpoint)."""

    def __init__(self, params: Dict, data_fn: Callable,
                 num_features: int, work_dir: str,
                 base_rounds: int = 6, extra_rounds: int = 2,
                 canary_batches: int = 2, name: str = "refresh",
                 traffic_threads: int = 2, traffic_rows: int = 64,
                 schedule: Optional[Dict[int, List[chaos_mod.ChaosLeg]]]
                 = None,
                 use_gateway: bool = True, checkpoint_freq: int = 1,
                 shard_rows: Optional[int] = None,
                 drain_timeout_s: float = 30.0,
                 canary_timeout_s: float = 60.0,
                 max_batch: int = 256, max_wait_ms: float = 2.0,
                 refresh_trigger: str = "cadence",
                 drift_max_windows: int = 4,
                 drift_window_s: float = 0.25,
                 drift_min_window_rows: int = 0,
                 traffic_pool: int = 1,
                 shift_after_rows: Optional[int] = None,
                 shift_fn: Optional[Callable] = None) -> None:
        if refresh_trigger not in ("cadence", "drift"):
            raise ValueError("refresh_trigger must be 'cadence' or "
                             "'drift', got %r" % (refresh_trigger,))
        self.params = dict(params)
        self.data_fn = data_fn
        self.num_features = int(num_features)
        self.work_dir = work_dir
        self.spill_dir = os.path.join(work_dir, "spill")
        self.ckpt_dir = os.path.join(work_dir, "ckpt")
        self.base_rounds = int(base_rounds)
        self.extra_rounds = int(extra_rounds)
        self.canary_batches = int(canary_batches)
        self.name = name
        self.traffic_threads = int(traffic_threads)
        self.traffic_rows = int(traffic_rows)
        self.schedule = schedule
        self.use_gateway = bool(use_gateway)
        self.checkpoint_freq = int(checkpoint_freq)
        self.shard_rows = shard_rows
        self.drain_timeout_s = float(drain_timeout_s)
        self.canary_timeout_s = float(canary_timeout_s)
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        # drift-gated refresh (refresh_trigger="drift"): before each
        # refresh cycle the controller drains short drift windows until
        # one breaches LIGHTGBM_TPU_WATCH_PSI, then refreshes early; a
        # clean streak of drift_max_windows falls back to cadence so a
        # refresh is never starved by a calm input stream
        self.refresh_trigger = refresh_trigger
        self.drift_max_windows = max(int(drift_max_windows), 1)
        self.drift_window_s = float(drift_window_s)
        self.drift_min_window_rows = int(drift_min_window_rows)
        # traffic_pool > 1 pumps a rotating pool of traffic_pool
        # equal-shape blocks instead of one block: a drift window then
        # holds pool*rows DISTINCT rows, keeping sampling-noise PSI
        # well under the drift threshold on an unshifted stream
        self.traffic_pool = max(int(traffic_pool), 1)
        self.shift_after_rows = shift_after_rows
        self.shift_fn = shift_fn
        self.quality = None
        self.drift_psi_max = 0.0
        self.drift_windows = 0
        self.drift_triggered = 0
        self.drift_detect_windows: Optional[int] = None
        self._warned_no_quality = False

        self.registry = ModelRegistry()
        self.server: Optional[PredictServer] = None
        self.traffic: Optional[TrafficGenerator] = None
        self.watchdog: Optional[obs_health.Watchdog] = None
        self._gateway = None
        self._pusher = None
        self._block: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    def _window(self, cycle: int):
        out = self.data_fn(cycle)
        if len(out) == 2:
            X, y = out
            w = None
        else:
            X, y, w = out
        return (np.asarray(X, dtype=np.float64),
                np.asarray(y, dtype=np.float64),
                None if w is None else np.asarray(w, dtype=np.float64))

    def _wrap(self, sharded) -> Dataset:
        # Dataset.construct() early-returns on a bound handle, so the
        # engine's checkpoint/resume machinery drives the sharded
        # dataset without ever re-binning raw data it does not have
        ds = Dataset(None)
        ds._handle = sharded
        ds.params = dict(self.params)
        return ds

    def _await_canary(self, version: int) -> str:
        deadline = time.time() + self.canary_timeout_s
        while (self.registry.canary_active(self.name)
               and time.time() < deadline):
            time.sleep(0.01)
        if self.registry.canary_active(self.name):
            return "stuck"
        return ("promoted"
                if self.registry.get(self.name)[0] == version
                else "rolled_back")

    # ------------------------------------------------------------------
    def _bootstrap(self) -> Dict:
        t0 = time.perf_counter()
        X0, y0, w0 = self._window(0)
        sd = StreamingDataset(self.num_features, params=self.params,
                              has_weight=w0 is not None)
        chunk = max(len(y0) // 4, 1)
        for lo in range(0, len(y0), chunk):
            sd.push_rows(X0[lo:lo + chunk], label=y0[lo:lo + chunk],
                         weight=(None if w0 is None
                                 else w0[lo:lo + chunk]))
        sharded = sd.finalize(
            spill_dir=self.spill_dir,
            shard_rows=self.shard_rows or max(len(y0) // 4, 1))
        bst = _train(dict(self.params), self._wrap(sharded),
                     num_boost_round=self.base_rounds,
                     checkpoint_dir=self.ckpt_dir,
                     checkpoint_freq=self.checkpoint_freq)
        version = self.registry.load(self.name, booster=bst)
        profile = getattr(bst.inner, "quality_profile", None)
        if profile is not None:
            from ..obs import quality as obs_quality
            if profile.score_hist is None:
                # checkpointed runs attach scores at save time; a
                # checkpoint-free loop attaches them here instead
                profile.attach_scores(
                    np.asarray(bst.inner.train_score, dtype=np.float32),
                    objective=getattr(bst.inner, "objective", None))
            _, forest = self.registry.get(self.name)
            # the monitor pins the BASE model's quantizer grid: drift
            # across later refresh publishes is measured on one fixed
            # grid, never an artifact of a model swap
            self.quality = obs_quality.QualityMonitor(
                forest, profile=profile, name=self.name,
                min_window_rows=self.drift_min_window_rows)
        self.server = PredictServer(self.registry, name=self.name,
                                    max_batch=self.max_batch,
                                    max_wait_ms=self.max_wait_ms,
                                    quality=self.quality)
        pool = []
        for i in range(self.traffic_pool):
            blk = X0[i * self.traffic_rows:(i + 1) * self.traffic_rows]
            if blk.shape[0] < self.traffic_rows:
                break
            pool.append(np.ascontiguousarray(blk, dtype=np.float32))
        if not pool:  # window smaller than one block: pump what exists
            pool = [np.ascontiguousarray(X0[:self.traffic_rows],
                                         dtype=np.float32)]
        self._block = pool[0]
        self.server.predict(self._block, timeout=120)  # warm the bucket
        if self.quality is not None:
            self.quality.drain(obs_registry)  # warm rows != window 0
        self.traffic = TrafficGenerator(
            self.server, pool, threads=self.traffic_threads,
            shift_after_rows=self.shift_after_rows,
            shift_fn=self.shift_fn)
        self.traffic.start()
        seconds = time.perf_counter() - t0
        rec = {"cycle": 0, "outcome": "bootstrap", "version": version,
               "stable_version": version, "seconds": round(seconds, 3),
               "rounds": self.base_rounds, "chaos": [], "injected": 0,
               "p99_ms": self.server.latency_percentiles()["p99"]}
        events.emit("refresh_cycle", **rec)
        return rec

    def _poisoned_publish(self, model_str: str, spec: str,
                          problems: List[str]):
        """Publish a canary that is SCHEDULED to die: quiesce the
        generator pumps (so the injected ``serve_dispatch`` fault can
        only land on the canary's first batch), publish, drive one
        request through the window, and let the rollback-and-replay
        machinery answer it on the stable version. Traffic resumes the
        instant the rollback is in the registry — the server itself
        never stopped."""
        if not self.traffic.pause():
            problems.append("could not quiesce traffic for the "
                            "poisoned publish")
        faults.configure(spec)
        version = None
        try:
            version = self.registry.load(
                self.name, model_str=model_str,
                canary_batches=self.canary_batches)
            try:
                # rolls back, then replays THIS batch on stable
                self.server.predict(self._block, timeout=120)
            except (ServeError, faults.InjectedFault) as e:
                problems.append(
                    "poisoned canary did not replay on stable: %s: %s"
                    % (type(e).__name__, str(e)[:120]))
        finally:
            faults.reset()
            self.traffic.resume()
        outcome = self._await_canary(version)
        return outcome, version

    def _refresh_cycle(self, cycle: int,
                       legs: List[chaos_mod.ChaosLeg],
                       problems: List[str]) -> Dict:
        t0 = time.perf_counter()
        inj0 = obs_registry.count("ft/faults_injected")
        train_spec = ";".join(l.spec for l in legs
                              if l.phase == "train")
        pub_legs = [l for l in legs if l.phase == "publish"]
        tele_spec = ";".join(l.spec for l in legs
                             if l.phase == "telemetry")
        poison = any(l.poison for l in pub_legs)

        # --- retrain: reopen the spill (no re-binning) + resume -------
        attached = ShardedBinnedDataset.attach(
            self.spill_dir, config=Config.from_params(self.params))
        rounds = self.base_rounds + self.extra_rounds * cycle
        if train_spec:
            faults.configure(train_spec)
        try:
            bst = _train(dict(self.params), self._wrap(attached),
                         num_boost_round=rounds,
                         checkpoint_dir=self.ckpt_dir,
                         checkpoint_freq=self.checkpoint_freq,
                         resume=True)
        finally:
            if train_spec:
                faults.reset()

        # --- refit on the fresh window (pure device replay) ----------
        Xw, yw, ww = self._window(cycle)
        if self.quality is not None:
            # refresh windows carry labels; serve traffic does not —
            # this is the label-drift signal's only source
            self.quality.observe_labels(yw)
        bst.refit(Xw, yw, weight=ww)
        model_str = bst.model_to_string()

        # --- canary publish into the LIVE server ---------------------
        prev_version = self.registry.get(self.name)[0]
        if poison:
            spec = ";".join(l.spec for l in pub_legs)
            outcome, version = self._poisoned_publish(
                model_str, spec, problems)
        else:
            pub_spec = ";".join(l.spec for l in pub_legs)
            if pub_spec:
                faults.configure(pub_spec)
            try:
                version = self.registry.load(
                    self.name, model_str=model_str,
                    canary_batches=self.canary_batches)
                # live traffic drives the canary window to a verdict
                outcome = self._await_canary(version)
            finally:
                if pub_spec:
                    faults.reset()

        # --- telemetry push (fault-injectable, retried, never fatal) -
        if self._pusher is not None:
            if tele_spec:
                faults.configure(tele_spec)
            try:
                self._pusher.push_now()
            finally:
                if tele_spec:
                    faults.reset()

        # --- per-cycle SLO evaluation ---------------------------------
        stable = self.registry.get(self.name)[0]
        p99 = self.server.latency_percentiles()["p99"]
        obs_registry.gauge("refresh/serve_p99_ms", p99)
        obs_registry.gauge("refresh/stable_version", stable)
        fired = self.watchdog.evaluate()
        injected = obs_registry.count("ft/faults_injected") - inj0

        if poison:
            if outcome != "rolled_back":
                problems.append("cycle %d: poisoned canary %s "
                                "(expected rolled_back)"
                                % (cycle, outcome))
            elif stable != prev_version:
                problems.append("cycle %d: rollback left stable v%s "
                                "(expected v%s to keep serving)"
                                % (cycle, stable, prev_version))
        elif outcome != "promoted":
            problems.append("cycle %d: clean refresh %s (expected "
                            "promoted)" % (cycle, outcome))
        if legs and injected == 0:
            problems.append("cycle %d: scheduled fault(s) %s never "
                            "fired" % (cycle,
                                       [l.spec for l in legs]))

        rec = {"cycle": cycle, "outcome": outcome, "version": version,
               "stable_version": stable,
               "seconds": round(time.perf_counter() - t0, 3),
               "rounds": rounds, "chaos": [l.spec for l in legs],
               "injected": injected, "p99_ms": round(p99, 3),
               "breaches": [f["rule"] for f in fired]}
        events.emit("refresh_cycle", **rec)
        return rec

    # ------------------------------------------------------------------
    def _drift_gate(self, cycle: int, problems: List[str]) -> Dict:
        """Gate one refresh cycle on observed serving-input drift.

        ``refresh_trigger="drift"``: drain up to ``drift_max_windows``
        short windows; the first whose per-feature PSI max breaches
        ``LIGHTGBM_TPU_WATCH_PSI`` starts the cycle early (counted in
        ``drift_triggered_refreshes``); a clean streak proceeds anyway
        (cadence fallback). ``refresh_trigger="cadence"``: one window
        still drains per cycle so the quality gauges — and the drift
        watchdog rules — stay live, but nothing is gated on them."""
        if self.quality is None:
            if (self.refresh_trigger == "drift"
                    and not self._warned_no_quality):
                problems.append(
                    "refresh_trigger='drift' but the spill carried no "
                    "quality profile (written before the quality "
                    "plane?) — cycles fall back to cadence")
                self._warned_no_quality = True
            return {}
        thr = float(os.environ.get("LIGHTGBM_TPU_WATCH_PSI", "0.25"))
        budget = (self.drift_max_windows
                  if self.refresh_trigger == "drift" else 1)
        psi_seen = 0.0
        for w in range(1, budget + 1):
            time.sleep(self.drift_window_s)
            rep = self.quality.drain(obs_registry)
            self.drift_windows += 1
            psi = float(rep.get("psi_max", 0.0))
            psi_seen = max(psi_seen, psi)
            self.drift_psi_max = max(self.drift_psi_max, psi)
            if (self.refresh_trigger == "drift"
                    and rep.get("rows", 0) and psi >= thr):
                self.drift_triggered += 1
                if self.drift_detect_windows is None:
                    self.drift_detect_windows = w
                return {"drift_gate": "triggered", "drift_windows": w,
                        "drift_psi": round(psi, 4)}
        if self.refresh_trigger != "drift":
            return {"drift_psi": round(psi_seen, 4)}
        return {"drift_gate": "cadence_fallback",
                "drift_windows": budget,
                "drift_psi": round(psi_seen, 4)}

    # ------------------------------------------------------------------
    def run(self, cycles: int) -> Dict:
        """Run ``cycles`` total cycles (cycle 0 bootstraps; each later
        cycle is a refresh) and return the loop report. The report's
        ``ok`` is the whole contract: every scheduled outcome happened,
        every scheduled fault fired, zero ``refresh_slo`` breaches,
        zero stranded futures, zero untyped traffic failures."""
        if cycles < 2:
            raise ValueError("a closed loop needs >= 2 cycles "
                             "(bootstrap + at least one refresh)")
        schedule = (self.schedule if self.schedule is not None
                    else chaos_mod.refresh_schedule(cycles))
        chaos_mod.validate_schedule(schedule)
        obs_registry.enable()
        rb0 = obs_registry.count("serve/rollbacks")
        drain0 = obs_registry.count("serve/drain_failed")
        slo0 = obs_registry.count("health/refresh_slo")
        inj0 = obs_registry.count("ft/faults_injected")

        if self.use_gateway:
            self._gateway = obs_gateway.MetricsGateway(port=0)
            self._pusher = obs_gateway.SnapshotPusher(
                self._gateway.url, interval=0, role="refresh")

        self.watchdog = obs_health.Watchdog(obs_registry)
        obs_registry.gauge("refresh/active", 1)
        self.watchdog.evaluate()   # arm: baseline the counter deltas

        problems: List[str] = []
        records: List[Dict] = []
        try:
            records.append(self._bootstrap())
            for cycle in range(1, cycles):
                gate = self._drift_gate(cycle, problems)
                rec = self._refresh_cycle(
                    cycle, schedule.get(cycle, []), problems)
                rec.update(gate)
                records.append(rec)
        finally:
            traffic = self.traffic.stop() if self.traffic else {}
            if self.server is not None:
                self.server.stop(self.drain_timeout_s)
            # stranded-future check runs with the loop still "active"
            # (the refresh_slo rule disarms once the gauge clears)
            if self.watchdog is not None:
                self.watchdog.evaluate()
            obs_registry.gauge("refresh/active", 0)
            if self._gateway is not None:
                self._gateway.close()

        if traffic.get("untyped"):
            problems.append("untyped traffic failures: %s"
                            % "; ".join(traffic["untyped"][:4]))
        rollbacks = obs_registry.count("serve/rollbacks") - rb0
        expected_rb = chaos_mod.expected_rollbacks(schedule)
        if rollbacks != expected_rb:
            problems.append("%d rollbacks (schedule expected %d)"
                            % (rollbacks, expected_rb))
        stranded = obs_registry.count("serve/drain_failed") - drain0
        if stranded:
            problems.append("%d futures stranded at drain" % stranded)
        slo_breaches = obs_registry.count("health/refresh_slo") - slo0
        if slo_breaches:
            problems.append("%d refresh_slo breaches" % slo_breaches)
        for p in problems:
            log.warning("refresh loop: %s" % p)

        refresh_secs = [r["seconds"] for r in records if r["cycle"] > 0]
        report = {
            "cycles": records,
            "num_cycles": len(records),
            "refresh_cycle_seconds": round(
                float(np.mean(refresh_secs)) if refresh_secs else 0.0,
                3),
            "serve_p99_during_refresh_ms": round(
                max((r["p99_ms"] for r in records), default=0.0), 3),
            "refresh_slo_breaches": int(slo_breaches),
            "refresh_rollbacks": int(rollbacks),
            "expected_rollbacks": int(expected_rb),
            "stranded_futures": int(stranded),
            "faults_injected": obs_registry.count("ft/faults_injected")
            - inj0,
            "refresh_trigger": self.refresh_trigger,
            "drift_psi_max": round(self.drift_psi_max, 4),
            "drift_windows": int(self.drift_windows),
            "drift_detect_windows": self.drift_detect_windows,
            "drift_triggered_refreshes": int(self.drift_triggered),
            "traffic": traffic,
            "problems": problems,
            "ok": not problems,
        }
        events.emit("refresh_done", ok=report["ok"],
                    num_cycles=report["num_cycles"],
                    rollbacks=rollbacks, slo_breaches=slo_breaches,
                    stranded=stranded)
        return report
