"""Closed-loop continuous model refresh.

``RefreshController`` keeps a model fresh under live traffic: stream a
window through the spill path, train with checkpoints, publish into a
live :class:`~lightgbm_tpu.serve.PredictServer`; then, every cycle,
re-attach the spill (no re-binning), resume training from the newest
checkpoint, refit leaf values on the newest window entirely on device
(``Booster.refit``), and canary-publish the refreshed model while
generated traffic keeps flowing. The ``refresh_slo`` watchdog rule
(obs/health.py) and the unified chaos schedule (loop/chaos.py) make the
loop's reliability claims falsifiable every cycle. See docs/REFRESH.md.
"""
from .chaos import (ChaosLeg, SERVE_SITES, TRAIN_SITES,  # noqa: F401
                    expected_rollbacks, refresh_schedule,
                    validate_schedule)
from .controller import RefreshController, TrafficGenerator  # noqa: F401

__all__ = ["RefreshController", "TrafficGenerator", "ChaosLeg",
           "refresh_schedule", "expected_rollbacks",
           "validate_schedule", "TRAIN_SITES", "SERVE_SITES"]
