"""Leveled logging for lightgbm_tpu.

TPU-native analogue of the reference's ``Log`` utility
(reference: include/LightGBM/utils/log.h:178): leveled Debug/Info/Warning/Fatal
where Fatal raises instead of aborting, and the sink is redirectable (the
reference exposes LGBM_RegisterLogCallback, src/c_api.cpp:904; here the sink is
just a Python callable).
"""
from __future__ import annotations

import sys
from enum import IntEnum
from typing import Callable, Optional


class LogLevel(IntEnum):
    FATAL = -1
    WARNING = 0
    INFO = 1
    DEBUG = 2


class LightGBMError(Exception):
    """Raised on fatal errors (reference: Log::Fatal throws std::runtime_error)."""


_level: LogLevel = LogLevel.INFO
_sink: Optional[Callable[[str], None]] = None


def set_verbosity(verbosity: int) -> None:
    """Map the reference's ``verbosity`` config to a log level.

    <0: fatal only, 0: warning, 1: info, >1: debug
    (reference: include/LightGBM/config.h:567 + c_api.cpp verbosity handling).
    """
    global _level
    if verbosity < 0:
        _level = LogLevel.FATAL
    elif verbosity == 0:
        _level = LogLevel.WARNING
    elif verbosity == 1:
        _level = LogLevel.INFO
    else:
        _level = LogLevel.DEBUG


def register_log_callback(fn: Optional[Callable[[str], None]]) -> None:
    global _sink
    _sink = fn


def _emit(msg: str) -> None:
    if _sink is not None:
        _sink(msg + "\n")
    else:
        print(msg, file=sys.stderr, flush=True)


def debug(msg: str, *args) -> None:
    if _level >= LogLevel.DEBUG:
        _emit("[LightGBM-TPU] [Debug] " + (msg % args if args else msg))


def info(msg: str, *args) -> None:
    if _level >= LogLevel.INFO:
        _emit("[LightGBM-TPU] [Info] " + (msg % args if args else msg))


def warning(msg: str, *args) -> None:
    if _level >= LogLevel.WARNING:
        _emit("[LightGBM-TPU] [Warning] " + (msg % args if args else msg))


def always(msg: str, *args) -> None:
    """Emit regardless of verbosity. For output the user explicitly
    asked for (the LIGHTGBM_TPU_TIMETAG stage table) — the analogue of
    the reference's USE_TIMETAG dump printing even in quiet builds."""
    _emit("[LightGBM-TPU] [Info] " + (msg % args if args else msg))


def warning_always(msg: str, *args) -> None:
    """Warning that ignores the verbosity gate — for degradations that
    must never be silent (backend fallback). verbosity=-1 callers (the
    bench) would otherwise swallow exactly the message the telemetry
    layer exists to surface."""
    _emit("[LightGBM-TPU] [Warning] " + (msg % args if args else msg))


def fatal(msg: str, *args) -> None:
    """Log then raise (reference: Log::Fatal prints to stderr before
    aborting, include/LightGBM/utils/log.h:178 — a registered sink must
    see fatal messages too, not just the exception)."""
    msg = msg % args if args else msg
    _emit("[LightGBM-TPU] [Fatal] " + msg)
    try:
        from ..obs import events as _events
        _events.emit("log_fatal", message=msg)
        _events.flush()  # buffered sink: the crash evidence must land
    except Exception:
        pass
    try:
        # streaming trace spool / span buffer: finalize what has been
        # emitted so far — the segments leading up to the crash are the
        # evidence the spool exists for
        from ..obs import trace as _trace
        if _trace.active():
            _trace.flush()
    except Exception:
        pass
    raise LightGBMError(msg)
