"""Bounded, seeded retry with exponential backoff for transient I/O.

A day-long run crosses thousands of staging hops, spill writes, segment
finalizes and snapshot dumps; any one of them can fail transiently (a
busy device runtime, an NFS hiccup, an interrupted syscall). Before
this module a single such failure either killed the run (spill write)
or wedged it (a prefetcher worker exception the consumer never saw).
:func:`retry_call` gives every such site the same contract:

- up to ``attempts`` tries (``LIGHTGBM_TPU_RETRY_ATTEMPTS``, default 3)
  with exponential backoff + jitter from a SEEDED RNG
  (``LIGHTGBM_TPU_RETRY_SEED`` xor the site name — reruns back off
  identically, which keeps chaos tests and the fault-injection harness
  in obs/faults.py deterministic);
- every retry counts under ``ft/retries`` (total) and
  ``ft/retries/<site>`` and emits an ``io_retry`` event — the
  ``fault_storm`` watchdog rule (obs/health.py) monitors the total;
- giving up counts under ``ft/retry_exhausted``, emits a flushed
  ``retry_exhausted`` event (the evidence must survive the crash that
  likely follows), and re-raises the last error unchanged.

``retry_on`` filters which exception types are considered transient;
``no_retry`` vetoes individual instances (the spill path passes a
predicate matching ENOSPC — a full disk does not get emptier by
retrying, it gets the degradation path in io/shards.py instead).
"""
from __future__ import annotations

import os
import time
import zlib
from typing import Callable, Optional, Tuple, Type

import numpy as np

from ..obs import events as obs_events
from ..obs.registry import registry
from . import log

_ENV_ATTEMPTS = "LIGHTGBM_TPU_RETRY_ATTEMPTS"
_ENV_BASE_MS = "LIGHTGBM_TPU_RETRY_BASE_MS"
_ENV_SEED = "LIGHTGBM_TPU_RETRY_SEED"
kMaxBackoffMs = 2000.0


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def retry_call(fn: Callable, site: str, *,
               attempts: Optional[int] = None,
               base_ms: Optional[float] = None,
               retry_on: Tuple[Type[BaseException], ...] = (OSError,),
               no_retry: Optional[Callable[[BaseException], bool]] = None,
               reg=registry):
    """Call ``fn()`` with the bounded-retry contract above; returns its
    result or re-raises the final (or non-retryable) error."""
    n = max(attempts if attempts is not None
            else _env_int(_ENV_ATTEMPTS, 3), 1)
    base = max(base_ms if base_ms is not None
               else _env_float(_ENV_BASE_MS, 25.0), 0.0)
    rng = None
    for attempt in range(1, n + 1):
        try:
            return fn()
        except retry_on as e:
            if no_retry is not None and no_retry(e):
                raise
            if attempt >= n:
                reg.inc("ft/retry_exhausted")
                obs_events.emit("retry_exhausted", site=site,
                                attempts=n, error=repr(e))
                obs_events.flush()
                log.warning_always(
                    "%s: giving up after %d attempts (%r)"
                    % (site, n, e))
                raise
            reg.inc("ft/retries")
            reg.inc("ft/retries/" + site)
            if rng is None:
                rng = np.random.RandomState(
                    (_env_int(_ENV_SEED, 0)
                     ^ zlib.crc32(site.encode())) & 0x7FFFFFFF)
            delay_ms = min(base * (2.0 ** (attempt - 1)),
                           kMaxBackoffMs) * (0.5 + rng.random_sample())
            obs_events.emit("io_retry", site=site, attempt=attempt,
                            delay_ms=round(delay_ms, 3), error=repr(e))
            if delay_ms > 0:
                time.sleep(delay_ms / 1000.0)
