"""Runtime lock sanitizer — the dynamic complement to jaxlint's JLT10x.

Static rules (JLT101-103) prove discipline over the code they can
see; this module checks the SAME discipline over executions: set
``LIGHTGBM_TPU_LOCKTRACE=1`` and every named lock of the serving and
refresh planes is wrapped in a tracing proxy that keeps

- a **per-thread acquisition stack** (which named locks this thread
  holds, in order),
- a **global lock-order graph**: the first time lock B is taken while
  A is held, the edge A->B is recorded with a witness stack; a later
  acquisition implying B->A raises :class:`LockOrderError`
  IMMEDIATELY — before the raw acquire, in the acquiring thread — so
  an inversion is caught deterministically even when the schedule
  never actually deadlocks (single-threaded replays included),
- **bounded hold times**: releasing a lock held longer than the
  budget records a violation (``Condition.wait`` closes the hold
  interval while the lock is out, so waiting is never billed as
  holding).

Hold-time overruns are recorded, not raised — a slow CI machine must
not turn a latency smell into a crash mid-dispatch; the test asserts
over :func:`report`/:func:`assert_clean` at the window boundary
instead. Order inversions DO raise at the acquire: they are schedule
bugs, not speed bugs, and the whole point is catching them on the
replay where the interleaving happened to be safe.

Wiring: classes call :func:`maybe_trace` at the end of ``__init__``
(before any worker thread starts); with the env var unset this is a
no-op and the class runs on raw primitives. Proxies wrap by
composition around the SAME underlying primitive, so a lock shared
across objects (the replica-shared ``entries_lock``) stays mutually
exclusive with every proxy and with untraced references alike.

Enable:   LIGHTGBM_TPU_LOCKTRACE=1
Budget:   LIGHTGBM_TPU_LOCKTRACE_MAX_HOLD_MS (default 500)
"""
from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LockOrderError", "TracedLock", "TracedCondition", "enabled",
    "trace_object", "maybe_trace", "reset", "report", "assert_clean",
]

_ENV = "LIGHTGBM_TPU_LOCKTRACE"
_ENV_HOLD = "LIGHTGBM_TPU_LOCKTRACE_MAX_HOLD_MS"

_LOCK_TYPE = type(threading.Lock())
_RLOCK_TYPE = type(threading.RLock())


def enabled() -> bool:
    return os.environ.get(_ENV, "") not in ("", "0")


class LockOrderError(AssertionError):
    """Two code paths take the same pair of locks in opposite orders;
    two threads interleaving them deadlock."""


def _stack(limit: int = 8) -> List[str]:
    # drop the locktrace frames themselves; the caller's frames are
    # what identifies the witness site
    return [ln.strip() for ln in
            traceback.format_stack(limit=limit)[:-3]]


class _Tracer:
    """One process-wide order graph + violation log. Its own state is
    guarded by a raw (untraced) lock that is never held across a
    traced acquire, so the sanitizer cannot deadlock the sanitized."""

    def __init__(self) -> None:
        self._meta = threading.Lock()
        self._tls = threading.local()
        #: (held, acquired) -> witness {thread, stack}
        self.edges: Dict[Tuple[str, str], dict] = {}
        self.order_violations: List[dict] = []
        self.hold_violations: List[dict] = []
        self.acquires = 0
        try:
            ms = float(os.environ.get(_ENV_HOLD, "500"))
        except ValueError:
            ms = 500.0
        self.max_hold_s = ms / 1000.0

    # -- per-thread stack ----------------------------------------------
    def held(self) -> List[Tuple[str, float]]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    # -- events --------------------------------------------------------
    def note_acquire(self, name: str) -> None:
        """Called BEFORE the raw acquire: record order edges from every
        currently-held lock and raise on an inversion."""
        held = self.held()
        self.acquires += 1
        for h, _t0 in held:
            if h == name:
                continue  # re-acquire of the same named lock
            with self._meta:
                rev = self.edges.get((name, h))
                self.edges.setdefault((h, name), {
                    "thread": threading.current_thread().name,
                    "stack": _stack(),
                })
                if rev is None:
                    continue
                v = {
                    "pair": (h, name),
                    "thread": threading.current_thread().name,
                    "stack": _stack(),
                    "reverse_thread": rev["thread"],
                    "reverse_stack": rev["stack"],
                }
                self.order_violations.append(v)
            raise LockOrderError(
                "lock order inversion: acquiring %r while holding %r, "
                "but thread %r already took %r before %r (witness:\n  "
                "%s)" % (name, h, v["reverse_thread"], name, h,
                         "\n  ".join(v["reverse_stack"][-2:])))

    def push(self, name: str) -> None:
        self.held().append((name, time.monotonic()))

    def pop(self, name: str) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                _, t0 = held.pop(i)
                dur = time.monotonic() - t0
                if dur > self.max_hold_s:
                    with self._meta:
                        self.hold_violations.append({
                            "lock": name,
                            "held_s": dur,
                            "budget_s": self.max_hold_s,
                            "thread":
                                threading.current_thread().name,
                        })
                return
        # release of a lock acquired before tracing wrapped it (or on
        # another proxy path): nothing to bill


_TRACER = _Tracer()


class TracedLock:
    """Composition proxy over a ``threading.Lock``/``RLock``."""

    def __init__(self, raw, name: str,
                 tracer: Optional[_Tracer] = None) -> None:
        self._raw = raw
        self._name = name
        self._tracer = tracer or _TRACER

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._tracer.note_acquire(self._name)
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._tracer.push(self._name)
        return got

    def release(self) -> None:
        self._tracer.pop(self._name)
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return "<TracedLock %s of %r>" % (self._name, self._raw)


class TracedCondition:
    """Composition proxy over a ``threading.Condition``. ``wait``/
    ``wait_for`` close the hold interval for the duration of the wait
    (the underlying lock really is released) and reopen it on wake."""

    def __init__(self, raw, name: str,
                 tracer: Optional[_Tracer] = None) -> None:
        self._raw = raw
        self._name = name
        self._tracer = tracer or _TRACER

    def acquire(self, *args):
        self._tracer.note_acquire(self._name)
        got = self._raw.acquire(*args)
        if got:
            self._tracer.push(self._name)
        return got

    def release(self) -> None:
        self._tracer.pop(self._name)
        self._raw.release()

    def __enter__(self) -> "TracedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def wait(self, timeout: Optional[float] = None):
        self._tracer.pop(self._name)
        try:
            return self._raw.wait(timeout)
        finally:
            self._tracer.push(self._name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._tracer.pop(self._name)
        try:
            return self._raw.wait_for(predicate, timeout)
        finally:
            self._tracer.push(self._name)

    def notify(self, n: int = 1) -> None:
        self._raw.notify(n)

    def notify_all(self) -> None:
        self._raw.notify_all()

    def __repr__(self) -> str:
        return "<TracedCondition %s of %r>" % (self._name, self._raw)


# ----------------------------------------------------------------------
# wiring
# ----------------------------------------------------------------------

def trace_object(obj, tracer: Optional[_Tracer] = None):
    """Replace every lock/condition attribute of ``obj`` with a traced
    proxy named ``ClassName.attr``. Idempotent; returns ``obj``."""
    tracer = tracer or _TRACER
    cls = type(obj).__name__
    for attr, val in list(vars(obj).items()):
        if isinstance(val, (TracedLock, TracedCondition)):
            continue
        name = "%s.%s" % (cls, attr)
        if isinstance(val, threading.Condition):
            setattr(obj, attr, TracedCondition(val, name, tracer))
        elif isinstance(val, (_LOCK_TYPE, _RLOCK_TYPE)):
            setattr(obj, attr, TracedLock(val, name, tracer))
    return obj


def maybe_trace(obj):
    """The ``__init__`` hook: trace ``obj`` when the sanitizer is
    enabled, otherwise hand it back untouched."""
    if enabled():
        trace_object(obj)
    return obj


# ----------------------------------------------------------------------
# inspection
# ----------------------------------------------------------------------

def reset() -> None:
    """Fresh order graph and violation log, cleared IN PLACE so the
    proxies already wrapped around live objects keep reporting here
    (tests call this between windows; per-thread held stacks of live
    threads are preserved)."""
    t = _TRACER
    with t._meta:
        t.edges.clear()
        t.order_violations.clear()
        t.hold_violations.clear()
        t.acquires = 0


def tracer() -> _Tracer:
    return _TRACER


def report() -> dict:
    t = _TRACER
    with t._meta:
        return {
            "enabled": enabled(),
            "acquires": t.acquires,
            "edges": {"%s->%s" % k: dict(v)
                      for k, v in t.edges.items()},
            "order_violations": [dict(v)
                                 for v in t.order_violations],
            "hold_violations": [dict(v) for v in t.hold_violations],
            "max_hold_s": t.max_hold_s,
        }


def assert_clean() -> None:
    """Raise ``AssertionError`` describing every recorded violation
    (order inversions that were swallowed by a caller, plus hold-time
    overruns). Clean window -> returns silently."""
    t = _TRACER
    with t._meta:
        order = list(t.order_violations)
        hold = list(t.hold_violations)
    if not order and not hold:
        return
    lines = []
    for v in order:
        lines.append("order inversion %s vs %s (thread %s; reverse "
                     "in %s)" % (v["pair"][0], v["pair"][1],
                                 v["thread"], v["reverse_thread"]))
    for v in hold:
        lines.append("%s held %.3fs by %s (budget %.3fs)"
                     % (v["lock"], v["held_s"], v["thread"],
                        v["budget_s"]))
    raise AssertionError("locktrace: %d violation(s):\n  %s"
                         % (len(lines), "\n  ".join(lines)))
