"""Crash-consistent file writes: tmp + fsync + rename, in one place.

Every artifact this package persists — model text, checkpoints, trace
segments, metric snapshots — must be either absent or complete on disk
after a crash at ANY instruction. The discipline is always the same
(write to a same-directory temp name, fsync, ``os.replace`` over the
final name), but before this module each writer carried its own copy
and the model-text path (``GBDT.save_model`` + the ``snapshot_freq``
snapshots) had none at all: a SIGKILL mid-``f.write`` left a truncated
model file that parses as a shorter model or not at all. This is THE
shared writer; new persistence code should not open(path, "w") a final
name directly.
"""
from __future__ import annotations

import hashlib
import os
from typing import Union


def sha256_file(path: str) -> str:
    """Streamed sha256 of a file — the content-hash half of the
    manifest discipline (shard spills, checkpoints): an artifact that
    does not hash to its manifest entry is rejected by name instead of
    trained on."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def fsync_dir(dirpath: str) -> None:
    """Flush a directory entry (the rename itself) to disk;
    best-effort — not every filesystem supports fsync on a dir fd."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data: Union[str, bytes],
                 durable: bool = True) -> None:
    """Write ``data`` so ``path`` is either its previous content or the
    complete new content — never a truncated mix. The temp file lives in
    the target's directory (rename is only atomic within a filesystem)
    and is removed on any failure. ``durable=True`` additionally fsyncs
    the file (and, best-effort, the directory entry) so the rename
    survives power loss, not just process death."""
    path = str(path)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    mode = "wb" if isinstance(data, bytes) else "w"
    try:
        with open(tmp, mode) as f:
            f.write(data)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if durable:
        fsync_dir(os.path.dirname(os.path.abspath(path)))
