"""Shared small utilities (reference analogue: include/LightGBM/utils/
common.h helpers; most of that header is subsumed by numpy/XLA)."""


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()
