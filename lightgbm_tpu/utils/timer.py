"""Back-compat shim: the stage timer moved into the telemetry subsystem.

The ``Timer``/``global_timer`` API (reference: ``Common::Timer``/
``FunctionTimer``, include/LightGBM/utils/common.h:973,1037) is now the
metrics registry's stage timer — :mod:`lightgbm_tpu.obs.registry` — which
also fixes the old per-scope ``import jax.profiler`` (the module is
resolved once at first use and the failure cached, so per-leaf scopes in
the hot tree-growth loop skip Python import machinery entirely).

``global_timer`` here IS the registry's timer: enabling/printing through
either name observes the same aggregation.
"""
from __future__ import annotations

from ..obs.registry import (StageTimer as Timer,  # noqa: F401
                            registry, start_device_trace,
                            stop_device_trace)

global_timer = registry.timer
