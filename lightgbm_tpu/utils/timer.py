"""Tracing/profiling scopes.

Equivalent of the reference's ``Common::Timer``/``FunctionTimer``
(reference: include/LightGBM/utils/common.h:973,1037 — RAII scopes around
every pipeline stage, aggregated table printed at exit when built with
USE_TIMETAG). The TPU twist: scopes also open ``jax.profiler.TraceAnnotation``
ranges so stages show up in TensorBoard/perfetto device traces.

Enable with ``LIGHTGBM_TPU_TIMETAG=1`` (the analogue of -DUSE_TIMETAG) or
``global_timer.enable()``; print with ``global_timer.print_summary()``.
"""
from __future__ import annotations

import atexit
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional

from . import log


class Timer:
    def __init__(self) -> None:
        self.enabled = bool(int(os.environ.get("LIGHTGBM_TPU_TIMETAG", "0")))
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._printed = False

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    @contextmanager
    def scope(self, name: str):
        """RAII stage scope (reference: FunctionTimer, common.h:1037)."""
        if not self.enabled:
            yield
            return
        annotation = None
        try:
            import jax.profiler
            annotation = jax.profiler.TraceAnnotation(name)
            annotation.__enter__()
        except Exception:
            annotation = None
        start = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - start
            self.counts[name] += 1
            if annotation is not None:
                annotation.__exit__(None, None, None)

    def print_summary(self) -> None:
        """reference: Timer::Print (common.h:1006) — per-stage totals."""
        if not self.totals:
            return
        width = max(len(k) for k in self.totals)
        log.info("%s" % ("-" * (width + 30)))
        log.info("%-*s %12s %8s" % (width, "stage", "seconds", "calls"))
        for name in sorted(self.totals, key=lambda k: -self.totals[k]):
            log.info("%-*s %12.6f %8d"
                     % (width, name, self.totals[name], self.counts[name]))

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


global_timer = Timer()


@atexit.register
def _print_at_exit() -> None:
    if global_timer.enabled:
        global_timer.print_summary()


def start_device_trace(logdir: str) -> None:
    """Start a jax profiler trace (device timeline → TensorBoard)."""
    import jax.profiler
    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax.profiler
    jax.profiler.stop_trace()
