"""Explicit device scalars for hot-loop dispatch arguments.

``jnp.int32(x)`` / ``jnp.float32(x)`` / ``jnp.ones(...)`` on a Python
scalar perform an *implicit* host-to-device transfer on every call —
invisible in traces, flagged by ``jax.transfer_guard("disallow")`` (the
sanitizer test in tests/test_jaxlint.py), and one tiny blocking
dispatch each. The helpers here route every per-iteration scalar
argument through an *explicit* ``jax.device_put`` instead, and cache
the resulting buffers: leaf indices, batch sizes and boolean gate flags
repeat across trees, so the steady-state training loop performs ZERO
host-to-device scalar transfers — the first tree pays one transfer per
distinct value, later trees hit the cache.

Values that never repeat (per-tree seeds) still go through these
helpers: the transfer then happens exactly once per tree and is
explicitly marked as deliberate, which is what keeps the
transfer-guard sanitizer green.
"""
from __future__ import annotations

import functools

import jax
import numpy as np


@functools.lru_cache(maxsize=65536)
def dev_i32(x: int):
    """Device int32 scalar via explicit transfer, cached per value."""
    return jax.device_put(np.int32(x))


@functools.lru_cache(maxsize=65536)
def dev_u32(x: int):
    """Device uint32 scalar via explicit transfer, cached per value."""
    return jax.device_put(np.uint32(x))


@functools.lru_cache(maxsize=65536)
def dev_f32(x: float):
    """Device float32 scalar via explicit transfer, cached per value
    (shrinkage rates and fixed fractions repeat across iterations)."""
    return jax.device_put(np.float32(x))


@functools.lru_cache(maxsize=2)
def dev_bool(x: bool):
    """Device bool scalar via explicit transfer (two cached values)."""
    return jax.device_put(np.bool_(x))
