"""User-facing ``Dataset`` and ``Booster``.

API-shaped after the reference's Python package
(reference: python-package/lightgbm/basic.py — ``Dataset`` lazy
construction at :1742, ``Booster`` at :2983, ``update`` at :3437). Where
the reference binds a C core through ctypes, this package's core is the
JAX/XLA boosting layer, so these classes adapt parameters and NumPy/pandas
inputs and delegate to :mod:`lightgbm_tpu.boosting`.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .boosting import create_boosting
from .boosting.gbdt import GBDT
from .config import Config
from .io.dataset import BinnedDataset
from .metric import create_metric, resolve_metric_names
from .utils import log

_ArrayLike = Union[np.ndarray, Sequence]


class LightGBMError(Exception):
    pass


def _to_2d_float(data) -> np.ndarray:
    if hasattr(data, "toarray"):  # scipy sparse (csr/csc/coo)
        data = data.toarray()
    elif hasattr(data, "values"):  # pandas
        data = data.values
    arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    if arr.dtype not in (np.float32, np.float64):
        arr = arr.astype(np.float64)
    return arr


class Dataset:
    """Lazy-constructed training data (reference: basic.py ``Dataset``;
    construction deferred to first use like ``construct`` at
    basic.py:2114)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._handle: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._handle is not None:
            return self
        if self.reference is not None:
            self.reference.construct()
        config = Config.from_params(self.params)
        if hasattr(self.data, "tocsc") and not config.linear_tree:
            # scipy sparse stays sparse until binning (per-column pass +
            # EFB in BinnedDataset.from_matrix); no densification
            data = self.data
        else:
            data = _to_2d_float(self.data)
        feature_names = None
        if isinstance(self.feature_name, (list, tuple)):
            feature_names = list(self.feature_name)
        elif hasattr(self.data, "columns"):
            feature_names = [str(c) for c in self.data.columns]
        cat = self.categorical_feature
        if cat == "auto":
            cat = None
        self._handle = BinnedDataset.from_matrix(
            data, config, label=self.label, weights=self.weight,
            group=self.group, init_score=self.init_score,
            feature_names=feature_names, categorical_feature=cat,
            reference=(self.reference._handle
                       if self.reference is not None else None),
            keep_raw_data=bool(config.linear_tree))
        if self.free_raw_data:
            self.data = None
        return self

    @property
    def handle(self) -> BinnedDataset:
        self.construct()
        return self._handle

    # ------------------------------------------------------------------
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._handle is not None and label is not None:
            self._handle.metadata.set_label(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._handle is not None:
            self._handle.metadata.set_weights(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._handle is not None:
            self._handle.metadata.set_group(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._handle is not None:
            self._handle.metadata.set_init_score(init_score)
        return self

    def get_label(self):
        if self._handle is not None:
            return self._handle.metadata.label
        return self.label

    def get_weight(self):
        if self._handle is not None:
            return self._handle.metadata.weights
        return self.weight

    def get_group(self):
        if self._handle is not None and \
                self._handle.metadata.query_boundaries is not None:
            qb = self._handle.metadata.query_boundaries
            return np.diff(qb)
        return self.group

    def num_data(self) -> int:
        return self.handle.num_data

    def num_feature(self) -> int:
        return self.handle.num_total_features

    def get_feature_name(self) -> List[str]:
        return list(self.handle.feature_names)

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        """reference: Dataset.create_valid (basic.py)."""
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset sharing this dataset's bin mappers
        (reference: Dataset.subset, basic.py)."""
        self.construct()
        idx = np.asarray(used_indices, dtype=np.int64)
        sub = Dataset.__new__(Dataset)
        sub.params = dict(params or self.params)
        sub.reference = self
        sub.free_raw_data = True
        sub.data = None
        sub.label = None
        sub.weight = None
        sub.group = None
        sub.init_score = None
        sub.feature_name = self.feature_name
        sub.categorical_feature = self.categorical_feature
        sub.used_indices = idx
        import copy
        h = BinnedDataset()
        src = self._handle
        h.bins = src.bins[idx]  # row subset keeps the bundle layout
        h.bundle = src.bundle
        h.bin_mappers = src.bin_mappers
        h.used_feature_map = src.used_feature_map
        h.num_total_features = src.num_total_features
        h.feature_names = src.feature_names
        h.num_bin_per_feature = src.num_bin_per_feature
        h.max_num_bin = src.max_num_bin
        h.monotone_constraints = src.monotone_constraints
        h.feature_penalty = src.feature_penalty
        if src.raw_data is not None:
            h.raw_data = src.raw_data[idx]
        from .io.dataset import Metadata
        md = Metadata(len(idx))
        md.set_label(np.asarray(src.metadata.label)[idx])
        if src.metadata.weights is not None:
            md.set_weights(np.asarray(src.metadata.weights)[idx])
        if src.metadata.init_score is not None:
            isc = np.asarray(src.metadata.init_score).reshape(
                -1, src.metadata.num_data)
            md.set_init_score(isc[:, idx].reshape(-1))
        if src.metadata.query_boundaries is not None:
            # rebuild group sizes from the subset rows' query ids (cv's
            # group-aware folds keep queries whole, so runs of equal ids
            # reconstruct the original groups)
            qb = np.asarray(src.metadata.query_boundaries)
            qid = np.searchsorted(qb, idx, side="right") - 1
            change = np.concatenate([[True], qid[1:] != qid[:-1]])
            starts = np.flatnonzero(change)
            sizes = np.diff(np.concatenate([starts, [len(idx)]]))
            md.set_group(sizes)
        h.metadata = md
        sub._handle = h
        return sub


class Booster:
    """reference: basic.py ``Booster`` (:2983)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.params = dict(params or {})
        self.config = Config.from_params(self.params)
        self._train_set = train_set
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._valid_names: List[str] = []
        if train_set is not None:
            if train_set._handle is None:
                # dataset-level knobs (monotone_constraints, max_bin,
                # categorical_feature, ...) passed at the Booster level
                # must reach construction, same precedence as
                # engine.train: the dataset's own params win (reference:
                # Booster::Booster passes the params string into
                # Dataset construction, c_api.cpp)
                train_set.params = dict(self.params,
                                        **(train_set.params or {}))
            train_set.construct()
            self.inner: GBDT = create_boosting(self.config,
                                               train_set.handle)
        elif model_file is not None:
            with open(model_file) as f:
                s = f.read()
            self.inner = create_boosting(self.config)
            self.inner.load_model_from_string(s)
            self.best_iteration = -1
        elif model_str is not None:
            self.inner = create_boosting(self.config)
            self.inner.load_model_from_string(model_str)
        else:
            raise LightGBMError(
                "Booster needs train_set, model_file or model_str")

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        self.inner.add_valid_data(data.handle)
        self._valid_names.append(name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration (reference: basic.py:3437; custom fobj
        path __boost at :3508). Returns True when training should stop."""
        if fobj is not None:
            label = self.inner.train_data.metadata.label
            grad, hess = fobj(np.asarray(self.inner.train_score).squeeze(),
                              self._train_set)
            return self.inner.train_one_iter(np.asarray(grad),
                                             np.asarray(hess))
        return self.inner.train_one_iter()

    def rollback_one_iter(self) -> "Booster":
        self.inner.rollback_one_iter()
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Update training parameters between iterations (reference:
        Booster.reset_parameter → LGBM_BoosterResetParameter →
        GBDT::ResetConfig). Supports the per-iteration schedulable
        subset (learning_rate, bagging, regularization...)."""
        import dataclasses
        self.params.update(params)
        cfg = Config.from_params(self.params)
        self.config = cfg
        inner = self.inner
        inner.config = cfg
        inner.shrinkage_rate = float(cfg.learning_rate)
        if getattr(inner, "learner", None) is not None:
            inner.learner.config = cfg
            from .ops_refresh import refresh_learner_params
            refresh_learner_params(inner.learner, cfg)
        if getattr(inner, "sample_strategy", None) is not None:
            # strategies cache config-derived draw state (fractions,
            # freq, GOSS warm-up); refresh re-derives it so scheduled
            # bagging params keep their pre-refactor live semantics
            inner.sample_strategy.refresh_config(cfg)
        return self

    @property
    def current_iteration(self) -> int:
        return self.inner.current_iteration

    def num_trees(self) -> int:
        return len(self.inner.models)

    def num_model_per_iteration(self) -> int:
        return self.inner.num_tree_per_iteration

    # ------------------------------------------------------------------
    def eval_train(self, feval=None) -> List[Tuple]:
        return self._eval(None, "training", feval)

    def eval_valid(self, feval=None) -> List[Tuple]:
        out = []
        for i in range(len(self.inner.valid_data)):
            name = (self._valid_names[i] if i < len(self._valid_names)
                    else "valid_%d" % i)
            out.extend(self._eval(i, name, feval))
        return out

    def _eval(self, valid_idx: Optional[int], name: str,
              feval=None) -> List[Tuple]:
        # one eval pass = one gbdt::eval_metrics scope + one `eval`
        # event, via the shared instrumentation point in boosting/gbdt.py
        from .boosting.gbdt import run_instrumented_eval
        self.inner._flush_valid_pending()  # eval-hoisting deferrals
        return run_instrumented_eval(
            self.inner.iter,
            lambda: self._eval_inner(valid_idx, name, feval))

    def _eval_inner(self, valid_idx: Optional[int], name: str,
                    feval=None) -> List[Tuple]:
        inner = self.inner
        out = []
        if valid_idx is None:
            score = np.asarray(inner.train_score, dtype=np.float64)
            metrics = inner.train_metrics
            if not metrics:
                # build lazily so eval_train works without
                # is_provide_training_metric
                metrics = []
                for mname in resolve_metric_names(inner.config,
                                                  inner.config.objective):
                    m = create_metric(mname, inner.config)
                    if m is not None:
                        m.init(inner.train_data.metadata, inner.num_data)
                        metrics.append(m)
                inner.train_metrics = metrics
            label_holder = inner.train_data
        else:
            vd = inner.valid_data[valid_idx]
            score = vd.scores
            metrics = vd.metrics
            label_holder = vd.dataset
        sq = score[:, 0] if inner.num_tree_per_iteration == 1 else score
        for m in metrics:
            for mname, v in zip(m.name, m.eval(sq, inner.objective)):
                out.append((name, mname, v, m.factor_to_bigger_better > 0))
        if feval is not None:
            for fe in (feval if isinstance(feval, (list, tuple))
                       else [feval]):
                ds = _FevalDataset(label_holder)
                res = fe(sq if inner.num_tree_per_iteration == 1
                         else score, ds)
                if isinstance(res, tuple):
                    res = [res]
                for mname, v, is_higher in res:
                    out.append((name, mname, v, is_higher))
        return out

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        X = _to_2d_float(data)
        # predict_disable_shape_check (config.h:805): by default a
        # feature-count mismatch is an error, not a silent misprediction
        n_feat = self.inner.max_feature_idx + 1
        if (X.ndim == 2 and X.shape[1] != n_feat
                and not bool(kwargs.get(
                    "predict_disable_shape_check",
                    self.config.predict_disable_shape_check))):
            raise ValueError(
                "The number of features in data (%d) is not the same as "
                "it was in training data (%d). You can set "
                "predict_disable_shape_check=true to discard this "
                "error, but please be aware what you are doing."
                % (X.shape[1], n_feat))
        ni = -1 if num_iteration is None else int(num_iteration)
        if ni <= 0 and self.best_iteration > 0:
            ni = self.best_iteration
        if pred_leaf:
            return self.inner.predict_leaf_index(X, start_iteration, ni)
        if pred_contrib:
            return self.inner.predict_contrib(X, start_iteration, ni)
        out = self._predict_stacked(X, start_iteration, ni, raw_score,
                                    kwargs)
        if out is not None:
            return out
        return self.inner.predict(X, raw_score=raw_score,
                                  start_iteration=start_iteration,
                                  num_iteration=ni)

    # batches below this ride the host walk — a device dispatch (plus a
    # possible first-bucket compile) only pays off on real batches
    _kDeviceMinRows = 256

    @staticmethod
    def _host_walk_warning(reason: str) -> None:
        """A FORCED device predict that must decline emits an assertable
        ``perf_warning`` event (never silent — the round-5 lesson): the
        ISSUE 11 contract is that linear-leaf, EFB-bundled, and f64
        batches all take the device fast path, so any remaining host
        walk under ``predict_on_device=True`` is an exception worth
        surfacing."""
        from .obs import events as obs_events
        from .utils import log
        log.warning("predict_on_device declined to the host walk: %s"
                    % reason)
        obs_events.emit("perf_warning", component="serve.host_walk",
                        message=reason)

    def _predict_stacked(self, X: np.ndarray, start_iteration: int,
                         num_iteration: int, raw_score: bool,
                         kwargs: Dict) -> Optional[np.ndarray]:
        """Fast path: one device dispatch through serve.StackedForest
        (bucketed compile cache kept across calls). Linear-leaf models
        pack their per-leaf fits into the stacked arrays and f64 rows
        ride the double-double encoding, so both keep the bit-exact
        device path. Returns None — fall back to the host walk — only
        when the stacked path cannot reproduce the host result
        BIT-FOR-BIT: pred_early_stop, feature-count mismatch, or mixed
        per-feature missing types (text-loaded edge case); a FORCED
        decline emits a ``perf_warning`` event."""
        forced = kwargs.get("predict_on_device")
        if forced is not None and not forced:
            return None
        if forced is None:
            # auto mode: only worth it where a device dispatch beats the
            # vectorized host walk — real batches on an accelerator. On
            # CPU backends the walk is the same XLA gathers plus compile
            # overhead, so auto stays off (kwarg True still forces).
            if (not self.config.predict_on_device
                    or X.shape[0] < self._kDeviceMinRows):
                return None
            import jax
            if jax.default_backend() == "cpu":
                return None
        if self.config.pred_early_stop or kwargs.get("pred_early_stop"):
            if forced:
                self._host_walk_warning(
                    "pred_early_stop is a host-loop contract")
            return None
        inner = self.inner
        models = inner._used_models(start_iteration, num_iteration)
        if not models:
            return None
        if X.shape[1] != inner.max_feature_idx + 1:
            if forced:
                self._host_walk_warning(
                    "feature count %d != model's %d"
                    % (X.shape[1], inner.max_feature_idx + 1))
            return None
        # cache the packed forest until the model slice changes. Object
        # identity is not enough: refit and DART normalization mutate
        # leaf values IN PLACE, so the key fingerprints the leaf
        # contents (O(total leaves), ~1ms at 500x255 — cheap next to a
        # >=256-row predict)
        import hashlib
        fp = hashlib.blake2b(digest_size=8)
        for t in models:
            fp.update(t.leaf_value[:t.num_leaves].tobytes())
            if t.is_linear:
                fp.update(t.leaf_const[:t.num_leaves].tobytes())
        key = (len(inner.models), fp.hexdigest(),
               start_iteration, num_iteration)
        cached = getattr(self, "_stacked_cache", None)
        if cached is None or cached[0] != key:
            from .serve import BucketedPredictor, StackedForest
            try:
                forest = StackedForest.from_gbdt(inner, start_iteration,
                                                 num_iteration)
            except ValueError as e:
                if forced:
                    self._host_walk_warning(
                        "model cannot stack: %s" % e)
                self._stacked_cache = (key, None)
                return None
            self._stacked_cache = (key, BucketedPredictor(
                forest, model_version=key))
            cached = self._stacked_cache
        predictor = cached[1]
        if predictor is None:
            if forced:
                self._host_walk_warning("model cannot stack (cached)")
            return None
        kind = ("raw" if raw_score or inner.objective is None
                else "value")
        return predictor.predict(X, output_kind=kind)

    # ------------------------------------------------------------------
    def refit(self, data, label, weight=None,
              decay_rate: Optional[float] = None) -> "Booster":
        """Recompute every leaf value from ``(data, label)`` over the
        FROZEN tree structure (reference: Booster.refit →
        GBDT::RefitTree) — the refresh loop's incremental update. Runs
        as a pure device replay: one stacked-forest leaf walk plus
        per-leaf ``segment_sum`` gradient statistics
        (``boosting/refit.py:refit_model_device``), no host tree walk.
        Mutates this booster in place and returns it; the packed
        predict cache re-keys itself off the leaf-value fingerprint.

        ``decay_rate`` defaults to ``config.refit_decay_rate``:
        ``new = decay*old + (1-decay)*shrinkage*optimum`` per leaf.
        """
        from .boosting.refit import refit_model_device
        X = np.asarray(data, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        y = np.asarray(label, dtype=np.float64)
        if X.shape[0] != len(y):
            raise ValueError("refit data has %d rows but %d labels"
                             % (X.shape[0], len(y)))
        if decay_rate is None:
            decay_rate = float(self.config.refit_decay_rate)
        inner = self.inner
        # refit freezes structure and the stacked walk reads ONLY
        # structure, so one packed forest serves every refit cycle
        # until training appends trees (leaf values ride separately)
        key = (len(inner.models),
               sum(t.num_leaves for t in inner.models))
        cached = getattr(self, "_refit_forest", None)
        if cached is None or cached[0] != key:
            from .serve import StackedForest
            cached = (key, StackedForest.from_gbdt(inner))
            self._refit_forest = cached
        refit_model_device(inner, X, y, weight=weight,
                           decay_rate=decay_rate, forest=cached[1])
        return self

    # ------------------------------------------------------------------
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        ni = self._resolve_num_iteration(num_iteration)
        self.inner.save_model(filename, start_iteration, ni)
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        ni = self._resolve_num_iteration(num_iteration)
        return self.inner.save_model_to_string(start_iteration, ni)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> dict:
        """Model as a JSON-serializable dict (reference:
        Booster.dump_model → LGBM_BoosterDumpModel → GBDT::DumpModel)."""
        ni = self._resolve_num_iteration(num_iteration)
        return self.inner.dump_model(start_iteration, ni, importance_type)

    def _resolve_num_iteration(self, num_iteration) -> int:
        if num_iteration is None:
            return self.best_iteration if self.best_iteration > 0 else -1
        return int(num_iteration)

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        ni = -1 if iteration is None else iteration
        return self.inner.feature_importance(importance_type, ni)

    def feature_name(self) -> List[str]:
        return list(self.inner.feature_names)

    def num_feature(self) -> int:
        return self.inner.max_feature_idx + 1

    # pickle via model string round-trip (reference: basic.py __getstate__)
    def __getstate__(self):
        state = self.__dict__.copy()
        state["_model_str"] = self.model_to_string(num_iteration=-1)
        state.pop("inner", None)
        state.pop("_train_set", None)
        state.pop("_stacked_cache", None)  # device arrays don't pickle
        state.pop("_refit_forest", None)
        return state

    def __setstate__(self, state):
        model_str = state.pop("_model_str", None)
        self.__dict__.update(state)
        self._train_set = None
        if model_str is not None:
            self.inner = create_boosting(self.config)
            self.inner.load_model_from_string(model_str)


class _FevalDataset:
    """Duck-typed Dataset passed to custom fevals (exposes get_label /
    get_weight / get_group like the reference's Dataset)."""

    def __init__(self, binned: BinnedDataset):
        self._b = binned

    def get_label(self):
        return np.asarray(self._b.metadata.label)

    def get_weight(self):
        w = self._b.metadata.weights
        return None if w is None else np.asarray(w)

    def get_group(self):
        qb = self._b.metadata.query_boundaries
        return None if qb is None else np.diff(qb)
