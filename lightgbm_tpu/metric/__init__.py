"""Metric layer.

TPU-native equivalent of the reference's metric family
(reference: src/metric/ — factory src/metric/metric.cpp:19). Metrics are
evaluated once per ``metric_freq`` iterations over the full score vector;
they are O(N) elementwise reductions (plus sorts for AUC/NDCG), so they run
vectorized NumPy on host over the fetched score — the same division of
labor as the reference, whose metrics are CPU-side even under device=cuda
(only l2/rmse/binary_logloss have CUDA mirrors, src/metric/cuda/).

``Metric.eval(score, objective)`` returns a list of values;
``factor_to_bigger_better`` follows the reference's convention (positive =
bigger is better) used by early stopping.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..objective import dcg
from ..utils import log

kEpsilon = 1e-15


class Metric:
    name: List[str] = []
    factor_to_bigger_better: float = -1.0  # negative: smaller is better

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = np.asarray(metadata.label, dtype=np.float64)
        self.weights = (None if metadata.weights is None
                        else np.asarray(metadata.weights, dtype=np.float64))
        self.sum_weights = (float(num_data) if self.weights is None
                            else float(self.weights.sum()))

    def eval(self, score: np.ndarray, objective=None) -> List[float]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Regression family (reference: src/metric/regression_metric.hpp)
# ---------------------------------------------------------------------------
class _PointwiseMetric(Metric):
    """Average of a pointwise loss, optionally weight-scaled
    (reference: RegressionMetric::Eval, regression_metric.hpp:55-95)."""

    convert_score = True  # apply objective->ConvertOutput before loss

    def loss(self, label: np.ndarray, score: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def average(self, sum_loss: float) -> float:
        return sum_loss / self.sum_weights

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(self.label.shape)
        if self.convert_score and objective is not None:
            score = objective.convert_output(score)
        pt = self.loss(self.label, score)
        if self.weights is not None:
            pt = pt * self.weights
        return [self.average(float(pt.sum()))]


class L2Metric(_PointwiseMetric):
    name = ["l2"]

    def loss(self, label, score):
        d = score - label
        return d * d


class RMSEMetric(L2Metric):
    name = ["rmse"]

    def average(self, sum_loss):
        return float(np.sqrt(sum_loss / self.sum_weights))


class L1Metric(_PointwiseMetric):
    name = ["l1"]

    def loss(self, label, score):
        return np.abs(score - label)


class QuantileMetric(_PointwiseMetric):
    name = ["quantile"]

    def loss(self, label, score):
        delta = label - score
        a = self.config.alpha
        return np.where(delta < 0, (a - 1.0) * delta, a * delta)


class HuberMetric(_PointwiseMetric):
    name = ["huber"]

    def loss(self, label, score):
        d = score - label
        a = self.config.alpha
        return np.where(np.abs(d) <= a, 0.5 * d * d,
                        a * (np.abs(d) - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = ["fair"]

    def loss(self, label, score):
        x = np.abs(score - label)
        c = self.config.fair_c
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    name = ["poisson"]

    def loss(self, label, score):
        s = np.maximum(score, 1e-10)
        return s - label * np.log(s)


class MAPEMetric(_PointwiseMetric):
    name = ["mape"]

    def loss(self, label, score):
        return np.abs(label - score) / np.maximum(1.0, np.abs(label))


class GammaMetric(_PointwiseMetric):
    name = ["gamma"]

    def loss(self, label, score):
        # reference: regression_metric.hpp:260-270 (negative gamma
        # log-likelihood with psi = 1)
        theta = -1.0 / np.maximum(score, 1e-300)
        b = -_safe_log(-theta)
        c = _safe_log(label) - _safe_log(label)  # psi=1 → zero, kept for parity
        return -((label * theta - b) + c)


class GammaDevianceMetric(_PointwiseMetric):
    name = ["gamma_deviance"]

    def loss(self, label, score):
        tmp = label / (score + 1e-9)
        return tmp - _safe_log(tmp) - 1.0

    def average(self, sum_loss):
        return sum_loss * 2.0


class TweedieMetric(_PointwiseMetric):
    name = ["tweedie"]

    def loss(self, label, score):
        rho = self.config.tweedie_variance_power
        s = np.maximum(score, 1e-10)
        a = label * np.exp((1.0 - rho) * np.log(s)) / (1.0 - rho)
        b = np.exp((2.0 - rho) * np.log(s)) / (2.0 - rho)
        return -a + b


def _safe_log(x):
    return np.log(np.maximum(x, 1e-300))


# ---------------------------------------------------------------------------
# Binary family (reference: src/metric/binary_metric.hpp)
# ---------------------------------------------------------------------------
class _BinaryPointwiseMetric(_PointwiseMetric):
    """Score -> prob via the objective's sigmoid when available
    (reference: BinaryMetric::Eval, binary_metric.hpp:60-95)."""

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(self.label.shape)
        if objective is not None:
            prob = objective.convert_output(score)
        else:
            prob = 1.0 / (1.0 + np.exp(-score))
        pt = self.loss(self.label, prob)
        if self.weights is not None:
            pt = pt * self.weights
        return [self.average(float(pt.sum()))]


class BinaryLoglossMetric(_BinaryPointwiseMetric):
    name = ["binary_logloss"]

    def loss(self, label, prob):
        # reference: binary_metric.hpp:119-130
        p = np.where(label > 0, prob, 1.0 - prob)
        return -np.log(np.maximum(p, kEpsilon))


class BinaryErrorMetric(_BinaryPointwiseMetric):
    name = ["binary_error"]

    def loss(self, label, prob):
        pred_pos = prob > 0.5
        return np.where(pred_pos, label <= 0, label > 0).astype(np.float64)


def _weighted_auc(label_pos: np.ndarray, score: np.ndarray,
                  weights: Optional[np.ndarray]) -> float:
    """Weighted AUC with tie handling (reference: AUCMetric::Eval,
    binary_metric.hpp:160-270: sorted threshold sweep, ties contribute a
    trapezoid)."""
    w = np.ones_like(score) if weights is None else weights
    # ascending order: for each positive, negatives *before* it are the
    # correctly-ranked pairs
    order = np.argsort(score, kind="stable")
    s, wp = score[order], (w * label_pos)[order]
    wn = (w * (1.0 - label_pos))[order]
    # tie groups
    boundary = np.concatenate([[True], s[1:] != s[:-1]])
    group = np.cumsum(boundary) - 1
    ngroups = group[-1] + 1
    gp = np.zeros(ngroups); gn = np.zeros(ngroups)
    np.add.at(gp, group, wp)
    np.add.at(gn, group, wn)
    cum_neg_before = np.concatenate([[0.0], np.cumsum(gn)[:-1]])
    accum = float((gp * (cum_neg_before + 0.5 * gn)).sum())
    total_pos, total_neg = float(wp.sum()), float(wn.sum())
    if total_pos <= 0 or total_neg <= 0:
        log.warning("AUC is undefined with only one class")
        return 1.0
    return accum / (total_pos * total_neg)


class AUCMetric(Metric):
    name = ["auc"]
    factor_to_bigger_better = 1.0

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(self.label.shape)
        return [_weighted_auc((self.label > 0).astype(np.float64), score,
                              self.weights)]


class AveragePrecisionMetric(Metric):
    """reference: binary_metric.hpp AveragePrecisionMetric — threshold
    sweep accumulating precision * recall increments."""

    name = ["average_precision"]
    factor_to_bigger_better = 1.0

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(self.label.shape)
        label_pos = (self.label > 0).astype(np.float64)
        w = np.ones_like(score) if self.weights is None else self.weights
        order = np.argsort(-score, kind="stable")
        s = score[order]
        wp = (w * label_pos)[order]
        wt = w[order]
        boundary = np.concatenate([[True], s[1:] != s[:-1]])
        group = np.cumsum(boundary) - 1
        ngroups = group[-1] + 1
        gp = np.zeros(ngroups); gt = np.zeros(ngroups)
        np.add.at(gp, group, wp)
        np.add.at(gt, group, wt)
        cum_pos = np.cumsum(gp)
        cum_tot = np.cumsum(gt)
        total_pos = cum_pos[-1]
        if total_pos <= 0:
            log.warning("Average precision is undefined without positives")
            return [1.0]
        precision = cum_pos / cum_tot
        recall_delta = gp / total_pos
        return [float((precision * recall_delta).sum())]


# ---------------------------------------------------------------------------
# Multiclass family (reference: src/metric/multiclass_metric.hpp)
# ---------------------------------------------------------------------------
class _MulticlassMetric(Metric):
    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        self.num_class = int(self.config.num_class)

    def _probs(self, score, objective):
        score = np.asarray(score, dtype=np.float64)
        if score.ndim == 1:
            score = score.reshape(self.num_class, -1).T
        if objective is not None:
            return objective.convert_output(score)
        e = np.exp(score - score.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)


class MultiLoglossMetric(_MulticlassMetric):
    name = ["multi_logloss"]

    def eval(self, score, objective=None) -> List[float]:
        p = self._probs(score, objective)
        k = self.label.astype(np.int64)
        pk = p[np.arange(len(k)), k]
        pt = -np.log(np.maximum(pk, kEpsilon))
        if self.weights is not None:
            pt = pt * self.weights
        return [float(pt.sum()) / self.sum_weights]


class MultiErrorMetric(_MulticlassMetric):
    @property
    def name(self):
        k = self.config.multi_error_top_k
        return ["multi_error" if k == 1 else "multi_error@%d" % k]

    def eval(self, score, objective=None) -> List[float]:
        p = self._probs(score, objective)
        k = self.label.astype(np.int64)
        own = p[np.arange(len(k)), k][:, None]
        num_larger = (p >= own).sum(axis=1)  # includes own class
        err = (num_larger > self.config.multi_error_top_k).astype(np.float64)
        if self.weights is not None:
            err = err * self.weights
        return [float(err.sum()) / self.sum_weights]


class AucMuMetric(_MulticlassMetric):
    """reference: AucMuMetric, multiclass_metric.hpp:184-340 — mean of
    pairwise class-separability AUCs over class-pair hyperplanes
    (Kleiman & Page, auc-mu)."""

    name = ["auc_mu"]
    factor_to_bigger_better = 1.0

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        K = self.num_class
        cw = self.config.auc_mu_weights
        if cw:
            if len(cw) != K * K:
                log.fatal("auc_mu_weights must have %d elements" % (K * K))
            self.class_weights = np.asarray(cw, dtype=np.float64).reshape(K, K)
        else:
            self.class_weights = 1.0 - np.eye(K)

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64)
        if score.ndim == 1:
            score = score.reshape(self.num_class, -1).T
        K = self.num_class
        label = self.label.astype(np.int64)
        total = 0.0
        for i in range(K):
            for j in range(i + 1, K):
                v = self.class_weights[i] - self.class_weights[j]
                t1 = v[i] - v[j]
                sel = (label == i) | (label == j)
                d = t1 * (score[sel] @ v)
                is_i = (label[sel] == i).astype(np.float64)
                w = None if self.weights is None else self.weights[sel]
                total += _weighted_auc(is_i, d, w)
        npairs = K * (K - 1) / 2
        return [total / npairs]


# ---------------------------------------------------------------------------
# Ranking family (reference: src/metric/rank_metric.hpp, map_metric.hpp)
# ---------------------------------------------------------------------------
class _RankMetric(Metric):
    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("For ranking metrics, there should be query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries)
        self.num_queries = len(self.query_boundaries) - 1
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]
        # per-query weight = weight of the query's first doc when weighted
        # (reference: Metadata::query_weights_)
        if self.weights is not None:
            qw = np.zeros(self.num_queries)
            for q in range(self.num_queries):
                qw[q] = self.weights[self.query_boundaries[q]]
            self.query_weights = qw
            self.sum_query_weights = float(qw.sum())
        else:
            self.query_weights = None
            self.sum_query_weights = float(self.num_queries)


class NDCGMetric(_RankMetric):
    factor_to_bigger_better = 1.0

    @property
    def name(self):
        return ["ndcg@%d" % k for k in self.eval_at]

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        self.label_gain = dcg.resolve_label_gain(self.config.label_gain)
        dcg.check_label(self.label, len(self.label_gain))
        # cache per-(query, k) inverse max DCG (reference:
        # NDCGMetric::Init, rank_metric.hpp)
        self.inverse_max_dcgs = np.zeros((self.num_queries,
                                          len(self.eval_at)))
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            for ki, k in enumerate(self.eval_at):
                m = dcg.max_dcg_at_k(k, self.label[lo:hi], self.label_gain)
                self.inverse_max_dcgs[q, ki] = 1.0 / m if m > 0 else -1.0

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64)
        result = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            qw = 1.0 if self.query_weights is None else self.query_weights[q]
            for ki, k in enumerate(self.eval_at):
                inv = self.inverse_max_dcgs[q, ki]
                if inv < 0:
                    # no positive labels: define NDCG = 1 (reference)
                    result[ki] += qw
                else:
                    d = dcg.dcg_at_k(k, self.label[lo:hi], score[lo:hi],
                                     self.label_gain)
                    result[ki] += qw * d * inv
        return list(result / self.sum_query_weights)


class MapMetric(_RankMetric):
    factor_to_bigger_better = 1.0

    @property
    def name(self):
        return ["map@%d" % k for k in self.eval_at]

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64)
        result = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            label = self.label[lo:hi]
            npos = int((label > 0.5).sum())
            order = np.argsort(-score[lo:hi], kind="stable")
            is_pos = (label[order] > 0.5).astype(np.float64)
            hits = np.cumsum(is_pos)
            prec = hits / np.arange(1, len(is_pos) + 1)
            qw = 1.0 if self.query_weights is None else self.query_weights[q]
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(is_pos))
                if npos > 0:
                    ap = float((prec[:kk] * is_pos[:kk]).sum()) \
                        / min(npos, kk)
                    result[ki] += qw * ap
                else:
                    result[ki] += qw
        return list(result / self.sum_query_weights)


# ---------------------------------------------------------------------------
# Cross-entropy family (reference: src/metric/xentropy_metric.hpp)
# ---------------------------------------------------------------------------
def _xent_loss(y, p):
    a = np.where(y > 0, y * np.log(np.maximum(p, kEpsilon)), 0.0)
    b = np.where(y < 1, (1.0 - y) * np.log(np.maximum(1.0 - p, kEpsilon)),
                 0.0)
    return -(a + b)


class CrossEntropyMetric(Metric):
    name = ["cross_entropy"]

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(self.label.shape)
        p = 1.0 / (1.0 + np.exp(-score))
        pt = _xent_loss(self.label, p)
        if self.weights is not None:
            pt = pt * self.weights
        return [float(pt.sum()) / self.sum_weights]


class CrossEntropyLambdaMetric(Metric):
    name = ["cross_entropy_lambda"]

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(self.label.shape)
        hhat = np.log1p(np.exp(score))
        w = np.ones_like(score) if self.weights is None else self.weights
        p = 1.0 - np.exp(-w * hhat)
        pt = _xent_loss(self.label, p)
        return [float(pt.sum()) / float(self.num_data)]


class KullbackLeiblerDivergence(Metric):
    """reference: KullbackLeiblerDivergence (xentropy_metric.hpp:240+):
    cross-entropy minus the label-entropy offset."""

    name = ["kullback_leibler"]

    def init(self, metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        y = self.label
        ent = _xent_loss(y, np.clip(y, kEpsilon, 1 - kEpsilon))
        if self.weights is not None:
            ent = ent * self.weights
        self.presum_label_entropy = float(ent.sum()) / self.sum_weights

    def eval(self, score, objective=None) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(self.label.shape)
        p = 1.0 / (1.0 + np.exp(-score))
        pt = _xent_loss(self.label, p)
        if self.weights is not None:
            pt = pt * self.weights
        return [float(pt.sum()) / self.sum_weights
                - self.presum_label_entropy]


# ---------------------------------------------------------------------------
# Factory (reference: Metric::CreateMetric, src/metric/metric.cpp:19)
# ---------------------------------------------------------------------------
_METRICS = {
    "l1": L1Metric, "mean_absolute_error": L1Metric, "mae": L1Metric,
    "regression_l1": L1Metric,
    "l2": L2Metric, "mean_squared_error": L2Metric, "mse": L2Metric,
    "regression": L2Metric, "l2_root": RMSEMetric,
    "rmse": RMSEMetric, "root_mean_squared_error": RMSEMetric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric, "mean_absolute_percentage_error": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "average_precision": AveragePrecisionMetric,
    "auc_mu": AucMuMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric,
    "rank_xendcg": NDCGMetric,
    "map": MapMetric, "mean_average_precision": MapMetric,
    "cross_entropy": CrossEntropyMetric, "xentropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "xentlambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KullbackLeiblerDivergence,
    "kldiv": KullbackLeiblerDivergence,
}


def create_metric(name: str, config) -> Optional[Metric]:
    name = name.strip().lower()
    if name in ("", "none", "null", "na", "custom"):
        return None
    if name not in _METRICS:
        log.fatal("Unknown metric type name: %s" % name)
    return _METRICS[name](config)


def resolve_metric_names(config, objective_name: str) -> List[str]:
    """When no metric is given, default to the objective's metric
    (reference: Config::Set metric default handling)."""
    names = [m for m in (config.metric or []) if m]
    if names:
        return names
    if objective_name in ("custom", "none", ""):
        return []
    return [objective_name]


__all__ = ["Metric", "create_metric", "resolve_metric_names"]
