"""Plotting utilities.

API-shaped after the reference's python-package/lightgbm/plotting.py
(plot_importance, plot_split_value_histogram, plot_metric, plot_tree,
create_tree_digraph). Matplotlib/graphviz are imported lazily and gated —
the module degrades to clear errors when they're absent.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel


def _check_not_tuple_of_2_elements(obj, obj_name: str) -> None:
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError("%s must be a tuple of 2 elements." % obj_name)


def _to_booster(booster) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be a Booster or LGBMModel instance")


def _import_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:
        raise ImportError(
            "You must install matplotlib and restart your session to "
            "use plotting.") from e


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    """reference: plotting.py plot_importance."""
    plt = _import_matplotlib()
    booster = _to_booster(booster)
    if importance_type == "auto":
        importance_type = "split"
    importance = booster.feature_importance(importance_type)
    feature_name = booster.feature_name()
    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("Cannot plot trees with zero importance")
    labels, values = zip(*tuples)
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                _float2str(x, precision) if importance_type == "gain"
                else str(int(x)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _float2str(value, precision: Optional[int] = None) -> str:
    if precision is not None:
        return "{0:.{1}f}".format(value, precision)
    return str(value)


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None,
                title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "auto",
                figsize=None, dpi=None, grid: bool = True):
    """reference: plotting.py plot_metric — plots recorded eval results
    (from ``record_evaluation`` or ``LGBMModel.evals_result_``)."""
    plt = _import_matplotlib()
    if isinstance(booster, LGBMModel):
        eval_results = dict(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = booster
    else:
        raise TypeError(
            "booster must be a dict from record_evaluation or a fitted "
            "LGBMModel instance")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    name_first = dataset_names[0]
    metrics_for_one = eval_results[name_first]
    if metric is None:
        if len(metrics_for_one) > 1:
            raise ValueError(
                "to avoid ambiguity, specify metric to plot")
        metric = list(metrics_for_one.keys())[0]
    for name in dataset_names:
        results = eval_results[name][metric]
        ax.plot(range(1, len(results) + 1), results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    ax.set_ylabel(metric if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None,
                               ylim=None,
                               title="Split value histogram for "
                                     "feature with @index/name@ @feature@",
                               xlabel="Feature split value",
                               ylabel="Count", figsize=None, dpi=None,
                               grid: bool = True, **kwargs):
    """reference: plotting.py plot_split_value_histogram."""
    plt = _import_matplotlib()
    booster = _to_booster(booster)
    if isinstance(feature, str):
        feature_idx = booster.feature_name().index(feature)
    else:
        feature_idx = int(feature)
    values = []
    for tree in booster.inner.models:
        ni = tree.num_internal
        for j in range(ni):
            if tree.split_feature[j] == feature_idx and \
                    not (tree.decision_type[j] & 1):
                values.append(tree.threshold[j])
    if not values:
        raise ValueError(
            "Cannot plot split value histogram, "
            "because feature {} was not used in splitting".format(feature))
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    hist, bin_edges = np.histogram(values, bins=bins or 10)
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2
    width = width_coef * (bin_edges[1] - bin_edges[0])
    ax.bar(centers, hist, width=width, **kwargs)
    if title:
        title = title.replace("@feature@", str(feature)).replace(
            "@index/name@",
            "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: int = 3, orientation: str = "horizontal",
                        **kwargs):
    """reference: plotting.py create_tree_digraph (graphviz-gated)."""
    try:
        import graphviz
    except ImportError as e:
        raise ImportError(
            "You must install graphviz and restart your session to plot "
            "a tree.") from e
    booster = _to_booster(booster)
    tree = booster.inner.models[tree_index]
    feature_names = booster.feature_name()
    graph = graphviz.Digraph(**kwargs)
    graph.attr(rankdir="LR" if orientation == "horizontal" else "TB")

    def add(node, parent=None, decision=None):
        if node < 0:
            leaf = ~node
            name = "leaf%d" % leaf
            label = "leaf %d: %s" % (
                leaf, _float2str(tree.leaf_value[leaf], precision))
            graph.node(name, label=label)
        else:
            name = "split%d" % node
            f = tree.split_feature[node]
            fname = (feature_names[f]
                     if f < len(feature_names) else "Column_%d" % f)
            label = "%s <= %s" % (
                fname, _float2str(tree.threshold[node], precision))
            graph.node(name, label=label)
            add(int(tree.left_child[node]), name, "yes")
            add(int(tree.right_child[node]), name, "no")
        if parent is not None:
            graph.edge(parent, name, decision)

    if tree.num_leaves > 1:
        add(0)
    else:
        add(~0)
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              dpi=None, show_info=None, precision: int = 3,
              orientation: str = "horizontal", **kwargs):
    """reference: plotting.py plot_tree (renders the digraph into a
    matplotlib axes)."""
    plt = _import_matplotlib()
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation, **kwargs)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    import io
    try:
        from PIL import Image
        s = graph.pipe(format="png")
        img = Image.open(io.BytesIO(s))
        ax.imshow(img)
    except Exception as e:
        raise ImportError("plot_tree needs graphviz + PIL") from e
    ax.axis("off")
    return ax
