"""Data-parallel tree learner: rows sharded over the mesh 'data' axis.

TPU-native equivalent of the reference's ``DataParallelTreeLearner``
(reference: src/treelearner/data_parallel_tree_learner.cpp): there, each
rank histograms its row shard, ``Network::ReduceScatter`` sums histograms
across ranks (:185), each rank scans its feature block, and the best split
is agreed via an Allreduce with a max-gain reducer
(SyncUpGlobalBestSplit, parallel_tree_learner.h:190). Here the same
dataflow is expressed as GSPMD: the bin matrix and per-row (grad, hess)
carry a ``P('data', None)`` sharding, the histogram one-hot contraction
reduces over the sharded row axis — XLA inserts the cross-device psum
(the ReduceScatter analogue) — and the split scan runs replicated, which
*is* the "everyone knows the best split" state the reference reaches via
its two collectives. The row partition update is a purely local sharded
elementwise op, like the reference's per-rank ``DataPartition::Split``.

Differences from the single-chip learner (treelearner/serial.py): the
smaller-child row *compaction* (``jnp.nonzero``) is replaced by a masked
full-length histogram pass — compaction is a global reshuffle that would
force cross-device gathers, while a mask rides the existing sharding. The
histogram-subtraction trick still halves the work: only the smaller child
is histogrammed, the sibling comes from parent − smaller.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..io.dataset import BinnedDataset
from ..models.tree import Tree
from ..ops.histogram import build_histogram, subtract_histogram
from ..ops.split import FeatureMeta, SplitParams, find_best_split
from ..treelearner.serial import (GrowState, SplitRecord, _go_left_by_bin,
                                  _record_at, _store_info, _NEG_INF,
                                  apply_split_record, make_root_state,
                                  record_is_valid)
from ..utils import log


def make_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    """1-D device mesh over the data axis (reference analogue: the
    machine list of src/network/linkers_socket.cpp:81)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


class DataParallelTreeLearner:
    """Leaf-wise grower over row-sharded binned data.

    Per split step (one SPMD dispatch):
      partition update (local) -> masked histogram of the smaller child
      (local partials + XLA-inserted psum) -> sibling by subtraction ->
      replicated best-split scan -> argmax over leaves.
    """

    def __init__(self, config, dataset: BinnedDataset, mesh: Mesh,
                 axis: str = "data"):
        bins_host_full = self._init_mesh_common(config, dataset, mesh,
                                                axis)
        N, F = bins_host_full.shape
        if F == 0:
            log.fatal("Cannot train without features")
        self.N, self.F = N, F
        n_dev = mesh.devices.size
        # pad rows to a devices multiple; pad rows carry leaf -1 / gh 0
        self.R = -(-N // n_dev) * n_dev
        pad = np.zeros((self.R - N, F), dtype=bins_host_full.dtype)
        bins_host = np.concatenate([bins_host_full, pad], axis=0)
        self.bins = jax.device_put(
            bins_host, NamedSharding(mesh, P(self.axis, None)))

    def _init_mesh_common(self, config, dataset: BinnedDataset,
                          mesh: Mesh, axis: str):
        """Shared mesh-learner setup (also used by the multi-process
        DistributedDataParallelLearner); returns the per-feature host bin
        matrix (unbundled if the dataset carries EFB bundles)."""
        self.config = config
        self.dataset = dataset
        self.mesh = mesh
        self.axis = axis
        if dataset.bundle is not None:
            # EFB routing is implemented in the serial learner only; the
            # mesh learners unbundle to per-feature columns (memory cost,
            # same semantics)
            log.warning("mesh-parallel learners run EFB-bundled datasets "
                        "unbundled")
            bins_host_full = dataset.feature_bins()
        else:
            bins_host_full = dataset.bins
        # power-of-two histogram width (see SerialTreeLearner: canonical
        # shapes share compiled variants across datasets)
        from ..utils import next_pow2
        self.B = next_pow2(max(int(dataset.max_num_bin), 2))
        self.L = int(config.num_leaves)
        self.max_depth = int(config.max_depth)
        self._hist_slots = self.L
        self.row_sharding = NamedSharding(mesh, P(axis))
        self.rep_sharding = NamedSharding(mesh, P())
        # histograms: replicated after the cross-row psum (the
        # feature-parallel subclass keeps them feature-sharded instead)
        self.hist_sharding = self.rep_sharding
        self.gh_sharding = NamedSharding(mesh, P(axis, None))
        self.meta = jax.device_put(
            FeatureMeta.from_dataset(dataset,
                                     int(config.max_cat_to_onehot)),
            self.rep_sharding)
        self.params = jax.device_put(SplitParams.from_config(config),
                                     self.rep_sharding)
        self._ff_rng = np.random.RandomState(config.feature_fraction_seed)
        from ..ops.histogram import resolve_hist_impl
        self._hist_impl = resolve_hist_impl(
            getattr(config, "hist_backend", "auto"),
            bool(getattr(config, "tpu_use_f64_hist", False)))
        self._has_cat = bool(
            np.asarray(self.meta.is_categorical).any())
        self._root_fn = None
        self._step_fn = None
        if getattr(config, "extra_trees", False):
            log.warning("extra_trees is only implemented in the serial "
                        "(single-chip) learner; the mesh-parallel learners "
                        "run full greedy threshold scans")
        # serial-learner-only features: warn LOUDLY instead of silently
        # ignoring (these knobs would otherwise corrupt experiments)
        if (config.cegb_tradeoff < 1.0 or config.cegb_penalty_split > 0.0
                or config.cegb_penalty_feature_coupled
                or config.cegb_penalty_feature_lazy):
            log.warning("CEGB (cegb_*) is only implemented in the serial "
                        "learner; IGNORED by mesh-parallel learners")
        if config.monotone_penalty != 0.0:
            log.warning("monotone_penalty is only implemented in the "
                        "serial learner; IGNORED here")
        if (config.monotone_constraints_method != "basic"
                and dataset.monotone_constraints is not None):
            log.warning("monotone_constraints_method=%s degrades to "
                        "'basic' in mesh-parallel learners"
                        % config.monotone_constraints_method)
        return bins_host_full

    # ------------------------------------------------------------------
    def _sample_features(self) -> jnp.ndarray:
        ff = float(self.config.feature_fraction)
        mask = np.ones(self.F, dtype=bool)
        if 0.0 < ff < 1.0:
            k = max(1, int(round(self.F * ff)))
            mask[:] = False
            mask[self._ff_rng.choice(self.F, k, replace=False)] = True
        return jax.device_put(jnp.asarray(mask), self.rep_sharding)

    # ------------------------------------------------------------------
    def _initial_partition(self, gh):
        """Root row→leaf vector: rows 0, pad rows -1. Subclasses with a
        different pad layout (per-process interleaved pads in the
        multi-process learner) override this."""
        leaf_of_row = jnp.concatenate([
            jnp.zeros(self.N, dtype=jnp.int32),
            jnp.full((self.R - self.N,), -1, dtype=jnp.int32)])
        return jax.lax.with_sharding_constraint(leaf_of_row,
                                                self.row_sharding)

    def _root_impl(self, bins, gh, feature_mask, children_allowed):
        hist = build_histogram(bins, gh, self.B, pallas_ok=False,
                               hist_impl=self._hist_impl)
        hist = jax.lax.with_sharding_constraint(hist, self.hist_sharding)
        sums = jnp.sum(gh, axis=0)
        from ..ops.split import calculate_leaf_output
        parent_out = calculate_leaf_output(sums[0], sums[1], self.params)
        info = find_best_split(hist, sums[0], sums[1], sums[2], sums[3],
                               self.meta, self.params, feature_mask,
                               parent_output=parent_out,
                               has_categorical=self._has_cat)
        leaf_of_row = self._initial_partition(gh)
        state = make_root_state(gh, hist, leaf_of_row, info, self.L,
                                self.F, self.B, children_allowed,
                                hist_slots=self._hist_slots)
        return state, _record_at(state, 0)

    def _step_impl(self, bins, state: GrowState, leaf, new_leaf,
                   children_allowed, feature_mask):
        meta, params, B = self.meta, self.params, self.B
        f = state.feature[leaf]
        tbin = state.threshold_bin[leaf]
        dl = state.default_left[leaf]
        col = jnp.take(bins, f, axis=1).astype(jnp.int32)
        gl = _go_left_by_bin(col, tbin, dl, meta.missing_type[f],
                             meta.num_bin[f] - 1, meta.zero_bin[f],
                             state.is_categorical[leaf],
                             state.cat_mask[leaf])
        on_leaf = state.leaf_of_row == leaf
        leaf_of_row = jnp.where(on_leaf & ~gl, new_leaf, state.leaf_of_row)
        leaf_of_row = jax.lax.with_sharding_constraint(
            leaf_of_row, self.row_sharding)

        ltc, rtc = (state.left_total_count[leaf],
                    state.right_total_count[leaf])
        smaller_is_left = ltc <= rtc
        (hist_left, hist_right, mask_left,
         mask_right) = self._children_histograms(
            bins, state, leaf, new_leaf, leaf_of_row, smaller_is_left,
            feature_mask)
        hists = self._update_hist_store(state, leaf, new_leaf, hist_left,
                                        hist_right)

        lc, rc = state.left_count[leaf], state.right_count[leaf]
        left_info = find_best_split(
            hist_left, state.left_sum_grad[leaf],
            state.left_sum_hess[leaf], lc, ltc, meta, params, mask_left,
            state.cand_left_min[leaf], state.cand_left_max[leaf],
            parent_output=state.left_output[leaf],
            has_categorical=self._has_cat)
        right_info = find_best_split(
            hist_right, state.right_sum_grad[leaf],
            state.right_sum_hess[leaf], rc, rtc, meta, params, mask_right,
            state.cand_right_min[leaf], state.cand_right_max[leaf],
            parent_output=state.right_output[leaf],
            has_categorical=self._has_cat)

        state = state._replace(leaf_of_row=leaf_of_row, hists=hists)
        state = _store_info(state, leaf, left_info, children_allowed)
        state = _store_info(state, new_leaf, right_info, children_allowed)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best)

    def _children_histograms(self, bins, state, leaf, new_leaf,
                             leaf_of_row, smaller_is_left, feature_mask):
        """Cross-device-summed child histograms + the per-child scan
        masks. Base learner: masked histogram of the smaller child over
        the full sharded row space (the analogue of the reference ranks
        histogramming their local leaf rows then ReduceScatter-summing,
        data_parallel_tree_learner.cpp:185), sibling by subtraction.
        Voting-parallel overrides this with the reduced-comm vote."""
        small_id = jnp.where(smaller_is_left, leaf, new_leaf)
        small_mask = (leaf_of_row == small_id).astype(jnp.float32)
        hist_small = build_histogram(bins, state.gh * small_mask[:, None],
                                     self.B, pallas_ok=False,
                                     hist_impl=self._hist_impl)
        hist_small = jax.lax.with_sharding_constraint(
            hist_small, self.hist_sharding)
        hist_large = subtract_histogram(state.hists[leaf], hist_small)
        hist_left = jnp.where(smaller_is_left, hist_small, hist_large)
        hist_right = jnp.where(smaller_is_left, hist_large, hist_small)
        return hist_left, hist_right, feature_mask, feature_mask

    def _update_hist_store(self, state, leaf, new_leaf, hist_left,
                           hist_right):
        """Per-leaf histogram pool update (the subtraction trick reads
        these; the voting learner overrides this to skip the store)."""
        return state.hists.at[leaf].set(hist_left) \
                          .at[new_leaf].set(hist_right)

    # ------------------------------------------------------------------
    def _ensure_compiled(self):
        if self._root_fn is None:
            self._root_fn = jax.jit(self._root_impl)
            self._step_fn = jax.jit(self._step_impl, donate_argnums=(1,))

    def _splittable(self, depth: int) -> bool:
        return self.max_depth <= 0 or depth < self.max_depth

    def _make_gh(self, grad, hess, bag) -> jnp.ndarray:
        """[N] grad/hess (+bag) → padded sharded [R, 4] gh matrix."""
        pad_n = self.R - self.N
        ind = jnp.ones(self.N, dtype=jnp.float32) if bag is None else bag
        gh = jnp.stack([grad * ind, hess * ind, ind,
                        jnp.ones(self.N, dtype=jnp.float32)], axis=1)
        if pad_n:
            gh = jnp.concatenate(
                [gh, jnp.zeros((pad_n, 4), dtype=jnp.float32)], axis=0)
        return jax.device_put(gh, self.gh_sharding)

    def _finalize_partition(self, leaf_of_row):
        return leaf_of_row[:self.N]

    def train(self, grad: jnp.ndarray, hess: jnp.ndarray,
              bag: Optional[jnp.ndarray] = None) -> Tuple[Tree, jnp.ndarray]:
        """Grow one tree over the sharded dataset. Same contract as
        SerialTreeLearner.train (treelearner/serial.py)."""
        self._ensure_compiled()
        gh = self._make_gh(grad, hess, bag)
        feature_mask = self._sample_features()

        tree = Tree(self.L)
        state, rec = self._root_fn(self.bins, gh, feature_mask,
                                   self._splittable(0))
        pending = jax.device_get(rec)
        for k in range(1, self.L):
            if not record_is_valid(pending):
                break
            leaf = int(pending.leaf)
            apply_split_record(tree, self.dataset, pending)
            children_allowed = self._splittable(int(tree.leaf_depth[leaf]))
            state, rec = self._step_fn(
                self.bins, state, jnp.int32(leaf), jnp.int32(k),
                jnp.asarray(children_allowed), feature_mask)
            pending = jax.device_get(rec)
        return tree, self._finalize_partition(state.leaf_of_row)
