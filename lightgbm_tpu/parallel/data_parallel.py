"""Data-parallel tree learner: rows sharded over the mesh 'data' axis.

TPU-native equivalent of the reference's ``DataParallelTreeLearner``
(reference: src/treelearner/data_parallel_tree_learner.cpp): there, each
rank histograms its row shard, ``Network::ReduceScatter`` sums histograms
across ranks (:185), each rank scans its feature block, and the best split
is agreed via an Allreduce with a max-gain reducer
(SyncUpGlobalBestSplit, parallel_tree_learner.h:190). Here the same
dataflow is expressed as GSPMD: the bin matrix and per-row (grad, hess)
carry a ``P('data', None)`` sharding, the histogram one-hot contraction
reduces over the sharded row axis — XLA inserts the cross-device psum
(the ReduceScatter analogue) — and the split scan runs replicated, which
*is* the "everyone knows the best split" state the reference reaches via
its two collectives. The row partition update is a purely local sharded
elementwise op, like the reference's per-rank ``DataPartition::Split``.

Two departures from the single-chip learner (treelearner/serial.py):

- the smaller-child row *compaction* (``jnp.nonzero``) is replaced by a
  masked full-length histogram pass — compaction is a global reshuffle
  that would force cross-device gathers, while a mask rides the existing
  sharding. The histogram-subtraction trick still halves the work: only
  the smaller child is histogrammed, the sibling comes from
  parent − smaller.
- the whole tree grows in ONE device dispatch: a ``lax.while_loop``
  argmaxes the next leaf, applies the split, and scans both children,
  writing each winning split into a [L-1] record buffer that the host
  reads back once per tree. (The reference syncs rank↔rank per split;
  a per-split host round-trip through a TPU tunnel costs ~27 ms, which
  at 255 leaves would dominate training — measured round 3.) Because
  there is no data-dependent gather size, the loop needs no host input
  at all, unlike the serial learner's bucketed batching. Features whose
  per-split host state steers the scan (CEGB penalties, intermediate
  monotone bounds, per-node feature masks) fall back to a stepwise
  host loop, exactly like the serial learner — via the shared drivers
  in treelearner/capabilities.py.

EFB stays *bundled* across the mesh (reference: bundles are built before
ReduceScatter, src/io/dataset.cpp:107 + data_parallel_tree_learner.cpp:185):
the sharded [N, G] bundle matrix is histogrammed locally, the [G, Bg, 4]
bundle histogram crosses devices (comm O(G·Bg), not O(F·B)), and
``unpack_bundle_histogram`` runs on the replicated side.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..io.dataset import BinnedDataset
from ..models.tree import Tree
from ..obs import compile as obs_compile
from ..obs.registry import registry as obs
from ..ops.histogram import (build_histogram, mask_gh,
                             subtract_histogram,
                             unpack_bundle_histogram)
from ..ops.quantize import dequantize_sums, sum_gh
from ..ops.split import (FeatureMeta, SplitParams, calculate_leaf_output,
                         find_best_split)
from ..treelearner.capabilities import (CapabilityMixin, train_cegb,
                                        train_monotone, train_stepwise)
from ..treelearner.serial import (GrowState, SplitRecord, _cegb_penalty,
                                  _empty_records, _finish_split,
                                  _go_left_by_bin, _maybe_rand_bins,
                                  _partition_col, _record_at, _store_info,
                                  apply_split_record, build_bundle_tables,
                                  make_root_state, rec_valid,
                                  record_is_valid)
from ..utils import log


def make_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    """1-D device mesh over the data axis (reference analogue: the
    machine list of src/network/linkers_socket.cpp:81)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


class DataParallelTreeLearner(CapabilityMixin):
    """Leaf-wise grower over row-sharded binned data.

    One device dispatch grows the whole tree:
      while splits remain: argmax over leaf gains -> partition update
      (local) -> masked histogram of the smaller child (local partials +
      XLA-inserted psum) -> sibling by subtraction -> replicated
      best-split scan -> record written to the read-back buffer.
    """

    # feature-/voting-parallel subclasses unbundle instead (their comm
    # patterns don't reduce over the full [F, B] histogram)
    _supports_bundles = True

    def __init__(self, config, dataset: BinnedDataset, mesh: Mesh,
                 axis: str = "data"):
        cols_host = self._init_mesh_common(config, dataset, mesh, axis)
        N, C = cols_host.shape
        if self.F == 0:
            log.fatal("Cannot train without features")
        self.N = N
        n_dev = mesh.devices.size
        # pad rows to a devices multiple; pad rows carry leaf -1 / gh 0.
        # Shards are materialized one at a time through
        # make_array_from_callback — a host-side concatenate of the full
        # padded matrix would double peak host memory at Higgs scale
        self.R = -(-N // n_dev) * n_dev
        sharding = NamedSharding(mesh, P(self.axis, None))

        def _shard(index):
            rs = index[0]
            start = rs.start or 0
            stop = rs.stop if rs.stop is not None else self.R
            avail = max(0, min(N, stop) - start)
            if avail == stop - start:
                return cols_host[start:stop]
            shard = np.zeros((stop - start, C), dtype=cols_host.dtype)
            if avail > 0:
                shard[:avail] = cols_host[start:start + avail]
            return shard

        with obs.scope("io::stage_bins_device"):
            self.bins = jax.make_array_from_callback(
                (self.R, C), sharding, _shard)
        self._init_cegb(config)
        self._init_monotone(config)

    def _init_mesh_common(self, config, dataset: BinnedDataset,
                          mesh: Mesh, axis: str):
        """Shared mesh-learner setup (also used by the multi-process
        DistributedDataParallelLearner); returns the host bin-column
        matrix — the EFB bundle matrix when bundled, per-feature
        otherwise."""
        self.config = config
        self.dataset = dataset
        self.mesh = mesh
        self.axis = axis
        self.F = dataset.num_features
        self.Fp = self.F  # masks/penalty vectors carry no padding here
        self._bundled = (dataset.bundle is not None
                         and self._supports_bundles)
        if dataset.bundle is not None and not self._bundled:
            cols_host = dataset.feature_bins()
        else:
            cols_host = dataset.bins
        # power-of-two histogram width (see SerialTreeLearner: canonical
        # shapes share compiled variants across datasets)
        from ..utils import next_pow2
        self.B = next_pow2(max(int(dataset.max_num_bin), 2))
        if self._bundled:
            self.Bg = next_pow2(max(dataset.bundle.num_bundled_bins, 2))
            self._btab = build_bundle_tables(
                dataset, self.F, dataset.bundle.num_groups, self.B,
                self.Bg)
        else:
            self.Bg = 0
            self._btab = jnp.int32(0)
        self.L = int(config.num_leaves)
        self.max_depth = int(config.max_depth)
        self._hist_slots = self.L
        self.row_sharding = NamedSharding(mesh, P(axis))
        self.rep_sharding = NamedSharding(mesh, P())
        # histograms: replicated after the cross-row psum (the
        # feature-parallel subclass keeps them feature-sharded instead)
        self.hist_sharding = self.rep_sharding
        self.gh_sharding = NamedSharding(mesh, P(axis, None))
        self.meta = jax.device_put(
            FeatureMeta.from_dataset(dataset,
                                     int(config.max_cat_to_onehot)),
            self.rep_sharding)
        self.params = jax.device_put(SplitParams.from_config(config),
                                     self.rep_sharding)
        self._ff_rng = np.random.RandomState(config.feature_fraction_seed)
        from ..ops.histogram import resolve_hist_impl
        qbits = (int(getattr(config, "quant_grad_bits", 8))
                 if getattr(config, "use_quantized_grad", False) else 0)
        self._hist_impl = resolve_hist_impl(
            getattr(config, "hist_backend", "auto"),
            bool(getattr(config, "tpu_use_f64_hist", False)), qbits)
        self._init_quantization(self._hist_impl[2], config,
                                cols_host.shape[0])
        self._has_cat = bool(
            np.asarray(self.meta.is_categorical).any())
        self._extra_trees = bool(config.extra_trees)
        self._extra_seed = int(config.extra_seed)
        self._tree_idx = 0
        self._resolve_constraints()
        self._forced = None
        if config.forcedsplits_filename:
            log.warning("forcedsplits_filename is only implemented in "
                        "the serial (single-chip) learner; IGNORED by "
                        "mesh-parallel learners")
        self._root_fn = None
        self._tree_fn = None
        self._step_fn = None
        self._cegb_root_fn = None
        self._mono_step_fn = None
        self._mono_root_fn = None
        self._adv_rescan_fn = None
        self._many_fn = None
        self._many_multi_fn = None
        self._many_grad_fn = None
        self._many_sample = None
        return cols_host

    def _make_cegb_fetched(self, rows: int) -> jnp.ndarray:
        """Row-sharded lazy-fetched matrix (global-view creation works
        across processes for the multi-process subclass too)."""
        sh = (NamedSharding(self.mesh, P(self.axis, None)) if rows > 1
              else self.rep_sharding)
        # jaxlint: disable=JLT003 -- one-shot sharded-zeros allocation
        # at CEGB setup (out_shardings is the point); a jit_trace entry
        # per row-shape would be noise, and no dispatch ever repeats
        return jax.jit(lambda: jnp.zeros((rows, self.Fp),
                                         dtype=jnp.float32),
                       out_shardings=sh)()

    # ------------------------------------------------------------------
    def _sample_features(self) -> jnp.ndarray:
        ff = float(self.config.feature_fraction)
        mask = np.ones(self.F, dtype=bool)
        if 0.0 < ff < 1.0:
            k = max(1, int(round(self.F * ff)))
            mask[:] = False
            mask[self._ff_rng.choice(self.F, k, replace=False)] = True
        if self._constraint_groups is not None:
            # root scan may only use features inside some constraint
            # group (reference: ColSampler::SetUsedFeatureByNode)
            allowed = np.zeros(self.F, dtype=bool)
            for grp in self._constraint_groups:
                allowed[list(grp)] = True
            mask &= allowed
        return jax.device_put(jnp.asarray(mask), self.rep_sharding)

    # ------------------------------------------------------------------
    def _initial_partition(self, gh):
        """Root row→leaf vector: rows 0, pad rows -1. Subclasses with a
        different pad layout (per-process interleaved pads in the
        multi-process learner) override this."""
        leaf_of_row = jnp.concatenate([
            jnp.zeros(self.N, dtype=jnp.int32),
            jnp.full((self.R - self.N,), -1, dtype=jnp.int32)])
        return jax.lax.with_sharding_constraint(leaf_of_row,
                                                self.row_sharding)

    def _mesh_hist(self, bins, gh, totals):
        """Globally-summed per-feature [F, B, 4] histogram. Bundled:
        only the [G, Bg, 4] bundle histogram crosses devices, then the
        per-feature unpack runs replicated (``totals`` reconstructs the
        zero-bin rows of bundled features, io/efb.py). Quantized mode:
        the local partials are int32 — the XLA-inserted cross-device
        psum then moves HALF the bytes of the f32 histogram (and a
        quarter on int8 gh rows vs f32 through the local pass).

        pallas_ok only on a 1-device mesh: pallas_call has no SPMD
        partitioning rule, so with real sharding GSPMD would all-gather
        the bins; unsharded, the kernel is safe (and is the fast path
        for single-chip tree_learner=data runs)."""
        p_ok = self.mesh.devices.size == 1
        if jnp.issubdtype(gh.dtype, jnp.integer):
            # callers hold dequantized f32 record totals; the bundled
            # zero-bin fix needs the exact int sums of THESE (already
            # masked) rows
            totals = sum_gh(gh)
        if not self._bundled:
            h = build_histogram(bins, gh, self.B, pallas_ok=p_ok,
                                hist_impl=self._hist_impl)
            # named so the XLA-inserted cross-device reduce is
            # attributable in device traces; the feature-parallel
            # subclass keeps histograms sharded (no psum crosses here),
            # so its boundary gets a distinct name
            name = ("obs_psum_histogram"
                    if self.hist_sharding == self.rep_sharding
                    else "obs_hist_feature_sharded")
            with jax.named_scope(name):
                return jax.lax.with_sharding_constraint(
                    h, self.hist_sharding)
        bh = build_histogram(bins, gh, self.Bg, pallas_ok=p_ok,
                             hist_impl=self._hist_impl)
        with jax.named_scope("obs_psum_bundle_histogram"):
            bh = jax.lax.with_sharding_constraint(bh, self.rep_sharding)
        return unpack_bundle_histogram(bh, self._btab.gidx_g,
                                       self._btab.gidx_b,
                                       self._btab.zero_fix,
                                       self.meta.zero_bin, totals)

    def _root_impl_opts(self, bins, gh, feature_mask, rand_seed,
                        extra_trees: bool, qscale):
        sums_raw = sum_gh(gh)
        hist = self._mesh_hist(bins, gh, sums_raw)
        sums = dequantize_sums(sums_raw, qscale)
        parent_out = calculate_leaf_output(sums[0], sums[1], self.params)
        info = find_best_split(
            hist, sums[0], sums[1], sums[2], sums[3], self.meta,
            self.params, feature_mask, parent_output=parent_out,
            rand_bins=_maybe_rand_bins(extra_trees, rand_seed, 0,
                                       self.meta, self.params),
            leaf_depth=jnp.int32(0), has_categorical=self._has_cat,
            hist_scale=qscale)
        leaf_of_row = self._initial_partition(gh)
        state = make_root_state(gh, hist, leaf_of_row, info, self.L,
                                self.F, self.B, self._splittable(0),
                                hist_slots=self._hist_slots)
        return state, _record_at(state, 0)

    def _root_impl(self, bins, gh, feature_mask, rand_seed, qscale):
        return self._root_impl_opts(bins, gh, feature_mask, rand_seed,
                                    self._extra_trees, qscale)

    def _mesh_split_body(self, bins, state: GrowState, rec: SplitRecord,
                         leaf, new_leaf, valid, mask_left, mask_right,
                         rand_seed=0, extra_trees=None, pen_left=None,
                         pen_right=None, qscale=None):
        """Apply one chosen split and scan both children. ``valid``
        guards every state write (loop steps after the no-more-splits
        point must leave state untouched). The tail — depth gating, the
        two child scans, the candidate stores — is the serial learner's
        _finish_split; only the child-histogram computation differs."""
        meta = self.meta
        f = jnp.maximum(rec.feature, 0)
        col = _partition_col(bins, f, meta, self._btab, self._bundled)
        gl = _go_left_by_bin(col, rec.threshold_bin, rec.default_left,
                             meta.missing_type[f], meta.num_bin[f] - 1,
                             meta.zero_bin[f], rec.is_categorical,
                             rec.cat_mask)
        on_leaf = state.leaf_of_row == leaf
        leaf_of_row = jnp.where(valid & on_leaf & ~gl, new_leaf,
                                state.leaf_of_row)
        leaf_of_row = jax.lax.with_sharding_constraint(
            leaf_of_row, self.row_sharding)

        smaller_is_left = rec.left_total_count <= rec.right_total_count
        (hist_left, hist_right, mask_left,
         mask_right) = self._children_histograms(
            bins, state, rec, leaf, new_leaf, leaf_of_row,
            smaller_is_left, mask_left, mask_right, qscale)
        hists = self._update_hist_store(state, leaf, new_leaf, hist_left,
                                        hist_right, valid)
        state = state._replace(leaf_of_row=leaf_of_row, hists=hists)
        return _finish_split(
            state, rec, leaf, new_leaf, valid, hist_left, hist_right,
            mask_left, mask_right, meta, self.params,
            max_depth=self.max_depth,
            extra_trees=(self._extra_trees if extra_trees is None
                         else extra_trees),
            has_cat=self._has_cat, rand_seed=rand_seed,
            pen_left=pen_left, pen_right=pen_right, qscale=qscale)

    def _children_histograms(self, bins, state, rec, leaf, new_leaf,
                             leaf_of_row, smaller_is_left, mask_left,
                             mask_right, qscale=None):
        """Cross-device-summed child histograms + the per-child scan
        masks. Base learner: masked histogram of the smaller child over
        the full sharded row space (the analogue of the reference ranks
        histogramming their local leaf rows then ReduceScatter-summing,
        data_parallel_tree_learner.cpp:185), sibling by subtraction —
        BIT-EXACT in quantized-integer mode. Voting-parallel overrides
        this with the reduced-comm vote."""
        small_id = jnp.where(smaller_is_left, leaf, new_leaf)
        small_sel = leaf_of_row == small_id
        small_totals = jnp.stack([
            jnp.where(smaller_is_left, rec.left_sum_grad,
                      rec.right_sum_grad),
            jnp.where(smaller_is_left, rec.left_sum_hess,
                      rec.right_sum_hess),
            jnp.where(smaller_is_left, rec.left_count, rec.right_count),
            jnp.where(smaller_is_left, rec.left_total_count,
                      rec.right_total_count)])
        if self.mesh.devices.size == 1:
            # single-chip fast path: compact the child's rows first so
            # histogram cost tracks the child size, not the full row
            # space (the reference's DataPartition + per-leaf iterators,
            # data_partition.hpp:21; the CUDA learner's equivalent win
            # is cuda_data_partition's leaf-indexed row sets)
            hist_small = self._compact_child_hist(
                bins, state.gh, small_sel, small_totals)
        else:
            # dtype-preserving mask (an f32 multiply would de-quantize
            # integer gh rows)
            hist_small = self._mesh_hist(
                bins, mask_gh(state.gh, small_sel), small_totals)
        hist_large = subtract_histogram(state.hists[leaf], hist_small)
        hist_left = jnp.where(smaller_is_left, hist_small, hist_large)
        hist_right = jnp.where(smaller_is_left, hist_large, hist_small)
        return hist_left, hist_right, mask_left, mask_right

    def _compact_child_hist(self, bins, gh, mask, totals):
        """Gather the smaller child's rows into a static power-ladder
        bucket (``lax.switch`` over compiled sizes) and histogram only
        those. A leaf-wise tree's total smaller-child row count is
        ~N·log2(L)/2, so this cuts per-tree histogram work by ~50x at
        255 leaves vs masked full-row scans — the single-chip analogue
        of the reference's per-leaf row iterators
        (data_partition.hpp:119 GetIndexOnLeaf). The scatter/gather
        compaction itself is O(R) bandwidth, far below the histogram's
        O(S·F) compute. Sharded meshes keep the masked full-row scan
        (compaction across shards would need an all-to-all; each shard
        already scans only its local rows)."""
        R = bins.shape[0]
        sizes = []
        s = -(-R // 2)
        while s > 16384:
            sizes.append(s)
            s = -(-s // 4)
        sizes.append(s)
        count = totals[3].astype(jnp.int32)     # rows on the leaf
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        rows = jnp.arange(R, dtype=jnp.int32)

        def make_branch(S):
            def branch(_):
                idx = jnp.zeros((S,), dtype=jnp.int32)
                idx = idx.at[jnp.where(mask, pos, S)].set(rows,
                                                          mode="drop")
                keep = (jnp.arange(S, dtype=jnp.int32)
                        < count)[:, None]
                gh_keep = jnp.where(keep, gh[idx],
                                    jnp.zeros((), dtype=gh.dtype))
                return self._mesh_hist(bins[idx], gh_keep, totals)
            return branch

        k = jnp.clip(
            jnp.sum(jnp.asarray(sizes, dtype=jnp.int32) >= count) - 1,
            0, len(sizes) - 1)
        return jax.lax.switch(k, [make_branch(S) for S in sizes], 0)

    def _update_hist_store(self, state, leaf, new_leaf, hist_left,
                           hist_right, valid):
        """Per-leaf histogram pool update (the subtraction trick reads
        these; the voting learner overrides this to skip the store)."""
        return state.hists \
            .at[leaf].set(jnp.where(valid, hist_left,
                                    state.hists[leaf])) \
            .at[new_leaf].set(jnp.where(valid, hist_right,
                                        state.hists[new_leaf]))

    # ------------------------------------------------------------------
    def _tree_impl(self, bins, state: GrowState, feature_mask, rand_seed,
                   qscale):
        """Grow the whole tree in one dispatch: while splits remain, the
        device argmaxes the next leaf (the argmax the reference reaches
        via SyncUpGlobalBestSplit), applies it, and appends the record.
        Exits as soon as no positive-gain candidate is left, so a short
        tree costs no wasted iterations."""
        kb = self.L - 1

        def cond(carry):
            i, _, _, cont = carry
            return cont & (i < kb)

        def body(carry):
            i, state, recs, _ = carry
            best = jnp.argmax(state.gain).astype(jnp.int32)
            rec = _record_at(state, best)
            valid = rec_valid(rec)
            recs = jax.tree_util.tree_map(
                lambda buf, v: buf.at[i].set(v), recs, rec)
            new_leaf = (i + 1).astype(jnp.int32)
            state = self._mesh_split_body(bins, state, rec, best,
                                          new_leaf, valid, feature_mask,
                                          feature_mask,
                                          rand_seed=rand_seed,
                                          qscale=qscale)
            return i + 1, state, recs, valid

        carry = (jnp.int32(0), state, _empty_records(kb, self.B),
                 jnp.asarray(True))
        _, state, recs, _ = jax.lax.while_loop(cond, body, carry)
        return state, recs

    def _step_impl(self, bins, state: GrowState, leaf, new_leaf,
                   mask_left, mask_right, rand_seed, qscale):
        """Single split step with a host-chosen leaf — the stepwise path
        used when per-split host state steers the scan (per-node feature
        masks; CEGB and intermediate monotone have their own variants)."""
        rec = _record_at(state, leaf)
        valid = rec_valid(rec)
        state = self._mesh_split_body(bins, state, rec, leaf, new_leaf,
                                      valid, mask_left, mask_right,
                                      rand_seed=rand_seed, qscale=qscale)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best)

    # --- CEGB (reference: cost_effective_gradient_boosting.hpp) -------
    def _cegb_root_impl(self, bins, gh, feature_mask, used, fetched,
                        qscale):
        sums_raw = sum_gh(gh)
        hist = self._mesh_hist(bins, gh, sums_raw)
        sums = dequantize_sums(sums_raw, qscale)
        parent_out = calculate_leaf_output(sums[0], sums[1], self.params)
        leaf_of_row = self._initial_partition(gh)
        if self._cegb_has_lazy:
            in_rows = (leaf_of_row >= 0).astype(jnp.float32)
            unfetched = jnp.einsum("r,rf->f", in_rows, 1.0 - fetched)
            lazy = self._cegb_lazy
        else:
            unfetched, lazy = None, None
        pen = _cegb_penalty(self.params, sums[3], used,
                            self._cegb_coupled, unfetched, lazy)
        info = find_best_split(
            hist, sums[0], sums[1], sums[2], sums[3], self.meta,
            self.params, feature_mask, parent_output=parent_out,
            gain_penalty=pen, has_categorical=self._has_cat,
            hist_scale=qscale)
        state = make_root_state(gh, hist, leaf_of_row, info, self.L,
                                self.F, self.B, self._splittable(0),
                                hist_slots=self._hist_slots)
        return state, _record_at(state, 0)

    def _cegb_step_impl(self, bins, state, leaf, new_leaf, feature_mask,
                        used, fetched, qscale):
        """Mesh CEGB step (mirrors serial.py _cegb_step_fn_cached; the
        unfetched row sums reduce over the sharded row axis — XLA
        inserts the psum)."""
        rec = _record_at(state, leaf)
        f = jnp.maximum(rec.feature, 0)
        used2 = used.at[f].set(True)
        on_leaf = state.leaf_of_row == leaf
        if self._cegb_has_lazy:
            fetched2 = jnp.maximum(
                fetched,
                on_leaf.astype(fetched.dtype)[:, None]
                * jax.nn.one_hot(f, fetched.shape[1],
                                 dtype=fetched.dtype))
            col = _partition_col(bins, f, self.meta, self._btab,
                                 self._bundled)
            gl = _go_left_by_bin(col, rec.threshold_bin, rec.default_left,
                                 self.meta.missing_type[f],
                                 self.meta.num_bin[f] - 1,
                                 self.meta.zero_bin[f],
                                 rec.is_categorical, rec.cat_mask)
            unf = 1.0 - fetched2
            unf_left = jnp.einsum(
                "r,rf->f", (on_leaf & gl).astype(jnp.float32), unf)
            unf_right = jnp.einsum(
                "r,rf->f", (on_leaf & ~gl).astype(jnp.float32), unf)
            lazy = self._cegb_lazy
        else:
            fetched2 = fetched
            unf_left = unf_right = lazy = None
        pen_l = _cegb_penalty(self.params, rec.left_total_count, used2,
                              self._cegb_coupled, unf_left, lazy)
        pen_r = _cegb_penalty(self.params, rec.right_total_count, used2,
                              self._cegb_coupled, unf_right, lazy)
        valid = rec_valid(rec)
        state = self._mesh_split_body(bins, state, rec, leaf, new_leaf,
                                      valid, feature_mask, feature_mask,
                                      extra_trees=False, pen_left=pen_l,
                                      pen_right=pen_r, qscale=qscale)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best), used2, fetched2

    # --- intermediate monotone (reference: monotone_constraints.hpp) --
    def _mono_step_impl(self, bins, state, leaf, new_leaf, feature_mask,
                        lmin, lmax, rmin, rmax, qscale):
        """The children's output bounds come from the host tracker
        (sibling-output based, monotone_constraints.hpp:543) instead of
        the mid-point rule baked into the stored candidate."""
        state = state._replace(
            cand_left_min=state.cand_left_min.at[leaf].set(lmin),
            cand_left_max=state.cand_left_max.at[leaf].set(lmax),
            cand_right_min=state.cand_right_min.at[leaf].set(rmin),
            cand_right_max=state.cand_right_max.at[leaf].set(rmax))
        rec = _record_at(state, leaf)
        valid = rec_valid(rec)
        state = self._mesh_split_body(bins, state, rec, leaf, new_leaf,
                                      valid, feature_mask, feature_mask,
                                      extra_trees=False, qscale=qscale)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best), state.gain

    def _rescan_impl(self, state, leaf, sg, sh, c, tc, vmin, vmax, depth,
                     allowed, feature_mask, qscale):
        """Recompute one leaf's candidate from its stored (replicated)
        histogram under tightened bounds (reference:
        SerialTreeLearner::RecomputeBestSplitForLeaf,
        serial_tree_learner.cpp:800)."""
        hist = state.hists[leaf]
        own = calculate_leaf_output(sg, sh, self.params)
        parent_out = jnp.where(self.params.path_smooth > 1e-10, own, 0.0)
        info = find_best_split(hist, sg, sh, c, tc, self.meta,
                               self.params, feature_mask, vmin, vmax,
                               parent_output=parent_out,
                               leaf_depth=depth,
                               has_categorical=self._has_cat,
                               hist_scale=qscale)
        state = _store_info(state, leaf, info, allowed)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best), state.gain

    def _adv_rescan_impl(self, state, leaf, sg, sh, c, tc, min_c, max_c,
                         depth, allowed, feature_mask, qscale):
        """monotone_constraints_method=advanced candidate scan — the
        per-(feature, bin) constraint arrays (replicated inputs) replace
        the leaf-wide pair (reference: AdvancedLeafConstraints,
        monotone_constraints.hpp:856; serial analogue
        _adv_rescan_fn_cached in treelearner/serial.py)."""
        hist = state.hists[leaf]
        own = calculate_leaf_output(sg, sh, self.params)
        parent_out = jnp.where(self.params.path_smooth > 1e-10, own, 0.0)
        info = find_best_split(hist, sg, sh, c, tc, self.meta,
                               self.params, feature_mask,
                               parent_output=parent_out,
                               leaf_depth=depth,
                               has_categorical=self._has_cat,
                               bound_arrays=(min_c, max_c),
                               hist_scale=qscale)
        state = _store_info(state, leaf, info, allowed)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best), state.gain

    def _adv_scan(self, state, leaf, sums, bound_arrays, depth, allowed,
                  feature_mask):
        if self._adv_rescan_fn is None:
            self._adv_rescan_fn = obs_compile.instrument_jit(
                "mesh.adv_rescan", self._adv_rescan_impl,
                donate_argnums=(0,))
        sg, sh, c, tc = sums
        min_c, max_c = bound_arrays
        return self._adv_rescan_fn(
            state, jnp.int32(leaf), jnp.float32(sg), jnp.float32(sh),
            jnp.float32(c), jnp.float32(tc), jnp.asarray(min_c),
            jnp.asarray(max_c), jnp.int32(depth), jnp.asarray(allowed),
            feature_mask, self._qscale)

    # --- adapter methods for the shared capability drivers ------------
    def _cegb_root(self, gh, feature_mask):
        if self._cegb_root_fn is None:
            self._cegb_root_fn = obs_compile.instrument_jit(
                "mesh.cegb_root", self._cegb_root_impl)
            self._cegb_step_fn = obs_compile.instrument_jit(
                "mesh.cegb_step", self._cegb_step_impl,
                donate_argnums=(1,))
        return self._cegb_root_fn(self.bins, gh, feature_mask,
                                  self._cegb_used, self._cegb_fetched,
                                  self._qscale)

    def _cegb_step(self, state, leaf, k, allowed, feature_mask, smaller):
        state, rec, self._cegb_used, self._cegb_fetched = \
            self._cegb_step_fn(self.bins, state, jnp.int32(leaf),
                               jnp.int32(k), feature_mask,
                               self._cegb_used, self._cegb_fetched,
                               self._qscale)
        return state, rec

    def _mono_root(self, gh, feature_mask, rand_seed):
        # the root scan must be greedy too, not just the step scans
        # (extra_trees is ignored under intermediate monotone — serial
        # learner contract, _mono_root in treelearner/serial.py)
        if self._mono_root_fn is None:
            self._mono_root_fn = obs_compile.instrument_jit(
                "mesh.mono_root",
                lambda b, g, f, r, q: self._root_impl_opts(b, g, f, r,
                                                           False, q))
        return self._mono_root_fn(self.bins, gh, feature_mask,
                                  jnp.int32(rand_seed), self._qscale)

    def _mono_step(self, state, leaf, k, allowed, feature_mask, bounds,
                   smaller):
        if self._mono_step_fn is None:
            self._mono_step_fn = obs_compile.instrument_jit(
                "mesh.mono_step", self._mono_step_impl,
                donate_argnums=(1,))
            self._rescan_fn = obs_compile.instrument_jit(
                "mesh.rescan", self._rescan_impl,
                donate_argnums=(0,))
        return self._mono_step_fn(
            self.bins, state, jnp.int32(leaf), jnp.int32(k), feature_mask,
            jnp.float32(bounds[0]), jnp.float32(bounds[1]),
            jnp.float32(bounds[2]), jnp.float32(bounds[3]),
            self._qscale)

    def _mono_rescan(self, state, leaf, sums, entry, depth, allowed,
                     feature_mask):
        sg, sh, c, tc = sums
        return self._rescan_fn(
            state, jnp.int32(leaf), jnp.float32(sg), jnp.float32(sh),
            jnp.float32(c), jnp.float32(tc), jnp.float32(entry[0]),
            jnp.float32(entry[1]), jnp.int32(depth), jnp.asarray(allowed),
            feature_mask, self._qscale)

    def _node_step(self, state, leaf, k, allowed, mask_left, mask_right,
                   rand_seed, smaller):
        if self._step_fn is None:
            self._step_fn = obs_compile.instrument_jit(
                "mesh.step", self._step_impl,
                donate_argnums=(1,))
        return self._step_fn(self.bins, state, jnp.int32(leaf),
                             jnp.int32(k), mask_left, mask_right,
                             jnp.int32(rand_seed), self._qscale)

    # ------------------------------------------------------------------
    def _ensure_compiled(self):
        if self._root_fn is None:
            self._root_fn = obs_compile.instrument_jit(
                "mesh.root", self._root_impl)
            self._tree_fn = obs_compile.instrument_jit(
                "mesh.tree", self._tree_impl,
                donate_argnums=(1,))

    def _splittable(self, depth: int) -> bool:
        return self.max_depth <= 0 or depth < self.max_depth

    def _make_gh(self, grad, hess, bag) -> jnp.ndarray:
        """[N] grad/hess (+bag) → padded sharded [R, 4] gh matrix."""
        pad_n = self.R - self.N
        ind = jnp.ones(self.N, dtype=jnp.float32) if bag is None else bag
        gh = jnp.stack([grad * ind, hess * ind, ind,
                        jnp.ones(self.N, dtype=jnp.float32)], axis=1)
        if pad_n:
            gh = jnp.concatenate(
                [gh, jnp.zeros((pad_n, 4), dtype=jnp.float32)], axis=0)
        return jax.device_put(gh, self.gh_sharding)

    def _make_gh_quantized(self, grad, hess, bag):
        """Quantized staging: discretize the UNPADDED [N] rows (the
        padding-invariant draw shared with the serial learner,
        capabilities.py _quantize_stage), then pad and shard the int
        rows. Returns (gh int[R, 4] sharded, qscale f32[2] replicated)."""
        ind = jnp.ones(self.N, dtype=jnp.float32) if bag is None else bag
        gh, qscale = self._quantize_stage(grad, hess, ind,
                                          self._tree_idx + 1)
        pad_n = self.R - self.N
        if pad_n:
            gh = jnp.concatenate(
                [gh, jnp.zeros((pad_n, 4), dtype=gh.dtype)], axis=0)
        return (jax.device_put(gh, self.gh_sharding),
                jax.device_put(qscale, self.rep_sharding))

    def _finalize_partition(self, leaf_of_row):
        return leaf_of_row[:self.N]

    def train(self, grad: jnp.ndarray, hess: jnp.ndarray,
              bag: Optional[jnp.ndarray] = None) -> Tuple[Tree, jnp.ndarray]:
        """Grow one tree over the sharded dataset. Same contract as
        SerialTreeLearner.train (treelearner/serial.py). On the default
        path there is exactly one host read-back per tree: the [L-1]
        record buffer."""
        self._ensure_compiled()
        with obs.scope("tree::stage_gh"):
            if self._quantized:
                gh, self._qscale = self._make_gh_quantized(grad, hess,
                                                           bag)
            else:
                gh = self._make_gh(grad, hess, bag)
                self._qscale = self._qs_ones
            obs.watch_ready("tree::stage_gh", gh)
            feature_mask = self._sample_features()

        tree = Tree(self.L)
        self._tree_idx += 1
        rand_seed = jnp.int32(
            (self._extra_seed + 7919 * self._tree_idx) & 0x7FFFFFFF)
        if self._cegb_enabled:
            state = train_cegb(self, tree, gh, feature_mask)
            return tree, self._finalize_partition(state.leaf_of_row)
        if self._mono_tracker is not None:
            state = train_monotone(self, tree, gh, feature_mask,
                                   rand_seed)
            return tree, self._finalize_partition(state.leaf_of_row)
        with obs.scope("tree::root_histogram"):
            state, rec = self._root_fn(self.bins, gh, feature_mask,
                                       rand_seed, self._qscale)
            obs.watch_ready("tree::root_histogram", rec)
        if self._needs_per_node_masks():
            state = train_stepwise(self, tree, state, rec, feature_mask,
                                   rand_seed)
            return tree, self._finalize_partition(state.leaf_of_row)
        # whole-tree dispatch (child histograms + split scans fused);
        # the device_get is the per-tree sync, so the scope covers the
        # real device time
        with obs.scope("tree::split_batches"):
            state, recs = self._tree_fn(self.bins, state, feature_mask,
                                        rand_seed, self._qscale)
            # jaxlint: disable=JLT001 -- THE per-tree sync: the whole
            # tree's split records read back in one hop (scope comment)
            recs_h = jax.device_get(recs)
        with obs.scope("tree::apply_records"):
            for i in range(self.L - 1):
                r = jax.tree_util.tree_map(lambda a: a[i], recs_h)
                if not record_is_valid(r):
                    break
                apply_split_record(tree, self.dataset, r)
        return tree, self._finalize_partition(state.leaf_of_row)

    # --- device-resident multi-iteration batching ---------------------
    # The tunnel to a remote chip charges ~27 ms per dispatch and a full
    # round-trip per host sync; at the reference's Higgs pace
    # (3.84 iters/s) that overhead alone is most of the per-iteration
    # budget. When nothing in the scan needs per-tree host state, T
    # boosting iterations (gradients -> tree growth -> score update)
    # run as ONE lax.scan dispatch with a single [T, L-1] record
    # read-back. The reference's CUDA learner amortizes the same way —
    # whole-loop on device (cuda_single_gpu_tree_learner.cpp:128) — but
    # per tree; the scan extends it across trees.

    def supports_train_many(self) -> bool:
        """True when the split scan needs no per-split or per-tree host
        state (CEGB penalties, monotone trackers, per-node feature
        masks) and no host RNG (feature_fraction redraws a host mask
        per tree). Quantized-gradient mode batches too: the per-tree
        stochastic-rounding key folds in from a scan-carried device
        counter, and the scan's ``alive`` flag freezes the score after
        a stump step — a later redraw can no longer grow a tree the
        host never applies. extra_trees batches under the same alive
        treatment: its per-node rand_bins key on the scanned per-tree
        seed, the exact sequence the looped path derives from
        ``_tree_idx``."""
        return (not self._cegb_enabled
                and self._mono_tracker is None
                and not self._needs_per_node_masks()
                and not (0.0 < float(self.config.feature_fraction) < 1.0))

    def _make_gh_traced(self, grad, hess, ind=None):
        """_make_gh without the device_put (inside jit the sharding is a
        constraint, not a transfer). ``ind`` is the in-bag indicator,
        None for all-rows — the same masked staging the looped
        ``_make_gh`` performs."""
        ones = jnp.ones(self.N, dtype=jnp.float32)
        if ind is None:
            gh = jnp.stack([grad, hess, ones, ones], axis=1)
        else:
            gh = jnp.stack([grad * ind, hess * ind, ind, ones], axis=1)
        if self.R - self.N:
            gh = jnp.concatenate(
                [gh, jnp.zeros((self.R - self.N, 4), dtype=jnp.float32)],
                axis=0)
        return jax.lax.with_sharding_constraint(gh, self.gh_sharding)

    def _make_gh_quantized_traced(self, grad, hess, ind, key):
        """_make_gh_quantized inside the batched scan: the stochastic
        draw runs on the UNPADDED [N] rows with the scan-carried
        fold-in key (bit-identical to the looped path's per-tree
        quantize_gh dispatch), then pads and shards the int rows. The
        barrier pins the quantize output at what is a dispatch
        boundary in the looped path — without it XLA may fuse the
        rounding into the histogram kernels and drift the drawn
        integers."""
        from ..ops.quantize import _quantize_gh
        barrier = jax.lax.optimization_barrier
        if ind is None:
            ind = jnp.ones(self.N, dtype=jnp.float32)
        gh, qscale = barrier(_quantize_gh(grad, hess, ind, key,
                                          self._qmax, self._qdtype))
        if self.R - self.N:
            gh = jnp.concatenate(
                [gh, jnp.zeros((self.R - self.N, 4), dtype=gh.dtype)],
                axis=0)
        return (barrier(jax.lax.with_sharding_constraint(
            gh, self.gh_sharding)), qscale)

    def _leaf_outputs_from_records(self, recs) -> jnp.ndarray:
        """[L] final leaf outputs replayed from the record buffer: step i
        re-homes the split leaf's rows under the same index (left child)
        and creates leaf i+1 (right child), so an in-order scatter of
        (left_output -> rec.leaf, right_output -> i+1) leaves each
        surviving leaf holding the value the host Tree will store."""
        L = self.L

        def body(i, out):
            rec = jax.tree_util.tree_map(lambda a: a[i], recs)
            v = rec_valid(rec)
            out = out.at[jnp.where(v, rec.leaf, L)].set(rec.left_output)
            out = out.at[jnp.where(v, i + 1, L)].set(rec.right_output)
            return out

        out = jnp.zeros(L + 1, dtype=jnp.float32)
        return jax.lax.fori_loop(0, L - 1, body, out)[:L]

    def _grow_one(self, bins, gh, feature_mask, seed, lr, qscale):
        """One tree inside the scan: root + whole-tree loop + leaf-output
        replay. Returns (records, per-row output deltas [N])."""
        barrier = jax.lax.optimization_barrier
        state, _ = self._root_impl(bins, gh, feature_mask, seed, qscale)
        state = barrier(state)
        state, recs = self._tree_impl(bins, state, feature_mask, seed,
                                      qscale)
        state, recs = barrier((state, recs))
        outs = self._leaf_outputs_from_records(recs) * lr
        return recs, outs[state.leaf_of_row[:self.N]]

    def _step_gh(self, grad, hess, ind, qkey, ctr):
        """Per-tree gh staging inside the scan: exact f32 rows, or —
        quantized — advance the scan-carried tree counter and draw
        with its fold-in key (the looped path's ops/quantize.tree_key
        sequence, bit-exact). ``ind`` is the iteration's in-bag
        indicator (None for all rows). Returns (gh, qscale, ctr)."""
        barrier = jax.lax.optimization_barrier
        if qkey is None:
            return (barrier(self._make_gh_traced(grad, hess, ind)),
                    self._qs_ones, ctr)
        ctr = ctr + jnp.uint32(1)
        gh, qscale = self._make_gh_quantized_traced(
            grad, hess, ind, jax.random.fold_in(qkey, ctr))
        return gh, qscale, ctr

    def _apply_sampling(self, iter_idx, grad, hess):
        """The sample strategy's draw inside the scan
        (``apply_traced``): bagging indicators / GOSS rescales keyed on
        the traced iteration index — the fold_in sequence the looped
        path's ``bagging`` dispatches one iteration at a time. The
        barrier pins the outputs at what is a dispatch boundary on the
        looped path."""
        strat = self._many_sample
        if strat is None:
            return grad, hess, None
        g, h, ind = strat.apply_traced(iter_idx, grad, hess)
        if ind is None:
            return g, h, None
        return jax.lax.optimization_barrier((g, h, ind))

    def _many_impl(self, bins, score0, seeds, iters, feature_mask, lr,
                   qkey=None, qctr0=None):
        # optimization_barrier at every boundary that is a separate
        # dispatch in the per-iteration path: without them XLA fuses the
        # gradient math into the histogram kernels, changing rounding,
        # and the batched trees drift bit-wise from the looped ones
        barrier = jax.lax.optimization_barrier

        def step(carry, xs):
            seed, it = xs
            # score [N] (single-model objectives)
            score, ctr, alive = carry
            grad, hess = barrier(self._many_grad_fn(score))
            grad, hess, ind = self._apply_sampling(it, grad, hess)
            gh, qscale, ctr = self._step_gh(grad, hess, ind, qkey, ctr)
            recs, delta = self._grow_one(bins, gh, feature_mask, seed,
                                         lr, qscale)
            grew = rec_valid(jax.tree_util.tree_map(
                lambda a: a[0], recs))
            # after a stump step the score FREEZES: a quantized redraw
            # (new fold-in per step) may otherwise grow a tree the
            # host — which stops applying at the first stump — never
            # sees; dead steps also surface invalid records
            score = barrier(jnp.where(alive, score + delta, score))
            recs = recs._replace(
                gain=jnp.where(alive, recs.gain, -jnp.inf))
            return (score, ctr, alive & grew), recs

        ctr0 = jnp.uint32(0) if qctr0 is None else qctr0
        carry = (score0, ctr0, jnp.asarray(True))
        (score, ctr, _), recs = jax.lax.scan(step, carry, (seeds, iters))
        return (score, ctr), recs

    def _many_impl_multi(self, bins, score0, seeds, iters, feature_mask,
                         lr, qkey=None, qctr0=None):
        # K trees per iteration (multiclass): one gradient pass per step
        # over the [N, K] scores, then a statically unrolled per-class
        # tree (reference: the k-loop of GBDT::TrainOneIter)
        barrier = jax.lax.optimization_barrier
        K = int(seeds.shape[1])

        def step(carry, xs):
            seeds_k, it = xs
            score, ctr, alive = carry
            grad, hess = barrier(self._many_grad_fn(score))
            # one sampling draw per ITERATION over the [N, K] columns —
            # the looped path draws before its per-class loop too
            grad, hess, ind = self._apply_sampling(it, grad, hess)
            all_recs = []
            grew = jnp.asarray(False)
            for k in range(K):
                gh, qscale, ctr = self._step_gh(grad[:, k], hess[:, k],
                                                ind, qkey, ctr)
                recs, delta = self._grow_one(bins, gh, feature_mask,
                                             seeds_k[k], lr, qscale)
                grew = grew | rec_valid(jax.tree_util.tree_map(
                    lambda a: a[0], recs))
                score = score.at[:, k].add(
                    jnp.where(alive, delta, jnp.float32(0.0)))
                all_recs.append(recs)
            recs = jax.tree_util.tree_map(
                lambda *a: jnp.stack(a), *all_recs)
            recs = recs._replace(
                gain=jnp.where(alive, recs.gain, -jnp.inf))
            return (barrier(score), ctr, alive & grew), recs

        ctr0 = jnp.uint32(0) if qctr0 is None else qctr0
        carry = (score0, ctr0, jnp.asarray(True))
        (score, ctr, _), recs = jax.lax.scan(step, carry, (seeds, iters))
        return (score, ctr), recs

    def train_many(self, grad_fn, sample_strategy, score0: jnp.ndarray,
                   seeds, iters, shrinkage: float):
        """Run T boosting iterations in one dispatch. ``seeds`` is [T]
        (single-model objectives; ``score0`` is the [N] score column)
        or [T, K] (K trees per iteration; ``score0`` is [N, K]);
        ``iters`` is the [T] vector of absolute iteration numbers (the
        sample strategy's draw index). Returns (final scores, stacked
        SplitRecords [T, (K,) L-1]) — the record read-back is the
        batch's single host sync. ``grad_fn`` must be traceable (the
        objective's jitted gradient fn); ``sample_strategy`` provides
        the traceable ``apply_traced`` draw (None for no sampling).
        Quantized mode threads the learner's device-side tree counter
        through the scan and stores its advanced value back, so a
        later looped tree draws the key the looped path would have
        drawn."""
        self._ensure_compiled()
        # explicit staging of the batch's control vectors (the
        # transfer-guard sanitizer pins the warmed batch clean)
        seeds = jax.device_put(np.asarray(seeds, dtype=np.int32))
        iters = jax.device_put(np.asarray(iters, dtype=np.int32))
        # bound methods are rebuilt per attribute access: compare by
        # equality (__self__/__func__), not identity, or every batch
        # would re-jit the scan; strategies compare by value the same
        # way (sample_strategy.py _jit_key)
        if self._many_fn is None or self._many_grad_fn != grad_fn \
                or self._many_sample != sample_strategy:
            self._many_grad_fn = grad_fn
            self._many_sample = sample_strategy
            self._many_fn = obs_compile.instrument_jit(
                "mesh.train_many", self._many_impl)
            self._many_multi_fn = obs_compile.instrument_jit(
                "mesh.train_many_multi", self._many_impl_multi)
        feature_mask = self._sample_features()
        self._tree_idx += int(seeds.size)
        from ..utils.scalars import dev_f32
        lr = dev_f32(float(shrinkage))
        fn = self._many_multi_fn if seeds.ndim == 2 else self._many_fn
        if self._quantized:
            out, recs = fn(self.bins, score0, seeds, iters, feature_mask,
                           lr, self._quant_base_key, self._quant_ctr)
            score_t, self._quant_ctr = out
            # the scan advanced the device counter once per tree slot;
            # keep the host mirror (the _quantize_stage assert) in step
            self._quant_ctr_host += int(seeds.size)
        else:
            out, recs = fn(self.bins, score0, seeds, iters, feature_mask,
                           lr)
            score_t = out[0]
        return score_t, recs
