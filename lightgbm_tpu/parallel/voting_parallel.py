"""Voting-parallel tree learner: data-parallel with top-k feature voting.

TPU-native equivalent of the reference's ``VotingParallelTreeLearner``
(reference: src/treelearner/voting_parallel_tree_learner.cpp — PV-tree:
each rank proposes its local top-k features (:243-394), a vote over the
gathered proposals picks ~2k global features (GlobalVoting, :151), and
only the voted features' histograms cross the network
(CopyLocalHistogram, :184), cutting comm volume from O(F·B) to O(2k·B).

Here the whole vote runs inside the jitted split step under ``shard_map``
over the data axis, per child leaf (the reference also revotes per leaf):
local shard histogram → local per-feature best gains → local top-k →
``psum`` of vote counts (an [F] i32 vector) → global top-2k ids →
slice the [V, B, 4] voted block → ``psum`` it → scatter back to a full
[F, B, 4] buffer for the replicated scan, with the scan masked to the
voted set. Cross-device bytes per child: F·4 + V·B·16 instead of
F·B·16. The histogram-subtraction trick is NOT used here — different
leaves vote different features, so both children are histogrammed
locally (a masked full-shard pass each, same local cost) and reduced on
their own voted sets, mirroring the reference's smaller/larger buffers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..io.dataset import BinnedDataset
from ..ops.histogram import build_histogram
from ..ops.quantize import dequantize_hist, dequantize_sums, sum_gh
from ..ops.split import leaf_gain
from .data_parallel import DataParallelTreeLearner


def _per_feature_best_gain(hist, sum_grad, sum_hess, sum_count, meta,
                           params, feature_mask, hist_scale=None):
    """Per-feature best split gain (the voting score): the numerical
    threshold scan reduced over bins only, no cross-feature argmax
    (reference: the local FindBestThreshold each rank runs before voting,
    voting_parallel_tree_learner.cpp:243). Integer (quantized)
    histograms dequantize here; the leaf sums arrive dequantized."""
    hist = dequantize_hist(hist, hist_scale)
    g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
    left_g = jnp.cumsum(g, axis=1)
    left_h = jnp.cumsum(h, axis=1)
    left_c = jnp.cumsum(c, axis=1)
    B = hist.shape[1]
    bin_ids = jnp.arange(B, dtype=jnp.int32)[None, :]
    valid_t = (bin_ids < meta.num_bin[:, None] - 1) & feature_mask[:, None]
    rg, rh, rc = (sum_grad - left_g, sum_hess - left_h, sum_count - left_c)
    ok = ((left_c >= params.min_data_in_leaf)
          & (rc >= params.min_data_in_leaf)
          & (left_h >= params.min_sum_hessian_in_leaf)
          & (rh >= params.min_sum_hessian_in_leaf))
    gains = leaf_gain(left_g, left_h, params) + leaf_gain(rg, rh, params)
    gains = jnp.where(ok & valid_t, gains, -jnp.inf)
    return jnp.max(gains, axis=1)  # [F]


class VotingParallelTreeLearner(DataParallelTreeLearner):
    """Data-parallel learner whose cross-device histogram traffic is
    restricted to per-leaf globally voted features.

    EFB bundles are unpacked here: votes are per-feature, and the voted
    block slice already bounds the cross-device bytes below a bundle
    histogram's O(G·B)."""

    _supports_bundles = False
    # no per-leaf histogram store → the intermediate monotone method's
    # rescans are impossible; it degrades to basic (CapabilityMixin)
    _supports_intermediate = False

    def __init__(self, config, dataset: BinnedDataset, mesh: Mesh,
                 axis: str = "data"):
        super().__init__(config, dataset, mesh, axis)
        self.top_k = max(1, min(int(config.top_k), self.F))
        self.n_voted = min(2 * self.top_k, self.F)
        # no subtraction trick here → per-leaf histograms are never read
        # back; keep a single hist slot instead of [L, F, B, 4]
        self._hist_slots = 1

    def _voted_reduced_histogram(self, bins, gh_masked, feature_mask,
                                 qscale):
        """One child's globally-summed histogram, reduced only on voted
        features; returns ([F, B, 4] hist with unvoted rows zero,
        bool[F] voted mask). Quantized mode: the [V, B, 4] voted block
        psums as int32 — half the f32 bytes on the wire."""
        mesh, axis = self.mesh, self.axis
        meta, params, B, F = self.meta, self.params, self.B, self.F
        k, V = self.top_k, self.n_voted

        def local(bins_shard, gh_shard, fmask, qs):
            h = build_histogram(bins_shard, gh_shard, B,
                                pallas_ok=False,
                                hist_impl=self._hist_impl)  # local partial
            s = dequantize_sums(sum_gh(gh_shard), qs)       # local sums
            gains = _per_feature_best_gain(h, s[0], s[1], s[2], meta,
                                           params, fmask, hist_scale=qs)
            _, top_ids = jax.lax.top_k(gains, k)
            # a shard with no valid local split must not vote at all
            # (top_k on all--inf gains returns arbitrary low indices)
            has_split = jnp.isfinite(gains[top_ids]).astype(jnp.int32)
            votes = jnp.zeros(F, dtype=jnp.int32) \
                .at[top_ids].add(has_split)
            with jax.named_scope("obs_psum_votes"):
                votes = jax.lax.psum(votes, axis)           # [F] i32 — tiny
            _, voted = jax.lax.top_k(votes, V)              # replicated ids
            with jax.named_scope("obs_psum_voted_hist"):
                hv = jax.lax.psum(h[voted], axis)           # [V, B, 4] — the
            #                                    reduced histogram traffic
            full = jnp.zeros((F, B, 4), hv.dtype).at[voted].set(hv)
            vmask = jnp.zeros(F, dtype=bool).at[voted].set(True)
            return full, vmask

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(axis, None), P(axis, None), P(), P()),
            out_specs=(P(), P()))(bins, gh_masked, feature_mask, qscale)

    def _children_histograms(self, bins, state, rec, leaf, new_leaf,
                             leaf_of_row, smaller_is_left, mask_left,
                             mask_right, qscale=None):
        left_id = leaf  # left child keeps the split leaf's id
        if qscale is None:
            qscale = self._qs_ones
        zero = jnp.zeros((), dtype=state.gh.dtype)
        gh_l = jnp.where((leaf_of_row == left_id)[:, None], state.gh,
                         zero)
        gh_r = jnp.where((leaf_of_row == new_leaf)[:, None], state.gh,
                         zero)
        hist_left, voted_l = self._voted_reduced_histogram(
            bins, gh_l, mask_left, qscale)
        hist_right, voted_r = self._voted_reduced_histogram(
            bins, gh_r, mask_right, qscale)
        return (hist_left, hist_right, mask_left & voted_l,
                mask_right & voted_r)

    def _update_hist_store(self, state, leaf, new_leaf, hist_left,
                           hist_right, valid):
        # histograms are re-voted fresh per leaf; nothing reads the store
        return state.hists
