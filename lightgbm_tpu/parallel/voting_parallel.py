"""Voting-parallel tree learner: data-parallel with top-k feature voting.

TPU-native equivalent of the reference's ``VotingParallelTreeLearner``
(reference: src/treelearner/voting_parallel_tree_learner.cpp — PV-tree:
each rank proposes its local top-k features (:243-394), an Allgather of
``LightSplitInfo`` lets every rank compute the global vote (GlobalVoting,
:151), and only the ~2k voted features' histograms are summed across ranks
(CopyLocalHistogram, :184), cutting comm volume from O(F*B) to O(2k*B).

Here the same three phases run under ``shard_map`` over the data axis:
local histogram → local per-feature best gains → ``all_gather`` of local
top-k feature ids (the vote) → ``psum`` restricted to the voted feature
block → replicated scan over that block. On TPU this matters when the
mesh spans hosts (DCN-bound); within one ICI domain the plain
data-parallel full-histogram psum is usually faster.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..io.dataset import BinnedDataset
from ..ops.histogram import build_histogram, subtract_histogram
from ..ops.split import (FeatureMeta, SplitParams, find_best_split,
                         leaf_gain, calculate_leaf_output,
                         leaf_gain_given_output)
from ..treelearner.serial import _go_left_by_bin, _record_at, _store_info
from .data_parallel import DataParallelTreeLearner


def _per_feature_best_gain(hist, sum_grad, sum_hess, sum_count, meta,
                           params, feature_mask):
    """Per-feature best split gain (the voting score): the numerical
    threshold scan reduced over bins only, no cross-feature argmax
    (reference: the local FindBestThreshold each rank runs before voting,
    voting_parallel_tree_learner.cpp:243)."""
    g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
    left_g = jnp.cumsum(g, axis=1)
    left_h = jnp.cumsum(h, axis=1)
    left_c = jnp.cumsum(c, axis=1)
    B = hist.shape[1]
    bin_ids = jnp.arange(B, dtype=jnp.int32)[None, :]
    valid_t = (bin_ids < meta.num_bin[:, None] - 1) & feature_mask[:, None]
    rg, rh, rc = (sum_grad - left_g, sum_hess - left_h, sum_count - left_c)
    ok = ((left_c >= params.min_data_in_leaf)
          & (rc >= params.min_data_in_leaf)
          & (left_h >= params.min_sum_hessian_in_leaf)
          & (rh >= params.min_sum_hessian_in_leaf))
    gains = leaf_gain(left_g, left_h, params) + leaf_gain(rg, rh, params)
    gains = jnp.where(ok & valid_t, gains, -jnp.inf)
    return jnp.max(gains, axis=1)  # [F]


class VotingParallelTreeLearner(DataParallelTreeLearner):
    """Data-parallel learner whose cross-device histogram reduction is
    restricted to globally voted features."""

    def __init__(self, config, dataset: BinnedDataset, mesh: Mesh,
                 axis: str = "data"):
        super().__init__(config, dataset, mesh, axis)
        self.top_k = min(int(config.top_k), self.F)

    def _voted_feature_mask(self, gh, leaf_mask, feature_mask):
        """Phase 1+2: local histograms → local top-k → global vote
        (reference: GlobalVoting, voting_parallel_tree_learner.cpp:151).
        Returns a replicated bool[F] mask of ~2k voted features."""
        mesh, axis = self.mesh, self.axis
        meta, params, B, k = self.meta, self.params, self.B, self.top_k

        def local_vote(bins_shard, gh_shard):
            hist = build_histogram(bins_shard, gh_shard, B)
            sums = jnp.sum(gh_shard, axis=0)
            gains = _per_feature_best_gain(
                hist, sums[0], sums[1], sums[2], meta, params,
                feature_mask)
            _, top_ids = jax.lax.top_k(gains, k)
            votes = jnp.zeros(self.F, dtype=jnp.int32).at[top_ids].add(1)
            votes = jax.lax.psum(votes, axis)          # the Allgather+count
            return votes

        votes = shard_map(
            local_vote, mesh=mesh,
            in_specs=(P(axis, None), P(axis, None)),
            out_specs=P())(self.bins,
                           gh * leaf_mask[:, None])
        _, voted = jax.lax.top_k(votes, min(2 * k, self.F))
        mask = jnp.zeros(self.F, dtype=bool).at[voted].set(True)
        return mask & feature_mask

    def _step_impl(self, bins, state, leaf, new_leaf, children_allowed,
                   feature_mask):
        """Same dataflow as the data-parallel step, with the best-split
        scan restricted to voted features. The full-histogram psum is
        avoided for unvoted features by zero-masking before the
        cross-device reduction (XLA still reduces the buffer, but the
        voted mask keeps the scan semantics of the reference; a DCN
        deployment would slice the buffer instead)."""
        return super()._step_impl(bins, state, leaf, new_leaf,
                                  children_allowed, feature_mask)

    def train(self, grad, hess, bag=None):
        # vote once per tree on the root distribution (the reference
        # revotes per leaf; per-tree voting keeps one compiled step and
        # is the same comm bound)
        pad_n = self.R - self.N
        ind = jnp.ones(self.N, dtype=jnp.float32) if bag is None else bag
        gh = jnp.stack([grad * ind, hess * ind, ind,
                        jnp.ones(self.N, dtype=jnp.float32)], axis=1)
        if pad_n:
            gh = jnp.concatenate(
                [gh, jnp.zeros((pad_n, 4), dtype=jnp.float32)], axis=0)
        gh = jax.device_put(gh, self.gh_sharding)
        base_mask = self._sample_features()
        voted = self._voted_feature_mask(
            gh, jnp.ones(self.R, dtype=jnp.float32), base_mask)
        self._voted_mask = voted
        # delegate to the data-parallel loop with the voted mask
        old_sample = self._sample_features
        try:
            self._sample_features = lambda: voted
            return super().train(grad, hess, bag)
        finally:
            self._sample_features = old_sample
