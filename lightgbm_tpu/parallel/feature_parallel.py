"""Feature-parallel tree learner: features sharded over the mesh axis.

TPU-native equivalent of the reference's ``FeatureParallelTreeLearner``
(reference: src/treelearner/feature_parallel_tree_learner.cpp: every rank
holds all rows but owns a feature subset; after finding its local best
split, ranks agree via ``SyncUpGlobalBestSplit`` — an Allreduce with a
max-gain reducer, parallel_tree_learner.h:190). Here the bin matrix is
sharded [rows, FEATURES→mesh] so each device histograms and scans only its
feature block; the winning (gain, feature) argmax is a replicated scalar
reduction XLA lowers to the same max-Allreduce; the partition update reads
one feature column (a one-column all-gather, the analogue of every rank
splitting locally since all ranks hold all data).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..io.dataset import BinnedDataset
from ..obs.registry import registry as obs
from ..ops.split import FeatureMeta
from .data_parallel import DataParallelTreeLearner


class FeatureParallelTreeLearner(DataParallelTreeLearner):
    """Same host loop and step dataflow as the data-parallel learner, but
    sharded over features instead of rows. Rows are replicated (the
    reference's "all ranks hold all data"), so the partition update is
    fully local and the histogram needs no cross-device reduction at all —
    only the best-split argmax crosses devices.

    EFB bundles are unpacked here: features are the sharded axis, and
    bundle columns would couple features across shards (the histogram
    never crosses devices in this learner, so bundling buys no comm)."""

    _supports_bundles = False

    def __init__(self, config, dataset: BinnedDataset, mesh: Mesh,
                 axis: str = "data"):
        # pad the FEATURE axis to a devices multiple before sharding
        super().__init__(config, dataset, mesh, axis)
        n_dev = mesh.devices.size
        bins_full = (dataset.feature_bins() if dataset.bundle is not None
                     else dataset.bins)
        N, F = bins_full.shape
        Fp = -(-F // n_dev) * n_dev
        pad = np.zeros((N, Fp - F), dtype=bins_full.dtype)
        bins_host = np.concatenate([bins_full, pad], axis=1)
        # rows replicated, features sharded
        self.R = N
        self.F_pad = Fp
        with obs.scope("io::stage_bins_device"):
            self.bins = jax.device_put(
                bins_host, NamedSharding(mesh, P(None, self.axis)))
        self.row_sharding = NamedSharding(mesh, P())  # rows replicated
        # feature metadata padded to Fp: padded features are trivial
        # (num_bin 1 → never valid thresholds)
        meta = FeatureMeta.from_dataset(dataset,
                                        int(config.max_cat_to_onehot))
        padF = Fp - F

        def padv(a, fill):
            return jnp.concatenate(
                [a, jnp.full((padF,), fill, dtype=a.dtype)])

        self.meta = FeatureMeta(
            num_bin=padv(meta.num_bin, 1),
            missing_type=padv(meta.missing_type, 0),
            zero_bin=padv(meta.zero_bin, 0),
            is_categorical=padv(meta.is_categorical, False),
            use_onehot=padv(meta.use_onehot, False),
            monotone=padv(meta.monotone, 0),
        )
        self.meta = jax.device_put(self.meta, self.rep_sharding)
        self.F = Fp
        self.Fp = Fp
        # keep histograms feature-sharded; only the argmax crosses devices
        self.hist_sharding = NamedSharding(mesh, P(self.axis, None, None))
        self.gh_sharding = NamedSharding(mesh, P(None, None))  # replicated
        # the base __init__ sized the CEGB/monotone vectors before the
        # feature-axis repadding above — rebuild them at [Fp]
        self._init_cegb(config)
        self._init_monotone(config)

    def _make_cegb_fetched(self, rows: int) -> jnp.ndarray:
        # rows are replicated in this learner
        # jaxlint: disable=JLT003 -- one-shot replicated-zeros
        # allocation at CEGB setup (out_shardings is the point), never
        # dispatched again
        return jax.jit(lambda: jnp.zeros((rows, self.Fp),
                                         dtype=jnp.float32),
                       out_shardings=self.rep_sharding)()

    def _sample_features(self) -> jnp.ndarray:
        mask = np.zeros(self.F_pad, dtype=bool)
        real_f = len(self.dataset.bin_mappers)
        base = np.ones(real_f, dtype=bool)
        ff = float(self.config.feature_fraction)
        if 0.0 < ff < 1.0:
            k = max(1, int(round(real_f * ff)))
            base[:] = False
            base[self._ff_rng.choice(real_f, k, replace=False)] = True
        mask[:real_f] = base
        if self._constraint_groups is not None:
            allowed = np.zeros(self.F_pad, dtype=bool)
            for grp in self._constraint_groups:
                allowed[list(grp)] = True
            mask &= allowed
        return jax.device_put(jnp.asarray(mask), self.rep_sharding)

