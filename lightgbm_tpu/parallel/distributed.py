"""Multi-process (multi-host) distributed training.

TPU-native replacement for the reference's network bootstrap + distributed
loading (reference: machine-list/TCP full-mesh connect
src/network/linkers_socket.cpp:166; rank-sharded BinMapper construction
with Allgather, src/io/dataset_loader.cpp:1070-1240; per-rank
pre-partitioned loading, dataset_loader.cpp:203-260):

- bootstrap: ``jax.distributed.initialize`` (gRPC coordinator ≙ the
  reference's machine list; ICI/DCN collectives ≙ its TCP/MPI links)
- distributed binning: every process samples its LOCAL rows, the samples
  are allgathered host-side, and every process runs the same BinMapper
  construction on the identical gathered sample — same outcome as the
  reference's "shard features, bin, allgather mappers" with one hop less
  serialization
- training: the mesh learners (data_parallel.py) run unchanged over a
  global mesh; each process feeds its row shard via
  ``jax.make_array_from_process_local_data``. Every process executes the
  same host loop (SPMD discipline); split records are replicated, so all
  processes build identical trees — the reference reaches the same state
  via SyncUpGlobalBestSplit.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..io.dataset import BinnedDataset
from ..obs.registry import registry as obs
from ..utils import log
from .data_parallel import DataParallelTreeLearner


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bootstrap the process group (reference: Network::Init,
    src/network/network.cpp:30 — machine list + listen port become the
    coordinator address + process id)."""
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(axis: str = "data") -> Mesh:
    """1-D mesh over every device of every process."""
    return Mesh(np.array(jax.devices()), (axis,))


def distributed_binned_dataset(local_X: np.ndarray, config: Config,
                               label: Optional[Sequence[float]] = None,
                               **kw) -> BinnedDataset:
    """Distributed binning (reference:
    DatasetLoader::ConstructBinMappersFromTextData,
    src/io/dataset_loader.cpp:1070): sample locally, allgather the
    samples, build identical mappers everywhere, bin only local rows."""
    from jax.experimental import multihost_utils

    local_X = np.asarray(local_X, dtype=np.float64)
    n_local = local_X.shape[0]
    n_proc = jax.process_count()
    per_proc = max(1, config.bin_construct_sample_cnt // max(n_proc, 1))
    rng = np.random.RandomState(config.data_random_seed
                                + jax.process_index())
    take = min(per_proc, n_local)
    idx = np.sort(rng.choice(n_local, take, replace=False)) \
        if take < n_local else np.arange(n_local)
    sample = local_X[idx]
    # pad to a common per-process shape for the allgather; padding rows
    # are trimmed back out via the gathered count vector (a zeros row
    # covers the empty-shard case)
    # process_allgather adds NO leading process axis when n_proc == 1;
    # reshape(n_proc, ...) normalizes both layouts
    counts = np.asarray(multihost_utils.process_allgather(
        np.asarray([take], dtype=np.int64))).reshape(n_proc, -1)[:, 0]
    max_take = int(counts.max())
    if take < max_take:
        pad_row = sample[:1] if take > 0 else np.zeros(
            (1, local_X.shape[1]), dtype=local_X.dtype)
        pad = np.repeat(pad_row, max_take - take, axis=0)
        sample = np.concatenate([sample, pad], axis=0)
    # allgather as int32 bit patterns: process_allgather canonicalizes
    # float64 -> float32 (and int64 -> int32) when x64 is off, which
    # would round the bin boundaries; two int32 words per double
    # round-trip exactly
    bits = np.ascontiguousarray(sample).view(np.int32)
    gathered_bits = np.asarray(multihost_utils.process_allgather(bits))
    gathered = np.ascontiguousarray(gathered_bits).view(np.float64) \
        .reshape(n_proc, max_take, local_X.shape[1])
    parts = [gathered[p][:int(counts[p])] for p in range(n_proc)]
    full_sample = np.concatenate(parts, axis=0)

    # every process now builds mappers from the identical global sample,
    # then bins only its local rows
    cfg2 = Config.from_params(dict(config.raw_params,
                                   bin_construct_sample_cnt=len(
                                       full_sample)))
    template = BinnedDataset.from_matrix(full_sample, cfg2)
    ds = BinnedDataset.from_matrix(local_X, config, label=label,
                                   reference=template, **kw)
    ds.num_total_features = template.num_total_features
    return ds


class DistributedDataParallelLearner(DataParallelTreeLearner):
    """Data-parallel learner over a multi-process global mesh: each
    process contributes its local row shard; the device mesh spans all
    processes and XLA's collectives ride ICI/DCN (reference analogue:
    DataParallelTreeLearner over MPI ranks)."""

    def supports_train_many(self) -> bool:
        """The batched scan hardcodes the single-process tail-pad gh
        layout (_make_gh_traced) and the [:N] partition slice; this
        learner's per-process interleaved pad blocks need their own
        staging, so the batched path stays off multi-process meshes."""
        return False

    def __init__(self, config, local_dataset: BinnedDataset, mesh: Mesh,
                 axis: str = "data"):
        from jax.experimental import multihost_utils

        bins_local = self._init_mesh_common(config, local_dataset, mesh,
                                            axis)
        if self._quantized:
            # the per-iteration scale is a GLOBAL max and the stochastic
            # draw is per-global-row; the host-side per-process staging
            # has neither without an extra allgather round — quantized
            # mode stays off the multi-process learner for now
            from ..ops.histogram import _warn_once
            _warn_once("use_quantized_grad is not supported by the "
                       "multi-process distributed learner; training "
                       "falls back to exact f32 histograms",
                       component="parallel.distributed")
            self._quantized = False
            self._hist_impl = self._hist_impl[:2] + (0,)
            self._qscale = self._qs_ones
        n_local, C = bins_local.shape
        if self.F == 0:
            log.fatal("Cannot train without features")
        n_proc = jax.process_count()
        dev_per_proc = len(mesh.devices.flatten()) // max(n_proc, 1)
        counts = np.asarray(multihost_utils.process_allgather(
            np.asarray([n_local], dtype=np.int64))).reshape(-1)
        self.N = int(counts.sum())
        # per-process padded block, equal across processes so the global
        # row axis splits evenly over devices
        block = -(-int(counts.max()) // max(dev_per_proc, 1)) \
            * max(dev_per_proc, 1)
        self.R = block * n_proc
        self._block = block
        self._n_local = n_local

        local_bins = np.zeros((block, C), dtype=bins_local.dtype)
        local_bins[:n_local] = bins_local
        with obs.scope("io::stage_bins_device"):
            self.bins = jax.make_array_from_process_local_data(
                NamedSharding(mesh, P(self.axis, None)), local_bins)
        self._init_cegb(config)
        self._init_monotone(config)

    def _make_gh(self, grad, hess, bag) -> jnp.ndarray:
        """Local [n_local] numpy grad/hess shard → global padded sharded
        [R, 4] gh matrix (overrides the single-process device path)."""
        n = self._n_local
        ind = np.ones(n, dtype=np.float32) if bag is None \
            else np.asarray(bag, dtype=np.float32)
        gh_local = np.zeros((self._block, 4), dtype=np.float32)
        gh_local[:n, 0] = np.asarray(grad, np.float32) * ind
        gh_local[:n, 1] = np.asarray(hess, np.float32) * ind
        gh_local[:n, 2] = ind
        gh_local[:n, 3] = 1.0
        return jax.make_array_from_process_local_data(
            self.gh_sharding, gh_local)

    # kept as the public name used by callers/tests
    make_global_gh = _make_gh

    def _initial_partition(self, gh):
        # each process's local pad rows are interleaved per-process, not
        # a single tail: rows with total-count channel 0 are padding
        leaf_of_row = jnp.where(gh[:, 3] > 0.0, 0, -1).astype(jnp.int32)
        return jax.lax.with_sharding_constraint(
            leaf_of_row, self.row_sharding)

    def _finalize_partition(self, leaf_of_row):
        # keep the global sharded vector; local_leaf_assignment slices it
        return leaf_of_row

    def local_leaf_assignment(self, leaf_of_row) -> np.ndarray:
        """This process's [n_local] slice of the global partition."""
        shards = [s for s in leaf_of_row.addressable_shards]
        shards.sort(key=lambda s: s.index[0].start)
        local = np.concatenate([np.asarray(s.data) for s in shards])
        return local[:self._n_local]
