"""Multi-process distributed training entry point.

The analogue of the reference's Dask integration
(python-package/lightgbm/dask.py:172 ``_train_part``: one training task
per worker, every worker holding a row shard, rank 0's model returned) —
except the collective layer is JAX's ICI/DCN mesh instead of the
reference's socket-list bootstrap (machines/local_listen_port,
dask.py:183-189).

Every process calls :func:`train` with its LOCAL shard. Binning,
histogram sums, and split decisions are globally synchronized (see
``distributed_binned_dataset`` / ``DistributedDataParallelLearner``), so
all processes end with identical trees; each returns a full Booster.

Usage (per process, after ``jax.distributed.initialize``)::

    booster = lightgbm_tpu.parallel.dtrain.train(
        {"objective": "binary", "num_leaves": 31},
        local_X, local_y, num_boost_round=100)
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..basic import Booster
from ..config import Config
from ..metric import create_metric
from ..objective import create_objective
from ..obs import events as obs_events
from ..obs import health as obs_health
from ..obs import trace as obs_trace
from ..obs.registry import registry as obs
from ..utils import log
from .distributed import (DistributedDataParallelLearner,
                          distributed_binned_dataset, global_mesh)


_ENV_COLLECTIVE_TIMEOUT = "LIGHTGBM_TPU_DTRAIN_TIMEOUT_S"
kDefaultCollectiveTimeoutS = 300.0


def _collective_timeout() -> float:
    """Seconds one cross-process collective may block (<= 0 disables
    the bound)."""
    try:
        return float(os.environ.get(_ENV_COLLECTIVE_TIMEOUT,
                                    kDefaultCollectiveTimeoutS))
    except ValueError:
        return kDefaultCollectiveTimeoutS


def run_collective(fn, what: str = "allreduce",
                   timeout: Optional[float] = None):
    """Run a blocking cross-process collective with peer-death
    detection: the call executes on a watcher-owned thread and a peer
    that never shows up (a dead/preempted rank would otherwise block
    this rank FOREVER — the socket-allreduce failure mode of the
    reference's network stack) turns into a fatal ``health`` event
    (flushed) + ``log.fatal`` after the timeout
    (``LIGHTGBM_TPU_DTRAIN_TIMEOUT_S``, default 300 s; <= 0 runs
    unbounded). The abandoned worker thread is daemonized — the
    process is going down anyway, loudly instead of silently."""
    if timeout is None:
        timeout = _collective_timeout()
    if timeout <= 0:
        return fn()
    import threading
    out: list = []
    err: list = []

    def _run():
        try:
            out.append(fn())
        except BaseException as e:  # surfaced on the caller thread
            err.append(e)

    t = threading.Thread(target=_run, daemon=True,
                         name="dtrain-collective")
    t.start()
    t.join(timeout)
    if t.is_alive():
        try:
            rank = int(jax.process_index())
        except Exception:
            rank = -1
        obs.inc("health/dtrain_peer_timeout")
        obs_events.emit("health", rule="dtrain_peer_timeout",
                        severity="fatal", what=what, rank=rank,
                        value=timeout, threshold=timeout,
                        detail="collective %r did not complete; a peer "
                               "rank is likely dead" % what)
        obs_events.flush()
        log.fatal("distributed collective %r did not complete within "
                  "%.0f s (%s) — a peer rank is likely dead or "
                  "partitioned; aborting this rank instead of hanging"
                  % (what, timeout, _ENV_COLLECTIVE_TIMEOUT))
    if err:
        raise err[0]
    return out[0]


def _allreduce_sum(vals: Sequence[float]) -> np.ndarray:
    """Scalar sums across processes (reference:
    Network::GlobalSyncUpBySum, include/LightGBM/network.h:189),
    bounded by :func:`run_collective`."""
    from jax.experimental import multihost_utils
    obs.inc("dtrain/allreduce_sum")
    arr = np.asarray(vals, dtype=np.float64).reshape(1, -1)
    # float64 survives as two int32 words (x64 may be disabled)
    bits = np.ascontiguousarray(arr).view(np.int32)
    gathered = np.asarray(run_collective(
        lambda: multihost_utils.process_allgather(bits),
        what="allreduce_sum"))
    return np.ascontiguousarray(gathered).view(np.float64) \
        .reshape(jax.process_count(), -1).sum(axis=0)


def train(params: Dict, local_X: np.ndarray, local_y: np.ndarray,
          num_boost_round: int = 100,
          local_weight: Optional[np.ndarray] = None,
          local_group: Optional[np.ndarray] = None,
          mesh=None) -> Booster:
    """Distributed GBDT boosting over per-process row shards. Returns a
    Booster (identical on every process). Gradient/hessian computation
    and score updates stay local to each process (reference: every rank
    runs the full GBDT driver in 3.1 with only the tree learner
    synchronized, src/boosting/gbdt.cpp + parallel learners).

    Multiclass trains num_class trees per iteration over the shared
    partition. Ranking objectives require query-aligned shards
    (``local_group`` per process), like the reference's pre-partitioned
    distributed data (config.h pre_partition)."""
    config = Config.from_params(params)
    # rank pinned for the whole telemetry plane, not just tracing: the
    # gateway pusher (obs/gateway.py) labels this process's pushes
    # {rank=}, and process_index() would otherwise lazily resolve to 0
    # if jax.distributed wasn't initialized when first asked
    rank = int(jax.process_index())
    obs_trace.set_process_index(rank)
    if obs_trace.active():
        if obs_trace.stream_dir() is not None:
            # streaming mode: segments already carry the rank in the
            # file name (segment-r<rank>-<seq>.json/.ctrace), so every
            # rank can share one LIGHTGBM_TPU_TRACE_STREAM directory —
            # the pid pin above landed before the first event
            pass
        else:
            # one trace file per rank, pid = the rank: ranks share one
            # LIGHTGBM_TPU_TRACE value, the rank is folded into the
            # file name, and tools/trace_report.py merge interleaves
            # the files into per-rank Perfetto lanes. Re-point the sink
            # BEFORE any event lands (record_backend below) —
            # configure() flushes the current buffer to the current
            # path, and ranks must never write the shared un-ranked
            # file
            obs_trace.configure(
                obs_trace.rank_path(obs_trace.sink_path(), rank),
                process_index_override=rank, keep_buffer=True)
    obs_health.record_backend_once(source="dtrain")
    # start the env-configured metrics exporter / fleet gateway pusher
    # NOW (not at the first iteration's sample_iteration tick): the
    # gateway should see every rank before the first — possibly long —
    # distributed binning stage finishes, so dead_rank watches cover
    # startup too
    obs_trace.sample_iteration(0)
    local_X = np.asarray(local_X, dtype=np.float64)
    local_y = np.asarray(local_y, dtype=np.float64)
    n_local = local_X.shape[0]

    with obs.scope("io::distributed_binning"):
        ds = distributed_binned_dataset(local_X, config, label=local_y,
                                        weights=local_weight,
                                        group=local_group)
    mesh = mesh if mesh is not None else global_mesh()
    learner = DistributedDataParallelLearner(config, ds, mesh)

    objective = create_objective(config.objective, config)
    obj_name = str(config.objective)
    if obj_name == "binary" or obj_name.startswith("multiclass"):
        # per-class state (need_train, is_unbalance weights) derives
        # from LOCAL labels only; a shard missing a class would silently
        # zero that class's gradients on this rank. The check must be
        # COLLECTIVE: a rank-local raise would leave the other ranks
        # hanging in the first psum — so every rank gathers every
        # rank's coverage bitmask and they all fail together.
        expected = sorted(range(max(int(config.num_class), 2))
                          if obj_name.startswith("multiclass")
                          else (0, 1))
        present = set(np.unique(local_y.astype(np.int64)))
        mask = [1.0 if k in present else 0.0 for k in expected]
        from jax.experimental import multihost_utils
        mask_arr = np.asarray(mask, dtype=np.float32).reshape(1, -1)
        all_masks = np.asarray(run_collective(
            lambda: multihost_utils.process_allgather(mask_arr),
            what="class_coverage_allgather"))
        all_masks = all_masks.reshape(jax.process_count(), -1)
        bad = {r: [expected[k] for k in range(len(expected))
                   if all_masks[r, k] == 0.0]
               for r in range(all_masks.shape[0])
               if (all_masks[r] == 0.0).any()}
        if bad:
            log.fatal("shards are missing classes (rank -> classes): "
                      "%s; distributed training needs every class on "
                      "every shard" % bad)
    objective.init(ds.metadata, n_local)

    K = max(int(objective.num_tree_per_iteration), 1)

    # boost_from_average over the GLOBAL label sums (reference:
    # BoostFromScore uses the full data; each rank only has a shard — the
    # init score must be identical everywhere or the shared trees would
    # sit on inconsistent base scores)
    init_scores = [0.0] * K
    if config.boost_from_average and objective is not None:
        w = (np.ones(n_local) if local_weight is None
             else np.asarray(local_weight, dtype=np.float64))
        name = objective.name
        eps = 1e-15
        if name == "multiclassova":
            sums = [float((w * (local_y.astype(np.int32) == k)).sum())
                    for k in range(K)] + [float(w.sum())]
            tot = _allreduce_sum(sums)
            for k in range(K):
                p = min(max(tot[k] / max(tot[-1], 1e-300), eps),
                        1.0 - eps)
                init_scores[k] = float(np.log(p / (1.0 - p))
                                       / float(config.sigmoid))
        elif name == "multiclass":
            pass  # softmax trains from zero scores (matches GBDT)
        else:
            tot = _allreduce_sum([float((local_y * w).sum()),
                                  float(w.sum())])
            gmean = tot[0] / max(tot[1], 1e-300)
            if name == "binary":
                p = min(max(gmean, eps), 1.0 - eps)
                init_scores[0] = float(np.log(p / (1.0 - p))
                                       / float(config.sigmoid))
            elif name in ("regression", "huber", "fair"):
                init_scores[0] = float(gmean)
            elif name in ("poisson", "gamma", "tweedie"):
                init_scores[0] = float(np.log(max(gmean, eps)))
            elif name in ("lambdarank", "rank_xendcg"):
                pass  # ranking trains from zero scores
            else:
                # percentile-based objectives (l1/quantile/mape) are not
                # sum-decomposable; per-shard approximation
                init_scores[0] = float(objective.boost_from_score(0))
                log.warning("%s boost_from_average uses per-shard "
                            "percentiles; init score is approximate"
                            % name)

    score = np.tile(np.asarray(init_scores, dtype=np.float64),
                    (n_local, 1))                       # [n, K]
    lr = float(config.learning_rate)
    trees = []
    import time as _time
    for it in range(num_boost_round):
        t_it = _time.perf_counter()
        with obs.scope("gbdt::gradients"):
            sc = jnp.asarray(score[:, 0] if K == 1 else score,
                             dtype=jnp.float32)
            grad, hess = objective.get_gradients(sc)
            g = np.asarray(grad, np.float32).reshape(n_local, K)
            h = np.asarray(hess, np.float32).reshape(n_local, K)
        iter_trees = []
        for k in range(K):
            with obs.scope("tree::grow"):
                tree, part = learner.train(g[:, k], h[:, k])
            tree.apply_shrinkage(lr)
            with obs.scope("gbdt::score_update"):
                local_leaf = learner.local_leaf_assignment(part)
                score[:, k] += tree.leaf_value[local_leaf]
            if it == 0 and abs(init_scores[k]) > 1e-35:
                # fold the init score into the first tree so saved
                # models predict standalone (reference: gbdt.cpp
                # new_tree->AddBias)
                tree.add_bias(init_scores[k])
            trees.append(tree)
            iter_trees.append(tree)
        obs_trace.sample_iteration(it + 1)
        if obs_events.enabled():
            obs_events.emit(
                "train_iter", iter=it + 1,
                seconds=round(_time.perf_counter() - t_it, 6),
                distributed=True,
                trees=[{"num_leaves": int(t.num_leaves),
                        "depth": int(t.leaf_depth[
                            :max(t.num_leaves, 1)].max())}
                       for t in iter_trees])
        if config.metric and (it + 1) % max(config.metric_freq, 1) == 0 \
                and config.is_provide_training_metric:
            for mname in config.metric:
                try:
                    m = create_metric(mname, config)
                    m.init(ds.metadata, n_local)
                    local_vals = m.eval(
                        score[:, 0] if K == 1 else score, objective)
                    # sum-decomposable metrics reduce exactly; the
                    # rank/AUC family is a per-shard approximation —
                    # classify from the metric's canonical name, not the
                    # user's alias string
                    red = _allreduce_sum([local_vals[0] * n_local,
                                          float(n_local)])
                    canon = (m.name[0] if isinstance(m.name, (list, tuple))
                             else str(m.name))
                    approx = any(canon.startswith(p) for p in
                                 ("auc", "ndcg", "map",
                                  "average_precision"))
                    log.info("[%d] %s %s: %.6f"
                             % (it + 1,
                                "shard-avg approx" if approx
                                else "global",
                                mname, red[0] / red[1]))
                except Exception as e:
                    log.warning("metric %s failed: %s" % (mname, e))

    # package as a Booster via the model text format so save / predict /
    # dump_model all work (and the format round-trip is exercised)
    from ..boosting import create_boosting
    gbdt = create_boosting(config)
    gbdt.models = list(trees)
    gbdt.num_class = K if objective.name.startswith("multiclass") else 1
    gbdt.num_tree_per_iteration = K
    gbdt.max_feature_idx = local_X.shape[1] - 1
    gbdt.feature_names = list(ds.feature_names)
    gbdt.feature_infos = ds.feature_infos()
    gbdt.objective = objective
    return Booster(params=dict(params),
                   model_str=gbdt.save_model_to_string())
