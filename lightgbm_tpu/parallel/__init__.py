"""Distributed training over a ``jax.sharding.Mesh``.

TPU-native replacement for the reference's network + parallel-learner
layers (reference: src/network/ — TCP/MPI collectives;
src/treelearner/data_parallel_tree_learner.cpp,
feature_parallel_tree_learner.cpp, voting_parallel_tree_learner.cpp).
Machine lists, listen ports and socket bootstrap have no TPU analogue:
an ICI/DCN mesh plus GSPMD sharding constraints make XLA insert the
collectives (psum ≙ Allreduce, psum_scatter ≙ ReduceScatter+
HistogramSumReducer, all_gather ≙ Allgather).
"""
from .data_parallel import DataParallelTreeLearner, make_mesh
from .feature_parallel import FeatureParallelTreeLearner
from .voting_parallel import VotingParallelTreeLearner

__all__ = ["DataParallelTreeLearner", "FeatureParallelTreeLearner",
           "VotingParallelTreeLearner", "make_mesh"]
