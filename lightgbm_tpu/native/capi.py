"""ctypes loader for the C inference library (_capi.so).

The shared object is the external-engine ABI (see capi.h); this module
is the in-repo consumer used by the test suite to cross-check the C
predictor against the Python one, and a convenience for Python hosts
that want GIL-free native prediction. Builds on first use with g++,
same pattern as the parser (native/__init__.py).
"""
from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_DTYPE_INT64 = 3

C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2
C_API_PREDICT_CONTRIB = 3


def load_lib() -> Optional[ctypes.CDLL]:
    """Build (once) and load _capi.so; None when no toolchain."""
    global _LIB, _LIB_FAILED
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        try:
            from . import compile_and_load
            lib = compile_and_load("capi.cpp", "_capi.so")
            lib.LGBM_GetLastError.restype = ctypes.c_char_p
            for name, argtypes in _SIGNATURES.items():
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int
                fn.argtypes = argtypes
            _LIB = lib
        except Exception:
            _LIB_FAILED = True
            from ..utils import log
            log.warning("native C API unavailable (g++ build failed)")
        return _LIB


_p = ctypes.POINTER
_SIGNATURES = {
    "LGBM_BoosterCreateFromModelfile":
        [ctypes.c_char_p, _p(ctypes.c_int), _p(ctypes.c_void_p)],
    "LGBM_BoosterLoadModelFromString":
        [ctypes.c_char_p, _p(ctypes.c_int), _p(ctypes.c_void_p)],
    "LGBM_BoosterFree": [ctypes.c_void_p],
    "LGBM_BoosterGetNumClasses": [ctypes.c_void_p, _p(ctypes.c_int)],
    "LGBM_BoosterGetNumFeature": [ctypes.c_void_p, _p(ctypes.c_int)],
    "LGBM_BoosterGetCurrentIteration": [ctypes.c_void_p, _p(ctypes.c_int)],
    "LGBM_BoosterCalcNumPredict":
        [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
         ctypes.c_int, _p(ctypes.c_int64)],
    "LGBM_BoosterPredictForMat":
        [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int32,
         ctypes.c_int32, ctypes.c_int, ctypes.c_int, ctypes.c_int,
         ctypes.c_int, ctypes.c_char_p, _p(ctypes.c_int64),
         _p(ctypes.c_double)],
    "LGBM_BoosterPredictForCSR":
        [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
         _p(ctypes.c_int32), ctypes.c_void_p, ctypes.c_int,
         ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
         ctypes.c_int, ctypes.c_int, ctypes.c_char_p, _p(ctypes.c_int64),
         _p(ctypes.c_double)],
    "LGBM_BoosterPredictForMatSingleRow":
        [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int32,
         ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
         ctypes.c_char_p, _p(ctypes.c_int64), _p(ctypes.c_double)],
    "LGBM_BoosterPredictForCSRSingleRow":
        [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
         _p(ctypes.c_int32), ctypes.c_void_p, ctypes.c_int,
         ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
         ctypes.c_int, ctypes.c_int, ctypes.c_char_p, _p(ctypes.c_int64),
         _p(ctypes.c_double)],
    "LGBM_BoosterPredictForCSC":
        [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
         _p(ctypes.c_int32), ctypes.c_void_p, ctypes.c_int,
         ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
         ctypes.c_int, ctypes.c_int, ctypes.c_char_p, _p(ctypes.c_int64),
         _p(ctypes.c_double)],
    "LGBM_BoosterGetNumPredict":
        [ctypes.c_void_p, ctypes.c_int, _p(ctypes.c_int64)],
    "LGBM_BoosterGetLeafValue":
        [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
         _p(ctypes.c_double)],
    "LGBM_BoosterSetLeafValue":
        [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_double],
    "LGBM_BoosterPredictForFile":
        [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
         ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p],
    "LGBM_BoosterDumpModel":
        [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
         ctypes.c_int64, _p(ctypes.c_int64), ctypes.c_char_p],
    "LGBM_BoosterSaveModel":
        [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
         ctypes.c_char_p],
    "LGBM_BoosterSaveModelToString":
        [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
         ctypes.c_int64, _p(ctypes.c_int64), ctypes.c_char_p],
    "LGBM_BoosterGetFeatureNames":
        [ctypes.c_void_p, ctypes.c_int, _p(ctypes.c_int), ctypes.c_size_t,
         _p(ctypes.c_size_t), _p(ctypes.c_char_p)],
}


class NativeBoosterError(RuntimeError):
    pass


def _check(lib, rc: int) -> None:
    if rc != 0:
        raise NativeBoosterError(lib.LGBM_GetLastError().decode())


class NativeBooster:
    """Thin handle over the C API — the same call sequence an external
    C/R/Java host performs, here driven from the tests."""

    def __init__(self, model_str: Optional[str] = None,
                 model_file: Optional[str] = None):
        lib = load_lib()
        if lib is None:
            raise NativeBoosterError("native C API library unavailable")
        self._lib = lib
        self._handle = ctypes.c_void_p()
        n_iter = ctypes.c_int()
        if model_file is not None:
            _check(lib, lib.LGBM_BoosterCreateFromModelfile(
                model_file.encode(), ctypes.byref(n_iter),
                ctypes.byref(self._handle)))
        else:
            _check(lib, lib.LGBM_BoosterLoadModelFromString(
                model_str.encode(), ctypes.byref(n_iter),
                ctypes.byref(self._handle)))
        self.num_iterations = n_iter.value

    def close(self) -> None:
        if self._handle:
            self._lib.LGBM_BoosterFree(self._handle)
            self._handle = ctypes.c_void_p()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------------
    @property
    def num_classes(self) -> int:
        v = ctypes.c_int()
        _check(self._lib, self._lib.LGBM_BoosterGetNumClasses(
            self._handle, ctypes.byref(v)))
        return v.value

    @property
    def num_features(self) -> int:
        v = ctypes.c_int()
        _check(self._lib, self._lib.LGBM_BoosterGetNumFeature(
            self._handle, ctypes.byref(v)))
        return v.value

    def feature_names(self) -> list:
        n = ctypes.c_int()
        width = ctypes.c_size_t()
        _check(self._lib, self._lib.LGBM_BoosterGetFeatureNames(
            self._handle, 0, ctypes.byref(n), 0, ctypes.byref(width), None))
        bufs = [ctypes.create_string_buffer(width.value + 1)
                for _ in range(n.value)]
        arr = (ctypes.c_char_p * n.value)(
            *[ctypes.cast(b, ctypes.c_char_p) for b in bufs])
        _check(self._lib, self._lib.LGBM_BoosterGetFeatureNames(
            self._handle, n.value, ctypes.byref(n), width.value + 1,
            ctypes.byref(width), arr))
        return [b.value.decode() for b in bufs]

    # ------------------------------------------------------------------
    def predict(self, X: np.ndarray, predict_type: int = 0,
                start_iteration: int = 0,
                num_iteration: int = -1) -> np.ndarray:
        X = np.ascontiguousarray(X)
        if X.dtype == np.float32:
            dtype = C_API_DTYPE_FLOAT32
        else:
            X = X.astype(np.float64, copy=False)
            dtype = C_API_DTYPE_FLOAT64
        nrow, ncol = X.shape
        total = ctypes.c_int64()
        _check(self._lib, self._lib.LGBM_BoosterCalcNumPredict(
            self._handle, nrow, predict_type, start_iteration,
            num_iteration, ctypes.byref(total)))
        out = np.empty(total.value, dtype=np.float64)
        out_len = ctypes.c_int64()
        _check(self._lib, self._lib.LGBM_BoosterPredictForMat(
            self._handle, X.ctypes.data_as(ctypes.c_void_p), dtype,
            nrow, ncol, 1, predict_type, start_iteration, num_iteration,
            b"", ctypes.byref(out_len),
            out.ctypes.data_as(_p(ctypes.c_double))))
        assert out_len.value == total.value
        return out.reshape(nrow, -1)

    def predict_csr(self, indptr: np.ndarray, indices: np.ndarray,
                    data: np.ndarray, num_col: int,
                    predict_type: int = 0, start_iteration: int = 0,
                    num_iteration: int = -1) -> np.ndarray:
        indptr64 = np.ascontiguousarray(indptr, dtype=np.int64)
        indices32 = np.ascontiguousarray(indices, dtype=np.int32)
        data64 = np.ascontiguousarray(data, dtype=np.float64)
        nrow = len(indptr64) - 1
        total = ctypes.c_int64()
        _check(self._lib, self._lib.LGBM_BoosterCalcNumPredict(
            self._handle, nrow, predict_type, start_iteration,
            num_iteration, ctypes.byref(total)))
        out = np.empty(total.value, dtype=np.float64)
        out_len = ctypes.c_int64()
        _check(self._lib, self._lib.LGBM_BoosterPredictForCSR(
            self._handle, indptr64.ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_INT64,
            indices32.ctypes.data_as(_p(ctypes.c_int32)),
            data64.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            len(indptr64), len(data64), num_col, predict_type,
            start_iteration, num_iteration, b"", ctypes.byref(out_len),
            out.ctypes.data_as(_p(ctypes.c_double))))
        return out.reshape(nrow, -1)

    def dump_model(self) -> dict:
        import json
        n = ctypes.c_int64()
        _check(self._lib, self._lib.LGBM_BoosterDumpModel(
            self._handle, 0, -1, 0, 0, ctypes.byref(n), None))
        buf = ctypes.create_string_buffer(n.value)
        _check(self._lib, self._lib.LGBM_BoosterDumpModel(
            self._handle, 0, -1, 0, n.value, ctypes.byref(n), buf))
        return json.loads(buf.value.decode())

    def save_model_to_string(self) -> str:
        n = ctypes.c_int64()
        _check(self._lib, self._lib.LGBM_BoosterSaveModelToString(
            self._handle, 0, -1, 0, 0, ctypes.byref(n), None))
        buf = ctypes.create_string_buffer(n.value)
        _check(self._lib, self._lib.LGBM_BoosterSaveModelToString(
            self._handle, 0, -1, 0, n.value, ctypes.byref(n), buf))
        return buf.value.decode()
