// Native text parser for lightgbm_tpu.
//
// Equivalent of the reference's C++ Parser (src/io/parser.cpp:
// CSVParser/TSVParser/LibSVMParser + Parser::CreateParser auto-detection)
// and the hot inner loop of DatasetLoader's text path
// (src/io/dataset_loader.cpp:203 LoadFromFile). The Python front end
// (application._load_tabular) dispatches here via ctypes; numpy's
// genfromtxt is ~40x slower on wide CSVs.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -o _parser.so parser.cpp
//
// C ABI:
//   ParseDense(path, delim, skip_rows, out*, rows*, cols*) -> status
//     parses a delimiter-separated numeric file into a malloc'd
//     row-major double buffer (caller frees with FreeBuffer); empty
//     fields and non-numeric tokens become NaN.
//   ParseLibSVM(path, out*, labels*, rows*, cols*) -> status
//     parses "label idx:val ..." lines into a dense row-major buffer
//     (absent entries 0.0, matching the reference's sparse semantics).
//   FreeBuffer(ptr)

// PARSER_API lets an including translation unit (native/capi.cpp pulls
// this file in for PredictForFile) make these symbols hidden instead of
// re-exporting duplicates of _parser.so's interface
#ifndef PARSER_API
#define PARSER_API
#endif

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <limits>
#include <string>
#include <vector>

namespace {

// Read a whole file into memory (data files are loaded wholesale by the
// reference's TextReader as well).
bool ReadAll(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  if (size < 0) { std::fclose(f); return false; }
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(&(*out)[0], 1, out->size(), f) : 0;
  std::fclose(f);
  return got == out->size();
}

inline const char* SkipSpaces(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
  return p;
}

}  // namespace

extern "C" {

PARSER_API void FreeBuffer(void* p) { std::free(p); }

// status: 0 ok, 1 io error, 2 empty/parse error
PARSER_API int ParseDense(const char* path, char delim, int skip_rows,
               double** out, long* n_rows, long* n_cols) {
  std::string buf;
  if (!ReadAll(path, &buf)) return 1;
  const char* p = buf.data();
  const char* end = p + buf.size();

  // first pass: column count from the first data line
  const char* q = p;
  for (int s = 0; s < skip_rows && q < end; ++s) {
    while (q < end && *q != '\n') ++q;
    if (q < end) ++q;
  }
  const char* data_start = q;
  long cols = 0;
  {
    // first non-blank, non-comment line sets the column count
    const char* scan = q;
    while (scan < end && cols == 0) {
      const char* line_end = scan;
      while (line_end < end && *line_end != '\n') ++line_end;
      const char* content = SkipSpaces(scan, line_end);
      if (content < line_end && *content != '#') {
        cols = 1;
        for (const char* c = scan; c < line_end; ++c)
          if (*c == delim) ++cols;
      }
      scan = line_end < end ? line_end + 1 : end;
    }
    if (cols == 0) return 2;
  }

  std::vector<double> vals;
  vals.reserve(1 << 20);
  long rows = 0;
  p = data_start;
  while (p < end) {
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    const char* stripped = line_end;
    if (stripped > p && stripped[-1] == '\r') --stripped;
    const char* content = SkipSpaces(p, stripped);
    // '#' comment lines are skipped (matching numpy genfromtxt's
    // default comments='#')
    if (content < stripped && *content != '#') {
      long col = 0;
      long n_fields = 1;
      for (const char* c = p; c < stripped; ++c)
        if (*c == delim) ++n_fields;
      if (n_fields != cols) return 2;  // ragged row (either direction):
                                       // fail like the numpy fallback
      const char* field = p;
      for (const char* c = p; c <= stripped && col < cols; ++c) {
        if (c == stripped || *c == delim) {
          char* parse_end = nullptr;
          double v = c == field ? 0.0 : std::strtod(field, &parse_end);
          // strtod skips leading whitespace INCLUDING newlines, so a
          // blank field could otherwise swallow the next line's number;
          // any parse that left the field is treated as missing
          bool ok = c != field && parse_end != field && parse_end <= c;
          vals.push_back(ok ? v : std::nan(""));
          field = c + 1;
          ++col;
        }
      }
      while (col < cols) { vals.push_back(std::nan("")); ++col; }
      ++rows;
    }
    p = line_end < end ? line_end + 1 : end;
  }
  if (rows == 0) return 2;
  double* res = static_cast<double*>(
      std::malloc(sizeof(double) * vals.size()));
  if (!res) return 1;
  std::memcpy(res, vals.data(), sizeof(double) * vals.size());
  *out = res;
  *n_rows = rows;
  *n_cols = cols;
  return 0;
}

PARSER_API int ParseLibSVM(const char* path, double** out, double** labels,
                long* n_rows, long* n_cols) {
  std::string buf;
  if (!ReadAll(path, &buf)) return 1;
  const char* p = buf.data();
  const char* end = p + buf.size();

  struct Entry { long row; long col; double val; };
  std::vector<Entry> entries;
  std::vector<double> labs;
  entries.reserve(1 << 20);
  long max_col = -1;
  long rows = 0;
  while (p < end) {
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    const char* c = SkipSpaces(p, line_end);
    if (c < line_end) {
      char* parse_end = nullptr;
      double lab = std::strtod(c, &parse_end);
      if (parse_end == c) return 2;
      labs.push_back(lab);
      c = parse_end;
      while (c < line_end) {
        c = SkipSpaces(c, line_end);
        if (c >= line_end) break;
        char* colon_end = nullptr;
        long idx = std::strtol(c, &colon_end, 10);
        if (colon_end == c || colon_end >= line_end || *colon_end != ':'
            || idx < 0)  // negative index would write before the buffer
          break;
        c = colon_end + 1;
        double v = std::strtod(c, &parse_end);
        // bound the parse to this line ("3:" at end of line must not
        // swallow the next line's label)
        if (parse_end == c || parse_end > line_end) break;
        c = parse_end;
        entries.push_back({rows, idx, v});
        if (idx > max_col) max_col = idx;
      }
      ++rows;
    }
    p = line_end < end ? line_end + 1 : end;
  }
  if (rows == 0) return 2;
  long cols = max_col + 1;
  if (cols <= 0) cols = 1;
  double* res = static_cast<double*>(
      std::calloc(static_cast<size_t>(rows) * cols, sizeof(double)));
  double* lab_buf = static_cast<double*>(
      std::malloc(sizeof(double) * rows));
  if (!res || !lab_buf) { std::free(res); std::free(lab_buf); return 1; }
  for (const Entry& e : entries)
    res[e.row * cols + e.col] = e.val;
  std::memcpy(lab_buf, labs.data(), sizeof(double) * rows);
  *out = res;
  *labels = lab_buf;
  *n_rows = rows;
  *n_cols = cols;
  return 0;
}

// Greedy equal-count bin boundary search over (distinct value, count)
// pairs — the hot loop of BinMapper construction (reference:
// GreedyFindBin, src/io/bin.cpp:78-152). Must match the Python
// implementation in io/binning.py bit-for-bit: same double arithmetic,
// same nextafter-based dedup of boundaries.
//
// out must have room for max_bin + 1 doubles; returns the number of
// bounds written (the last one is +inf).
PARSER_API int GreedyFindBin(const double* distinct_values, const double* counts,
                  long num_distinct, int max_bin, double total_cnt,
                  int min_data_in_bin, double* out) {
  const double kInf = std::numeric_limits<double>::infinity();
  int n_out = 0;
  auto push_bound = [&](double val) {
    if (n_out == 0 || val > std::nextafter(out[n_out - 1], kInf)) {
      out[n_out++] = val;
    }
  };
  if (num_distinct <= max_bin) {
    double cur_cnt_inbin = 0;
    for (long i = 0; i < num_distinct - 1; ++i) {
      cur_cnt_inbin += counts[i];
      if (cur_cnt_inbin >= min_data_in_bin) {
        double mid = (distinct_values[i] + distinct_values[i + 1]) / 2.0;
        double val = std::nextafter(mid, kInf);
        int before = n_out;
        push_bound(val);
        if (n_out > before) cur_cnt_inbin = 0;
      }
    }
    out[n_out++] = kInf;
    return n_out;
  }

  if (min_data_in_bin > 0) {
    long cap = static_cast<long>(total_cnt) / min_data_in_bin;
    if (cap < max_bin) max_bin = static_cast<int>(cap);
    if (max_bin < 1) max_bin = 1;
  }
  double mean_bin_size = total_cnt / max_bin;

  std::vector<char> is_big(num_distinct);
  long n_big = 0;
  double big_cnt = 0;
  for (long i = 0; i < num_distinct; ++i) {
    is_big[i] = counts[i] >= mean_bin_size;
    if (is_big[i]) { ++n_big; big_cnt += counts[i]; }
  }
  long rest_bin_cnt = max_bin - n_big;
  double rest_sample_cnt = total_cnt - big_cnt;
  mean_bin_size = rest_sample_cnt /
      (rest_bin_cnt > 1 ? rest_bin_cnt : 1);

  std::vector<double> upper_bounds(max_bin, kInf);
  std::vector<double> lower_bounds(max_bin, kInf);
  int bin_cnt = 0;
  lower_bounds[0] = distinct_values[0];
  double cur_cnt_inbin = 0;
  for (long i = 0; i < num_distinct - 1; ++i) {
    if (!is_big[i]) rest_sample_cnt -= counts[i];
    cur_cnt_inbin += counts[i];
    double half = mean_bin_size * 0.5;
    if (half < 1.0) half = 1.0;
    if (is_big[i] || cur_cnt_inbin >= mean_bin_size ||
        (is_big[i + 1] && cur_cnt_inbin >= half)) {
      upper_bounds[bin_cnt] = distinct_values[i];
      ++bin_cnt;
      lower_bounds[bin_cnt] = distinct_values[i + 1];
      if (bin_cnt >= max_bin - 1) break;
      cur_cnt_inbin = 0;
      if (!is_big[i]) {
        --rest_bin_cnt;
        mean_bin_size = rest_sample_cnt /
            (rest_bin_cnt > 1 ? rest_bin_cnt : 1);
      }
    }
  }
  ++bin_cnt;
  for (int i = 0; i < bin_cnt - 1; ++i) {
    double mid = (upper_bounds[i] + lower_bounds[i + 1]) / 2.0;
    push_bound(std::nextafter(mid, kInf));
  }
  out[n_out++] = kInf;
  return n_out;
}

}  // extern "C"
