/*
 * C API for the lightgbm_tpu inference runtime (_capi.so).
 *
 * Predict-side surface of the reference's C API
 * (reference: include/LightGBM/c_api.h): load a v3 model text file —
 * produced by this framework or by the original implementation, the
 * formats interchange bit-exactly — and run dense/CSR prediction from
 * any C host with no Python runtime. Training entry points are Python
 * by design (docs/PARITY.md, layer 8).
 *
 * All functions return 0 on success, nonzero on failure;
 * LGBM_GetLastError() describes the most recent failure on this thread.
 */
#ifndef LIGHTGBM_TPU_CAPI_H_
#define LIGHTGBM_TPU_CAPI_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* BoosterHandle;

/* data_type values for prediction inputs */
#define C_API_DTYPE_FLOAT32 (0)
#define C_API_DTYPE_FLOAT64 (1)
#define C_API_DTYPE_INT32   (2)
#define C_API_DTYPE_INT64   (3)

/* predict_type values */
#define C_API_PREDICT_NORMAL     (0)  /* transformed score */
#define C_API_PREDICT_RAW_SCORE  (1)
#define C_API_PREDICT_LEAF_INDEX (2)
#define C_API_PREDICT_CONTRIB    (3)  /* SHAP values, last col = bias */

const char* LGBM_GetLastError(void);

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);
int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);
int LGBM_BoosterFree(BoosterHandle handle);
int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);
int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len);
int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                    int* out_iteration);
int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                               int predict_type, int start_iteration,
                               int num_iteration, int64_t* out_len);
int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result);
int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result);
int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle,
                                       const void* data, int data_type,
                                       int32_t ncol, int is_row_major,
                                       int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result);
int LGBM_BoosterPredictForCSRSingleRow(BoosterHandle handle,
                                       const void* indptr,
                                       int indptr_type,
                                       const int32_t* indices,
                                       const void* data, int data_type,
                                       int64_t nindptr, int64_t nelem,
                                       int64_t num_col, int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char* parameter,
                                       int64_t* out_len,
                                       double* out_result);
int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          const char* filename);
int LGBM_BoosterSaveModelToString(BoosterHandle handle,
                                  int start_iteration, int num_iteration,
                                  int feature_importance_type,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str);
int LGBM_BoosterPredictForCSC(BoosterHandle handle, const void* col_ptr,
                              int col_ptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result);
int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                              int64_t* out_len);
int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double* out_val);
int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double val);
int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header, int predict_type,
                               int start_iteration, int num_iteration,
                               const char* parameter,
                               const char* result_filename);
int LGBM_BoosterDumpModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str);
int LGBM_BoosterGetFeatureNames(BoosterHandle handle, int len,
                                int* out_len, size_t buffer_len,
                                size_t* out_buffer_len, char** out_strs);

#ifdef __cplusplus
}
#endif

#endif  /* LIGHTGBM_TPU_CAPI_H_ */
