// C-ABI inference runtime over the v3 model text format.
//
// External-engine counterpart of the reference's C API
// (reference: include/LightGBM/c_api.h, src/c_api.cpp): a C/C++/R/Java
// host can load a model file produced by this framework OR by the
// reference (the text formats interchange bit-exactly,
// tests/test_reference_parity.py) and run prediction with no Python
// runtime at all. Function names and signatures follow the reference's
// predict-side surface so existing C clients re-link against this
// library unchanged; training-side entry points live in the Python
// runtime by design (docs/PARITY.md layer 8).
//
// Semantics mirrored here (and cross-checked by tests/test_c_api.py
// against the Python predictor bit-for-bit):
//  - numerical/categorical decisions incl. missing-value routing
//    (reference: include/LightGBM/tree.h:133 Predict,
//    NumericalDecision/CategoricalDecision; missing bits 2-3 of
//    decision_type, default-left bit 1, categorical bit 0)
//  - categorical bitset membership (reference: common.h FindInBitset)
//  - piecewise-linear leaves with NaN fallback to the constant
//    (reference: src/treelearner/linear_tree_learner.cpp predict)
//  - objective raw->output transforms (reference:
//    ObjectiveFunction::ConvertOutput per objective file)
//  - random-forest score averaging (average_output header flag)
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -o _capi.so capi.cpp
#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

#define LGBM_EXPORT extern "C" __attribute__((visibility("default")))

namespace {

thread_local std::string g_last_error = "everything is fine";

constexpr double kZeroThreshold = 1e-35;  // io/binning.py:25
constexpr int kCategoricalMask = 1;
constexpr int kDefaultLeftMask = 2;
enum MissingType { kMissingNone = 0, kMissingZero = 1, kMissingNaN = 2 };

// predict_type values (reference: c_api.h C_API_PREDICT_*)
enum { kPredictNormal = 0, kPredictRaw = 1, kPredictLeaf = 2,
       kPredictContrib = 3 };
// data_type values (reference: c_api.h C_API_DTYPE_*)
enum { kDtypeF32 = 0, kDtypeF64 = 1, kDtypeI32 = 2, kDtypeI64 = 3 };

enum Transform { kIdentity, kSigmoid, kSoftmax, kExp, kSignSquare,
                 kLog1pExp };

struct CTree {
  int num_leaves = 1;
  std::vector<int> split_feature, left_child, right_child;
  std::vector<double> threshold, leaf_value;
  std::vector<int8_t> decision_type;
  std::vector<int> threshold_in_bin;  // cat-split index for cat nodes
  std::vector<int64_t> cat_boundaries;
  std::vector<uint32_t> cat_threshold;
  // data-coverage weights for SHAP (reference: tree.h data_count(node))
  std::vector<double> leaf_count, internal_count;
  // kept for DumpModel JSON (absent lines stay empty -> zeros)
  std::vector<double> split_gain, internal_value, internal_weight,
      leaf_weight;
  double shrinkage = 1.0;
  // prepared once at load time (PrepareShap): clamped coverage weights
  // (mirroring models/shap.py _node_count's max(count, 1)), the
  // cover-weighted expected value, and the flat-path capacity
  std::vector<double> shap_leaf_w, shap_node_w;
  double shap_expected = 0.0;
  size_t shap_path_capacity = 2;
  // linear leaves
  bool is_linear = false;
  std::vector<double> leaf_const;
  std::vector<std::vector<int>> leaf_features;
  std::vector<std::vector<double>> leaf_coeff;

  bool CatContains(int cat_idx, double fval) const {
    if (std::isnan(fval)) return false;
    int iv = static_cast<int>(fval);
    if (iv < 0) return false;
    int64_t lo = cat_boundaries[cat_idx];
    int64_t hi = cat_boundaries[cat_idx + 1];
    int64_t word = lo + iv / 32;
    if (word >= hi) return false;
    return (cat_threshold[word] >> (iv % 32)) & 1u;
  }

  // returns ~leaf walk; row is a dense feature vector (NaN = missing)
  int PredictLeaf(const double* row, int ncol) const {
    if (num_leaves <= 1) return 0;
    int node = 0;
    while (node >= 0) {
      int f = split_feature[node];
      double fval = (f < ncol) ? row[f] : std::nan("");
      int dt = decision_type[node];
      bool go_left;
      if (dt & kCategoricalMask) {
        go_left = CatContains(threshold_in_bin[node], fval);
      } else {
        int missing = (dt >> 2) & 3;
        bool default_left = dt & kDefaultLeftMask;
        bool is_nan = std::isnan(fval);
        double v = (is_nan && missing != kMissingNaN) ? 0.0 : fval;
        if (missing == kMissingZero && std::fabs(v) <= kZeroThreshold) {
          go_left = default_left;
        } else if (missing == kMissingNaN && is_nan) {
          go_left = default_left;
        } else {
          go_left = v <= threshold[node];
        }
      }
      node = go_left ? left_child[node] : right_child[node];
    }
    return ~node;
  }

  void PrepareShap() {
    int ni = num_leaves - 1;
    if ((int)leaf_count.size() >= num_leaves) {
      shap_leaf_w.resize(num_leaves);
      for (int l = 0; l < num_leaves; ++l)
        shap_leaf_w[l] = std::max(leaf_count[l], 1.0);
    } else {
      shap_leaf_w.assign(std::max(num_leaves, 1), 1.0);
    }
    if (ni <= 0) {
      shap_expected = leaf_value.empty() ? 0.0 : leaf_value[0];
      return;
    }
    if ((int)internal_count.size() >= ni) {
      shap_node_w.resize(ni);
      for (int j = 0; j < ni; ++j)
        shap_node_w[j] = std::max(internal_count[j], 1.0);
    } else {
      // bottom-up sums of leaf mass (a child internal node always has
      // a larger index than its parent — creation order)
      shap_node_w.assign(ni, 0.0);
      for (int j = ni - 1; j >= 0; --j) {
        int l = left_child[j], r = right_child[j];
        shap_node_w[j] = (l >= 0 ? shap_node_w[l] : shap_leaf_w[~l]) +
                         (r >= 0 ? shap_node_w[r] : shap_leaf_w[~r]);
      }
    }
    // expected value: RAW-count weighted leaf mean, unweighted when the
    // counts are absent/zero (models/shap.py _expected_value)
    double total = 0.0, acc = 0.0, plain = 0.0;
    for (int l = 0; l < num_leaves; ++l) {
      double c = (int)leaf_count.size() > l ? leaf_count[l] : 0.0;
      total += c;
      acc += c * leaf_value[l];
      plain += leaf_value[l];
    }
    shap_expected = total > 0 ? acc / total : plain / num_leaves;
    // flat path buffer: level d's segment starts after sum_{k<d}(k+1)
    // elements (reference TreeSHAP's parent_unique_path advance)
    std::vector<int> depth_of(ni, 0);
    int max_depth = 0;
    for (int j = 0; j < ni; ++j) {
      for (int child : {left_child[j], right_child[j]}) {
        int d = depth_of[j] + 1;
        if (child >= 0) depth_of[child] = d;
        if (d > max_depth) max_depth = d;
      }
    }
    size_t D = max_depth + 2;
    shap_path_capacity = (D + 1) * (D + 2) / 2 + D + 2;
  }

  double PredictValue(const double* row, int ncol) const {
    int leaf = PredictLeaf(row, ncol);
    if (is_linear) {
      // unfitted leaves (no features) and NaN rows keep the constant
      // leaf_value — NOT leaf_const, which misses later AddBias shifts
      // (reference: linear predict falls back to the leaf output)
      const auto& feats = leaf_features[leaf];
      if (!feats.empty()) {
        double out = leaf_const[leaf];
        bool ok = true;
        for (size_t j = 0; j < feats.size(); ++j) {
          double fv = (feats[j] < ncol) ? row[feats[j]] : std::nan("");
          if (std::isnan(fv)) { ok = false; break; }
          out += leaf_coeff[leaf][j] * fv;
        }
        if (ok) return out;
      }
    }
    return leaf_value[leaf];
  }
};

template <typename T>
std::vector<T> ParseArray(const std::string& s) {
  std::vector<T> out;
  std::istringstream is(s);
  if constexpr (std::is_same_v<T, int8_t>) {
    int v;  // int8 must parse as integer text, not raw char
    while (is >> v) out.push_back(static_cast<int8_t>(v));
  } else {
    T v;
    while (is >> v) out.push_back(v);
  }
  return out;
}

struct CBooster {
  int num_class = 1;
  int num_tree_per_iteration = 1;
  int max_feature_idx = 0;
  bool average_output = false;
  Transform transform = kIdentity;
  double sigmoid = 1.0;
  int label_index = 0;
  std::string objective_str;
  std::vector<int> monotone_constraints;
  std::vector<std::string> feature_infos;
  std::vector<std::string> feature_names;
  std::vector<CTree> trees;
  std::string raw_model;  // original text, for SaveModel round-trip

  int NumIterations() const {
    return static_cast<int>(trees.size()) / num_tree_per_iteration;
  }

  // trees [start_iteration, start_iteration + num_iteration) in
  // iteration units; num_iteration <= 0 means "to the end"
  void UsedRange(int start_iteration, int num_iteration,
                 int* t0, int* t1) const {
    int total = NumIterations();
    int s = std::max(start_iteration, 0);
    int n = (num_iteration <= 0) ? total - s
                                 : std::min(num_iteration, total - s);
    n = std::max(n, 0);
    *t0 = s * num_tree_per_iteration;
    *t1 = (s + n) * num_tree_per_iteration;
  }

  void PredictRawRow(const double* row, int ncol, int t0, int t1,
                     double* out) const {
    for (int k = 0; k < num_class; ++k) out[k] = 0.0;
    for (int i = t0; i < t1; ++i) {
      out[i % num_tree_per_iteration] += trees[i].PredictValue(row, ncol);
    }
    if (average_output && t1 > t0) {
      double denom = double(t1 - t0) / num_tree_per_iteration;
      for (int k = 0; k < num_class; ++k) out[k] /= denom;
    }
  }

  void ApplyTransform(double* out) const {
    switch (transform) {
      case kIdentity:
        break;
      case kSigmoid:
        for (int k = 0; k < num_class; ++k)
          out[k] = 1.0 / (1.0 + std::exp(-sigmoid * out[k]));
        break;
      case kSoftmax: {
        double m = out[0];
        for (int k = 1; k < num_class; ++k) m = std::max(m, out[k]);
        double sum = 0.0;
        for (int k = 0; k < num_class; ++k) {
          out[k] = std::exp(out[k] - m);
          sum += out[k];
        }
        for (int k = 0; k < num_class; ++k) out[k] /= sum;
        break;
      }
      case kExp:
        for (int k = 0; k < num_class; ++k) out[k] = std::exp(out[k]);
        break;
      case kSignSquare:
        for (int k = 0; k < num_class; ++k)
          out[k] = (out[k] < 0 ? -1.0 : 1.0) * out[k] * out[k];
        break;
      case kLog1pExp:
        for (int k = 0; k < num_class; ++k)
          out[k] = std::log1p(std::exp(out[k]));
        break;
    }
  }
};

bool ParseTree(const std::map<std::string, std::string>& kv, CTree* t,
               std::string* err) {
  auto get = [&](const char* k) -> const std::string* {
    auto it = kv.find(k);
    return it == kv.end() ? nullptr : &it->second;
  };
  const std::string* nl = get("num_leaves");
  if (!nl) { *err = "tree block missing num_leaves"; return false; }
  t->num_leaves = std::atoi(nl->c_str());
  if (t->num_leaves <= 1) {
    t->leaf_value = {get("leaf_value") ? std::atof(get("leaf_value")->c_str())
                                       : 0.0};
    t->num_leaves = 1;
  } else {
    int ni = t->num_leaves - 1;
    for (const char* k : {"split_feature", "threshold", "decision_type",
                          "left_child", "right_child", "leaf_value"}) {
      if (!get(k)) {
        *err = std::string("tree block missing ") + k;
        return false;
      }
    }
    t->split_feature = ParseArray<int>(*get("split_feature"));
    t->threshold = ParseArray<double>(*get("threshold"));
    t->decision_type = ParseArray<int8_t>(*get("decision_type"));
    t->left_child = ParseArray<int>(*get("left_child"));
    t->right_child = ParseArray<int>(*get("right_child"));
    t->leaf_value = ParseArray<double>(*get("leaf_value"));
    if ((int)t->split_feature.size() < ni ||
        (int)t->threshold.size() < ni ||
        (int)t->decision_type.size() < ni ||
        (int)t->left_child.size() < ni ||
        (int)t->right_child.size() < ni ||
        (int)t->leaf_value.size() < t->num_leaves) {
      *err = "tree block has truncated arrays";
      return false;
    }
    for (int j = 0; j < ni; ++j) {
      // child pointers: >=0 internal node index, <0 encodes leaf ~idx
      // internal children must have a LARGER index than the parent
      // (creation order, tree.h Split numbering) — also rules out
      // cycles that would hang PredictLeaf's walk
      if (t->left_child[j] >= ni || t->left_child[j] < -t->num_leaves ||
          t->right_child[j] >= ni || t->right_child[j] < -t->num_leaves ||
          (t->left_child[j] >= 0 && t->left_child[j] <= j) ||
          (t->right_child[j] >= 0 && t->right_child[j] <= j) ||
          t->split_feature[j] < 0) {
        *err = "tree block has out-of-range node indices";
        return false;
      }
    }
    if (get("leaf_count"))
      t->leaf_count = ParseArray<double>(*get("leaf_count"));
    if (get("internal_count"))
      t->internal_count = ParseArray<double>(*get("internal_count"));
    if (get("split_gain"))
      t->split_gain = ParseArray<double>(*get("split_gain"));
    if (get("internal_value"))
      t->internal_value = ParseArray<double>(*get("internal_value"));
    if (get("internal_weight"))
      t->internal_weight = ParseArray<double>(*get("internal_weight"));
    if (get("leaf_weight"))
      t->leaf_weight = ParseArray<double>(*get("leaf_weight"));
    // cat nodes keep the cat-split index in `threshold`
    t->threshold_in_bin.assign(ni, 0);
    if (get("cat_boundaries")) {
      t->cat_boundaries = ParseArray<int64_t>(*get("cat_boundaries"));
      if (get("cat_threshold"))
        t->cat_threshold = ParseArray<uint32_t>(*get("cat_threshold"));
      for (size_t k = 1; k < t->cat_boundaries.size(); ++k) {
        if (t->cat_boundaries[k] < t->cat_boundaries[k - 1] ||
            t->cat_boundaries[k] > (int64_t)t->cat_threshold.size()) {
          *err = "tree block has inconsistent cat_boundaries";
          return false;
        }
      }
    }
    for (int j = 0; j < ni; ++j) {
      if (t->decision_type[j] & kCategoricalMask) {
        int ci = static_cast<int>(t->threshold[j]);
        if (ci < 0 || ci + 1 >= (int)t->cat_boundaries.size()) {
          *err = "tree block has categorical node without bitset";
          return false;
        }
        t->threshold_in_bin[j] = ci;
      }
    }
  }
  if (get("shrinkage")) t->shrinkage = std::atof(get("shrinkage")->c_str());
  const std::string* lin = get("is_linear");
  if (lin && std::atoi(lin->c_str())) {
    if (!get("leaf_const")) {
      *err = "linear tree block missing leaf_const";
      return false;
    }
    t->is_linear = true;
    t->leaf_const = ParseArray<double>(*get("leaf_const"));
    if ((int)t->leaf_const.size() < t->num_leaves) {
      *err = "linear tree block has truncated leaf_const";
      return false;
    }
    std::vector<int> nfeat = get("num_features")
        ? ParseArray<int>(*get("num_features")) : std::vector<int>();
    std::vector<int> flat_f = get("leaf_features")
        ? ParseArray<int>(*get("leaf_features")) : std::vector<int>();
    std::vector<double> flat_c = get("leaf_coeff")
        ? ParseArray<double>(*get("leaf_coeff")) : std::vector<double>();
    size_t pos = 0;
    for (int c : nfeat) {
      if (c < 0 || pos + c > flat_f.size() || pos + c > flat_c.size()) {
        *err = "linear tree block has truncated leaf features";
        return false;
      }
      t->leaf_features.emplace_back(flat_f.begin() + pos,
                                    flat_f.begin() + pos + c);
      t->leaf_coeff.emplace_back(flat_c.begin() + pos,
                                 flat_c.begin() + pos + c);
      pos += c;
    }
    t->leaf_features.resize(t->num_leaves);
    t->leaf_coeff.resize(t->num_leaves);
  }
  return true;
}

bool SetObjective(const std::string& spec, CBooster* b, std::string* err) {
  std::istringstream is(spec);
  std::string name, tok;
  is >> name;
  double sigmoid = 1.0;
  while (is >> tok) {
    if (tok.rfind("sigmoid:", 0) == 0)
      sigmoid = std::atof(tok.c_str() + 8);
    // num_class:/alpha:/etc. don't affect ConvertOutput
  }
  b->sigmoid = sigmoid;
  if (name == "binary" || name == "cross_entropy" ||
      name == "multiclassova" || name == "xentropy") {
    b->transform = kSigmoid;
  } else if (name == "multiclass" || name == "softmax") {
    b->transform = kSoftmax;
  } else if (name == "poisson" || name == "gamma" || name == "tweedie") {
    b->transform = kExp;
  } else if (name == "cross_entropy_lambda" || name == "xentlambda") {
    b->transform = kLog1pExp;
  } else if (name == "regression" && spec.find("sqrt") != std::string::npos) {
    b->transform = kSignSquare;
  } else {
    b->transform = kIdentity;  // l2/l1/huber/fair/quantile/mape/ranking
  }
  (void)err;
  return true;
}

CBooster* LoadFromString(const std::string& s, std::string* err) {
  auto b = std::make_unique<CBooster>();
  b->raw_model = s;
  std::istringstream is(s);
  std::string line;
  auto getline_crlf = [&](std::string& out) -> bool {
    if (!std::getline(is, out)) return false;
    if (!out.empty() && out.back() == '\r') out.pop_back();
    return true;
  };
  // header until the first Tree= block
  while (getline_crlf(line)) {
    if (line.rfind("Tree=", 0) == 0) break;
    if (line == "average_output") { b->average_output = true; continue; }
    auto eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string k = line.substr(0, eq), v = line.substr(eq + 1);
    if (k == "num_class") b->num_class = std::atoi(v.c_str());
    else if (k == "num_tree_per_iteration")
      b->num_tree_per_iteration = std::atoi(v.c_str());
    else if (k == "max_feature_idx") b->max_feature_idx = std::atoi(v.c_str());
    else if (k == "label_index") b->label_index = std::atoi(v.c_str());
    else if (k == "objective") {
      b->objective_str = v;
      if (!SetObjective(v, b.get(), err)) return nullptr;
    } else if (k == "monotone_constraints") {
      std::istringstream ms(v);
      int mc;
      while (ms >> mc) b->monotone_constraints.push_back(mc);
    } else if (k == "feature_infos") {
      std::istringstream fs(v);
      std::string info;
      while (fs >> info) b->feature_infos.push_back(info);
    } else if (k == "feature_names") {
      std::istringstream ns(v);
      std::string n;
      while (ns >> n) b->feature_names.push_back(n);
    }
  }
  if (line.rfind("Tree=", 0) != 0) {
    *err = "no Tree= blocks found (not a model file?)";
    return nullptr;
  }
  // tree blocks: key=value lines until blank/next Tree=/end of trees
  std::map<std::string, std::string> kv;
  auto flush = [&]() -> bool {
    if (kv.empty()) return true;
    CTree t;
    if (!ParseTree(kv, &t, err)) return false;
    // feature indices size the caller's contrib buffer
    // (max_feature_idx + 2 per class) — an index past the header's
    // bound would write out of that buffer in the SHAP path
    for (int j = 0; j < t.num_leaves - 1; ++j) {
      if (t.split_feature[j] > b->max_feature_idx) {
        *err = "tree split_feature exceeds header max_feature_idx";
        return false;
      }
    }
    t.PrepareShap();
    b->trees.push_back(std::move(t));
    kv.clear();
    return true;
  };
  while (getline_crlf(line)) {
    if (line.rfind("Tree=", 0) == 0) {
      if (!flush()) return nullptr;
      continue;
    }
    if (line == "end of trees") break;
    auto eq = line.find('=');
    if (eq != std::string::npos)
      kv[line.substr(0, eq)] = line.substr(eq + 1);
  }
  if (!flush()) return nullptr;
  if (b->trees.empty()) { *err = "model has no trees"; return nullptr; }
  if (b->num_class < 1) b->num_class = 1;
  if (b->num_tree_per_iteration < 1) b->num_tree_per_iteration = 1;
  if (b->num_tree_per_iteration > b->num_class) {
    // output stride is num_class; a larger ntpi would write past the
    // caller's buffer in PredictRawRow (out[i % ntpi])
    *err = "num_tree_per_iteration exceeds num_class";
    return nullptr;
  }
  return b.release();
}

int64_t PredictOutputLen(const CBooster* b, int64_t nrow, int predict_type,
                         int t0, int t1) {
  if (predict_type == kPredictLeaf) return nrow * (t1 - t0);
  if (predict_type == kPredictContrib)
    return nrow * b->num_class * (b->max_feature_idx + 2);
  return nrow * b->num_class;
}

// SHAP feature contributions via per-leaf path attribution
// (reference: src/io/tree.cpp TreeSHAP / PredictContrib). Exact
// TreeSHAP (Lundberg's EXPVALUE recursion over weight-extended paths).
struct PathElem {
  int feature_index;
  double zero_fraction, one_fraction, pweight;
};

void ExtendPath(PathElem* path, int depth,
                double zero_fraction, double one_fraction,
                int feature_index) {
  path[depth] = {feature_index, zero_fraction, one_fraction,
                 depth == 0 ? 1.0 : 0.0};
  for (int i = depth - 1; i >= 0; --i) {
    path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1)
                           / double(depth + 1);
    path[i].pweight = zero_fraction * path[i].pweight * (depth - i)
                      / double(depth + 1);
  }
}

void UnwindPath(PathElem* path, int depth, int index) {
  double one_fraction = path[index].one_fraction;
  double zero_fraction = path[index].zero_fraction;
  double next_one = path[depth].pweight;
  for (int i = depth - 1; i >= 0; --i) {
    if (one_fraction != 0) {
      double tmp = path[i].pweight;
      path[i].pweight = next_one * (depth + 1)
                        / (double(i + 1) * one_fraction);
      next_one = tmp - path[i].pweight * zero_fraction * (depth - i)
                       / double(depth + 1);
    } else {
      path[i].pweight = path[i].pweight * (depth + 1)
                        / (zero_fraction * (depth - i));
    }
  }
  for (int i = index; i < depth; ++i) {
    path[i].feature_index = path[i + 1].feature_index;
    path[i].zero_fraction = path[i + 1].zero_fraction;
    path[i].one_fraction = path[i + 1].one_fraction;
  }
}

double UnwoundPathSum(const PathElem* path, int depth,
                      int index) {
  double one_fraction = path[index].one_fraction;
  double zero_fraction = path[index].zero_fraction;
  double next_one = path[depth].pweight;
  double total = 0;
  for (int i = depth - 1; i >= 0; --i) {
    if (one_fraction != 0) {
      double tmp = next_one * (depth + 1)
                   / (double(i + 1) * one_fraction);
      total += tmp;
      next_one = path[i].pweight - tmp * zero_fraction * (depth - i)
                                   / double(depth + 1);
    } else if (zero_fraction != 0) {
      total += (path[i].pweight / zero_fraction)
               / ((depth - i) / double(depth + 1));
    }
  }
  return total;
}

struct ShapContext {
  const CTree* tree;
  const double* row;
  int ncol;
  double* contribs;  // length ncol+1; last = expected value
  // one flat buffer per predict call; each recursion level claims the
  // segment after its parent's (reference: src/io/tree.cpp TreeSHAP's
  // parent_unique_path + unique_depth + 1 advance), so a child's
  // duplicate-unwind never corrupts the path its parent hands to the
  // sibling
  std::vector<PathElem> storage;
};

double NodeWeight(const ShapContext& ctx, int node) {
  return node >= 0 ? ctx.tree->shap_node_w[node]
                   : ctx.tree->shap_leaf_w[~node];
}

void TreeShapRecurse(ShapContext& ctx, int node, PathElem* parent_path,
                     int depth, double zero_fraction, double one_fraction,
                     int parent_feature) {
  PathElem* path = parent_path + depth + 1;  // fresh copy per level
  std::copy(parent_path, parent_path + depth + 1, path);
  ExtendPath(path, depth, zero_fraction, one_fraction, parent_feature);
  if (node < 0) {  // leaf
    double v = ctx.tree->leaf_value[~node];
    for (int i = 1; i <= depth; ++i) {
      double w = UnwoundPathSum(path, depth, i);
      ctx.contribs[path[i].feature_index] +=
          w * (path[i].one_fraction - path[i].zero_fraction) * v;
    }
    return;
  }
  const CTree* t = ctx.tree;
  int f = t->split_feature[node];
  double fval = (f < ctx.ncol) ? ctx.row[f] : std::nan("");
  int dt = t->decision_type[node];
  bool go_left;
  if (dt & kCategoricalMask) {
    go_left = t->CatContains(t->threshold_in_bin[node], fval);
  } else {
    int missing = (dt >> 2) & 3;
    bool default_left = dt & kDefaultLeftMask;
    bool is_nan = std::isnan(fval);
    double v = (is_nan && missing != kMissingNaN) ? 0.0 : fval;
    if (missing == kMissingZero && std::fabs(v) <= kZeroThreshold)
      go_left = default_left;
    else if (missing == kMissingNaN && is_nan)
      go_left = default_left;
    else
      go_left = v <= t->threshold[node];
  }
  int hot = go_left ? t->left_child[node] : t->right_child[node];
  int cold = go_left ? t->right_child[node] : t->left_child[node];
  double w = NodeWeight(ctx, node);
  double hot_frac = w > 0 ? NodeWeight(ctx, hot) / w : 0.0;
  double cold_frac = w > 0 ? NodeWeight(ctx, cold) / w : 0.0;
  // if this feature is already on the path, undo and merge fractions
  double incoming_zero = 1.0, incoming_one = 1.0;
  int path_index = 0;
  for (; path_index <= depth; ++path_index) {
    if (path[path_index].feature_index == f) break;
  }
  if (path_index != depth + 1) {
    incoming_zero = path[path_index].zero_fraction;
    incoming_one = path[path_index].one_fraction;
    UnwindPath(path, depth, path_index);
    depth -= 1;
  }
  TreeShapRecurse(ctx, hot, path, depth + 1, hot_frac * incoming_zero,
                  incoming_one, f);
  TreeShapRecurse(ctx, cold, path, depth + 1, cold_frac * incoming_zero,
                  0.0, f);
}

}  // namespace

// ---------------------------------------------------------------------
// exported C surface
// ---------------------------------------------------------------------

LGBM_EXPORT const char* LGBM_GetLastError() { return g_last_error.c_str(); }

static int Fail(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

LGBM_EXPORT int LGBM_BoosterLoadModelFromString(const char* model_str,
                                                int* out_num_iterations,
                                                void** out) {
  if (!model_str || !out) return Fail("null argument");
  std::string err;
  CBooster* b = LoadFromString(model_str, &err);
  if (!b) return Fail(err);
  if (out_num_iterations) *out_num_iterations = b->NumIterations();
  *out = b;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterCreateFromModelfile(const char* filename,
                                                int* out_num_iterations,
                                                void** out) {
  if (!filename || !out) return Fail("null argument");
  std::ifstream f(filename, std::ios::binary);
  if (!f) return Fail(std::string("cannot open ") + filename);
  std::ostringstream ss;
  ss << f.rdbuf();
  return LGBM_BoosterLoadModelFromString(ss.str().c_str(),
                                         out_num_iterations, out);
}

LGBM_EXPORT int LGBM_BoosterFree(void* handle) {
  delete static_cast<CBooster*>(handle);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumClasses(void* handle, int* out_len) {
  if (!handle || !out_len) return Fail("null argument");
  *out_len = static_cast<CBooster*>(handle)->num_class;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumFeature(void* handle, int* out_len) {
  if (!handle || !out_len) return Fail("null argument");
  *out_len = static_cast<CBooster*>(handle)->max_feature_idx + 1;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetCurrentIteration(void* handle,
                                                int* out_iteration) {
  if (!handle || !out_iteration) return Fail("null argument");
  *out_iteration = static_cast<CBooster*>(handle)->NumIterations();
  return 0;
}

LGBM_EXPORT int LGBM_BoosterCalcNumPredict(void* handle, int num_row,
                                           int predict_type,
                                           int start_iteration,
                                           int num_iteration,
                                           int64_t* out_len) {
  if (!handle || !out_len) return Fail("null argument");
  auto* b = static_cast<CBooster*>(handle);
  int t0, t1;
  b->UsedRange(start_iteration, num_iteration, &t0, &t1);
  *out_len = PredictOutputLen(b, num_row, predict_type, t0, t1);
  return 0;
}

static void PredictRowInto(const CBooster* b, const double* row, int ncol,
                           int predict_type, int t0, int t1, double* out,
                           ShapContext* scratch = nullptr) {
  if (predict_type == kPredictLeaf) {
    for (int i = t0; i < t1; ++i)
      out[i - t0] = b->trees[i].PredictLeaf(row, ncol);
    return;
  }
  if (predict_type == kPredictContrib) {
    int ncontrib = b->max_feature_idx + 2;
    for (int k = 0; k < b->num_class; ++k)
      std::memset(out + k * ncontrib, 0, sizeof(double) * ncontrib);
    ShapContext local;
    ShapContext& ctx = scratch ? *scratch : local;
    ctx.row = row;
    ctx.ncol = ncol;
    for (int i = t0; i < t1; ++i) {
      const CTree& t = b->trees[i];
      double* cls_out = out + (i % b->num_tree_per_iteration) * ncontrib;
      cls_out[ncontrib - 1] += t.shap_expected;
      if (t.num_leaves <= 1) continue;
      ctx.tree = &t;
      ctx.contribs = cls_out;  // recursion touches feature slots only
      if (ctx.storage.size() < t.shap_path_capacity)
        ctx.storage.resize(t.shap_path_capacity);
      TreeShapRecurse(ctx, 0, ctx.storage.data(), 0, 1.0, 1.0, -1);
    }
    return;
  }
  // normal / raw
  b->PredictRawRow(row, ncol, t0, t1, out);
  if (predict_type == kPredictNormal) b->ApplyTransform(out);
}

LGBM_EXPORT int LGBM_BoosterPredictForMat(
    void* handle, const void* data, int data_type, int32_t nrow,
    int32_t ncol, int is_row_major, int predict_type, int start_iteration,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  (void)parameter;
  if (!handle || !data || !out_result) return Fail("null argument");
  if (data_type != kDtypeF32 && data_type != kDtypeF64)
    return Fail("data_type must be C_API_DTYPE_FLOAT32/64");
  auto* b = static_cast<CBooster*>(handle);
  int t0, t1;
  b->UsedRange(start_iteration, num_iteration, &t0, &t1);
  int64_t stride = PredictOutputLen(b, 1, predict_type, t0, t1);
  std::vector<double> row(ncol);
  ShapContext scratch;  // reused path storage across rows
  for (int32_t r = 0; r < nrow; ++r) {
    for (int32_t c = 0; c < ncol; ++c) {
      int64_t idx = is_row_major ? int64_t(r) * ncol + c
                                 : int64_t(c) * nrow + r;
      row[c] = (data_type == kDtypeF64)
                   ? static_cast<const double*>(data)[idx]
                   : static_cast<double>(
                         static_cast<const float*>(data)[idx]);
    }
    PredictRowInto(b, row.data(), ncol, predict_type, t0, t1,
                   out_result + r * stride, &scratch);
  }
  if (out_len) *out_len = nrow * stride;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterPredictForCSR(
    void* handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  (void)parameter;
  (void)nelem;
  if (!handle || !indptr || !indices || !data || !out_result)
    return Fail("null argument");
  auto* b = static_cast<CBooster*>(handle);
  int t0, t1;
  b->UsedRange(start_iteration, num_iteration, &t0, &t1);
  int64_t stride = PredictOutputLen(b, 1, predict_type, t0, t1);
  int64_t nrow = nindptr - 1;
  std::vector<double> row(num_col);
  ShapContext scratch;  // reused path storage across rows
  for (int64_t r = 0; r < nrow; ++r) {
    std::fill(row.begin(), row.end(), 0.0);
    int64_t lo, hi;
    if (indptr_type == kDtypeI64) {
      lo = static_cast<const int64_t*>(indptr)[r];
      hi = static_cast<const int64_t*>(indptr)[r + 1];
    } else {
      lo = static_cast<const int32_t*>(indptr)[r];
      hi = static_cast<const int32_t*>(indptr)[r + 1];
    }
    for (int64_t j = lo; j < hi; ++j) {
      int32_t c = indices[j];
      if (c >= 0 && c < num_col)
        row[c] = (data_type == kDtypeF64)
                     ? static_cast<const double*>(data)[j]
                     : static_cast<double>(
                           static_cast<const float*>(data)[j]);
    }
    PredictRowInto(b, row.data(), static_cast<int>(num_col), predict_type,
                   t0, t1, out_result + r * stride, &scratch);
  }
  if (out_len) *out_len = nrow * stride;
  return 0;
}

// single-row fast paths (reference: c_api.h PredictForMatSingleRow /
// PredictForCSRSingleRow — the serving hot path; same semantics as the
// batched calls with nrow == 1)
LGBM_EXPORT int LGBM_BoosterPredictForMatSingleRow(
    void* handle, const void* data, int data_type, int32_t ncol,
    int is_row_major, int predict_type, int start_iteration,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  return LGBM_BoosterPredictForMat(handle, data, data_type, 1, ncol,
                                   is_row_major, predict_type,
                                   start_iteration, num_iteration,
                                   parameter, out_len, out_result);
}

LGBM_EXPORT int LGBM_BoosterPredictForCSRSingleRow(
    void* handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  if (nindptr != 2) return Fail("single-row CSR requires nindptr == 2");
  return LGBM_BoosterPredictForCSR(handle, indptr, indptr_type, indices,
                                   data, data_type, nindptr, nelem,
                                   num_col, predict_type,
                                   start_iteration, num_iteration,
                                   parameter, out_len, out_result);
}

LGBM_EXPORT int LGBM_BoosterSaveModelToString(
    void* handle, int start_iteration, int num_iteration,
    int feature_importance_type, int64_t buffer_len, int64_t* out_len,
    char* out_str) {
  (void)feature_importance_type;
  if (!handle || !out_len) return Fail("null argument");
  auto* b = static_cast<CBooster*>(handle);
  if (b->raw_model.empty())
    return Fail("model was modified in memory (SetLeafValue); the "
                "verbatim text is gone — re-save from the training "
                "runtime instead");
  if (start_iteration != 0 ||
      (num_iteration > 0 && num_iteration < b->NumIterations()))
    return Fail("predict-side C API keeps the loaded model verbatim; "
                "slice iterations at predict time instead");
  *out_len = static_cast<int64_t>(b->raw_model.size()) + 1;
  if (out_str && buffer_len >= *out_len)
    std::memcpy(out_str, b->raw_model.c_str(), *out_len);
  return 0;
}

LGBM_EXPORT int LGBM_BoosterSaveModel(void* handle, int start_iteration,
                                      int num_iteration,
                                      int feature_importance_type,
                                      const char* filename) {
  (void)feature_importance_type;
  if (!handle || !filename) return Fail("null argument");
  auto* b = static_cast<CBooster*>(handle);
  if (b->raw_model.empty())
    return Fail("model was modified in memory (SetLeafValue); the "
                "verbatim text is gone — re-save from the training "
                "runtime instead");
  if (start_iteration != 0 ||
      (num_iteration > 0 && num_iteration < b->NumIterations()))
    return Fail("predict-side C API keeps the loaded model verbatim");
  std::ofstream f(filename, std::ios::binary);
  if (!f) return Fail(std::string("cannot write ") + filename);
  f << b->raw_model;
  return 0;
}

// CSC prediction: counting-sort the nonzeros into per-row (col, val)
// buckets in O(nnz), then run the same per-row walk as CSR — no dense
// materialization (reference: c_api.cpp PredictForCSC iterates columns
// through an adapter for the same reason)
LGBM_EXPORT int LGBM_BoosterPredictForCSC(
    void* handle, const void* col_ptr, int col_ptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t ncol_ptr, int64_t nelem, int64_t num_row, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  (void)parameter;
  if (!handle || !col_ptr || !indices || !data || !out_result)
    return Fail("null argument");
  if (data_type != kDtypeF32 && data_type != kDtypeF64)
    return Fail("data_type must be C_API_DTYPE_FLOAT32/64");
  int64_t ncol = ncol_ptr - 1;
  if (ncol < 0 || num_row < 0 || ncol > INT32_MAX || num_row > INT32_MAX)
    return Fail("bad CSC dimensions");
  auto colptr_at = [&](int64_t c) -> int64_t {
    return col_ptr_type == kDtypeI64
               ? static_cast<const int64_t*>(col_ptr)[c]
               : static_cast<const int64_t>(
                     static_cast<const int32_t*>(col_ptr)[c]);
  };
  int64_t nnz = colptr_at(ncol);
  if (nnz < 0 || nnz > nelem) return Fail("bad CSC col_ptr");
  // counting sort by row
  std::vector<int64_t> row_start(num_row + 1, 0);
  for (int64_t j = 0; j < nnz; ++j) {
    int32_t r = indices[j];
    if (r < 0 || r >= num_row) return Fail("CSC row index out of range");
    row_start[r + 1] += 1;
  }
  for (int64_t r = 0; r < num_row; ++r) row_start[r + 1] += row_start[r];
  std::vector<int32_t> row_col(nnz);
  std::vector<double> row_val(nnz);
  {
    std::vector<int64_t> cursor(row_start.begin(), row_start.end() - 1);
    for (int64_t c = 0; c < ncol; ++c) {
      for (int64_t j = colptr_at(c); j < colptr_at(c + 1); ++j) {
        int64_t pos = cursor[indices[j]]++;
        row_col[pos] = static_cast<int32_t>(c);
        row_val[pos] =
            (data_type == kDtypeF64)
                ? static_cast<const double*>(data)[j]
                : static_cast<double>(
                      static_cast<const float*>(data)[j]);
      }
    }
  }
  auto* b = static_cast<CBooster*>(handle);
  int t0, t1;
  b->UsedRange(start_iteration, num_iteration, &t0, &t1);
  int64_t stride = PredictOutputLen(b, 1, predict_type, t0, t1);
  std::vector<double> row(ncol, 0.0);
  ShapContext scratch;
  for (int64_t r = 0; r < num_row; ++r) {
    for (int64_t j = row_start[r]; j < row_start[r + 1]; ++j)
      row[row_col[j]] = row_val[j];
    PredictRowInto(b, row.data(), static_cast<int>(ncol), predict_type,
                   t0, t1, out_result + r * stride, &scratch);
    for (int64_t j = row_start[r]; j < row_start[r + 1]; ++j)
      row[row_col[j]] = 0.0;
  }
  if (out_len) *out_len = num_row * stride;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetNumPredict(void* handle, int data_idx,
                                          int64_t* out_len) {
  (void)data_idx;
  if (!handle || !out_len) return Fail("null argument");
  // prediction-only runtime: no attached datasets
  *out_len = 0;
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetLeafValue(void* handle, int tree_idx,
                                         int leaf_idx, double* out_val) {
  if (!handle || !out_val) return Fail("null argument");
  auto* b = static_cast<CBooster*>(handle);
  if (tree_idx < 0 || tree_idx >= (int)b->trees.size())
    return Fail("tree_idx out of range");
  const CTree& t = b->trees[tree_idx];
  if (leaf_idx < 0 || leaf_idx >= t.num_leaves)
    return Fail("leaf_idx out of range");
  *out_val = t.leaf_value[leaf_idx];
  return 0;
}

LGBM_EXPORT int LGBM_BoosterSetLeafValue(void* handle, int tree_idx,
                                         int leaf_idx, double val) {
  if (!handle) return Fail("null argument");
  auto* b = static_cast<CBooster*>(handle);
  if (tree_idx < 0 || tree_idx >= (int)b->trees.size())
    return Fail("tree_idx out of range");
  CTree& t = b->trees[tree_idx];
  if (leaf_idx < 0 || leaf_idx >= t.num_leaves)
    return Fail("leaf_idx out of range");
  t.leaf_value[leaf_idx] = val;
  t.PrepareShap();  // expected value depends on leaf values
  // the loaded text no longer matches the edited model; SaveModel*
  // return the error below rather than stale bytes
  b->raw_model.clear();
  return 0;
}

LGBM_EXPORT int LGBM_BoosterGetFeatureNames(void* handle, int len,
                                            int* out_len,
                                            size_t buffer_len,
                                            size_t* out_buffer_len,
                                            char** out_strs) {
  if (!handle || !out_len || !out_buffer_len) return Fail("null argument");
  auto* b = static_cast<CBooster*>(handle);
  *out_len = static_cast<int>(b->feature_names.size());
  size_t longest = 0;
  for (auto& n : b->feature_names) longest = std::max(longest, n.size() + 1);
  *out_buffer_len = longest;
  if (out_strs) {
    int n = std::min(len, *out_len);
    for (int i = 0; i < n; ++i) {
      std::snprintf(out_strs[i], buffer_len, "%s",
                    b->feature_names[i].c_str());
    }
  }
  return 0;
}

// ---------------------------------------------------------------------
// file prediction (reference: c_api.cpp LGBM_BoosterPredictForFile,
// backing the CLI predict task): parse a CSV/TSV/LibSVM file with the
// shared native parser and write one prediction line per row — a
// complete C-only deployment pipeline with no Python runtime.
#define PARSER_API __attribute__((visibility("hidden")))
#include "parser.cpp"  // ParseDense/ParseLibSVM/FreeBuffer (same TU,
                       // symbols hidden: _parser.so owns the exports)

namespace {

// format sniff mirroring the Python dispatch (application's loader):
// the SECOND whitespace token of the first data line looking like
// "idx:val" means LibSVM; otherwise the delimiter is , / tab / space.
// The sniffed line skips the header row when the caller declared one.
int SniffFormat(const char* path, int skip_header, char* delim) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return -1;
  std::string line;
  for (int i = 0; i <= (skip_header ? 1 : 0); ++i) {
    if (!std::getline(f, line)) return -1;
  }
  if (line.find(',') != std::string::npos) { *delim = ','; return 0; }
  // whitespace format: LibSVM iff the second token carries ':'
  const char* p = line.c_str();
  while (*p && !std::isspace((unsigned char)*p)) ++p;   // token 0
  while (*p && std::isspace((unsigned char)*p)) ++p;    // gap
  const char* tok1 = p;
  while (*p && !std::isspace((unsigned char)*p)) ++p;   // token 1
  if (std::memchr(tok1, ':', p - tok1) != nullptr) return 1;
  *delim = line.find('\t') != std::string::npos ? '\t' : ' ';
  return 0;
}

}  // namespace

LGBM_EXPORT int LGBM_BoosterPredictForFile(
    void* handle, const char* data_filename, int data_has_header,
    int predict_type, int start_iteration, int num_iteration,
    const char* parameter, const char* result_filename) {
  if (!handle || !data_filename || !result_filename)
    return Fail("null argument");
  // honored parameters: label_column=N (dense files carry the label at
  // column N, CLI convention; default 0) and no_label=true. Anything
  // else is rejected loudly — silently ignoring a reference parameter
  // would mis-map columns.
  long label_col = 0;
  bool has_label = true;
  if (parameter && *parameter) {
    std::istringstream ps(parameter);
    std::string tok;
    while (ps >> tok) {
      if (tok.rfind("label_column=", 0) == 0) {
        const char* v = tok.c_str() + 13;
        char* endp = nullptr;
        label_col = std::strtol(v, &endp, 10);
        if (endp == v || *endp != '\0' || label_col < 0)
          return Fail("label_column must be a column index (the "
                      "name: syntax needs the Python front end): "
                      + tok);
      } else if (tok == "no_label=true" || tok == "has_label=false") {
        has_label = false;
      } else {
        return Fail("unsupported predict parameter: " + tok);
      }
    }
  }
  auto* b = static_cast<CBooster*>(handle);
  int nfeat = b->max_feature_idx + 1;
  char delim = ',';
  int kind = SniffFormat(data_filename, data_has_header, &delim);
  if (kind < 0)
    return Fail(std::string("cannot read ") + data_filename);
  if (kind == 1 && data_has_header)
    return Fail("LibSVM files have no header line");
  double* X = nullptr;
  double* labels = nullptr;
  long rows = 0, cols = 0;
  int rc;
  if (kind == 1) {
    rc = ParseLibSVM(data_filename, &X, &labels, &rows, &cols);
  } else {
    rc = ParseDense(data_filename, delim, data_has_header ? 1 : 0,
                    &X, &rows, &cols);
  }
  if (rc != 0) {
    return Fail(std::string("cannot parse ") + data_filename);
  }
  // column accounting mirrors the Python predictor: dense files carry
  // the label column (stripped unconditionally unless no_label=true);
  // LibSVM files narrower than the model pad with zeros (sparse
  // semantics: absent means 0). ParseLibSVM already splits labels out.
  int64_t label_at = (kind == 0 && has_label) ? label_col : -1;
  if (label_at >= cols) {
    FreeBuffer(X);
    FreeBuffer(labels);
    return Fail("label_column is out of range for the data file");
  }
  int64_t data_cols = cols - (label_at >= 0 ? 1 : 0);
  if (kind == 0 && data_cols != nfeat) {
    FreeBuffer(X);
    FreeBuffer(labels);
    return Fail("the data file has a different number of features "
                "than the model (see no_label/label_column "
                "parameters)");
  }
  std::vector<double> row(nfeat, 0.0);
  int t0, t1;
  b->UsedRange(start_iteration, num_iteration, &t0, &t1);
  int64_t stride = PredictOutputLen(b, 1, predict_type, t0, t1);
  std::vector<double> out(stride);
  std::ofstream rf(result_filename);
  if (!rf) {
    FreeBuffer(X);
    FreeBuffer(labels);
    return Fail(std::string("cannot write ") + result_filename);
  }
  ShapContext scratch;
  char num[32];
  for (long r = 0; r < rows; ++r) {
    int64_t w = 0;
    for (int64_t c = 0; c < cols && w < nfeat; ++c) {
      if (c == label_at) continue;
      row[w++] = X[r * cols + c];
    }
    for (; w < nfeat; ++w)
      row[w] = (kind == 1) ? 0.0 : std::nan("");
    PredictRowInto(b, row.data(), nfeat, predict_type, t0, t1,
                   out.data(), &scratch);
    for (int64_t j = 0; j < stride; ++j) {
      std::snprintf(num, sizeof(num), "%.17g", out[j]);
      rf << (j ? "\t" : "") << num;
    }
    rf << "\n";
  }
  FreeBuffer(X);
  FreeBuffer(labels);
  rf.flush();
  if (!rf.good())
    return Fail(std::string("write failed: ") + result_filename);
  return 0;
}

// ---------------------------------------------------------------------
// JSON model dump (reference: c_api.cpp LGBM_BoosterDumpModel ->
// GBDT::DumpModel, gbdt_model_text.cpp:21-170) — same structure as the
// Python runtime's dump_model() so R/Java hosts parse one schema.
namespace {

void JsonNum(std::string* out, double v) {
  if (std::isfinite(v)) {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "%.17g", v);
    *out += buf;
  } else {
    *out += v > 0 ? "\"inf\"" : (v < 0 ? "\"-inf\"" : "\"nan\"");
  }
}

void JsonStr(std::string* out, const std::string& s) {
  *out += '"';
  for (char c : s) {
    if (c == '"' || c == '\\') { *out += '\\'; *out += c; }
    else if ((unsigned char)c < 0x20) {
      char buf[8];
      std::snprintf(buf, sizeof(buf), "\\u%04x", c);
      *out += buf;
    } else *out += c;
  }
  *out += '"';
}

double TreeField(const std::vector<double>& a, int i) {
  return i < (int)a.size() ? a[i] : 0.0;
}

void AppendLinearLeaf(const CTree& t, int leaf, std::string* j) {
  *j += ",\"leaf_const\":";
  JsonNum(j, TreeField(t.leaf_const, leaf));
  *j += ",\"leaf_features\":[";
  const auto& feats = t.leaf_features[leaf];
  for (size_t i = 0; i < feats.size(); ++i) {
    if (i) *j += ",";
    *j += std::to_string(feats[i]);
  }
  *j += "],\"leaf_coeff\":[";
  const auto& coef = t.leaf_coeff[leaf];
  for (size_t i = 0; i < coef.size(); ++i) {
    if (i) *j += ",";
    JsonNum(j, coef[i]);
  }
  *j += "]";
}

void NodeToJson(const CTree& t, int index, std::string* out) {
  // iterative post-order with memoized child strings (chain trees can
  // be num_leaves-1 deep; mirror models/tree.py _node_to_json)
  std::map<int, std::string> memo;
  std::vector<int> order, stack{index};
  while (!stack.empty()) {
    int idx = stack.back();
    stack.pop_back();
    order.push_back(idx);
    if (idx >= 0) {
      stack.push_back(t.left_child[idx]);
      stack.push_back(t.right_child[idx]);
    }
  }
  for (auto it = order.rbegin(); it != order.rend(); ++it) {
    int idx = *it;
    std::string j;
    if (idx < 0) {
      int leaf = ~idx;
      j += "{\"leaf_index\":" + std::to_string(leaf) + ",\"leaf_value\":";
      JsonNum(&j, t.leaf_value[leaf]);
      j += ",\"leaf_weight\":";
      JsonNum(&j, TreeField(t.leaf_weight, leaf));
      j += ",\"leaf_count\":"
           + std::to_string((long long)TreeField(t.leaf_count, leaf));
      if (t.is_linear) AppendLinearLeaf(t, leaf, &j);
      j += "}";
    } else {
      int dt = t.decision_type[idx];
      j += "{\"split_index\":" + std::to_string(idx);
      j += ",\"split_feature\":" + std::to_string(t.split_feature[idx]);
      j += ",\"split_gain\":";
      JsonNum(&j, TreeField(t.split_gain, idx));
      j += ",\"threshold\":";
      if (dt & kCategoricalMask) {
        // expand the bitset back to "a||b||c" (reference NodeToJSON)
        int ci = t.threshold_in_bin[idx];
        std::string cats;
        int64_t lo = t.cat_boundaries[ci], hi = t.cat_boundaries[ci + 1];
        for (int64_t w = 0; w < hi - lo; ++w) {
          uint32_t word = t.cat_threshold[lo + w];
          for (int bit = 0; bit < 32; ++bit) {
            if ((word >> bit) & 1u) {
              if (!cats.empty()) cats += "||";
              cats += std::to_string(w * 32 + bit);
            }
          }
        }
        JsonStr(&j, cats);
        j += ",\"decision_type\":\"==\"";
      } else {
        JsonNum(&j, t.threshold[idx]);
        j += ",\"decision_type\":\"<=\"";
      }
      int missing = (dt >> 2) & 3;
      j += std::string(",\"default_left\":")
           + ((dt & kDefaultLeftMask) ? "true" : "false");
      j += std::string(",\"missing_type\":\"")
           + (missing == kMissingZero ? "Zero"
              : missing == kMissingNaN ? "NaN" : "None") + "\"";
      j += ",\"internal_value\":";
      JsonNum(&j, TreeField(t.internal_value, idx));
      j += ",\"internal_weight\":";
      JsonNum(&j, TreeField(t.internal_weight, idx));
      j += ",\"internal_count\":"
           + std::to_string((long long)TreeField(t.internal_count, idx));
      auto lit = memo.find(t.left_child[idx]);
      auto rit = memo.find(t.right_child[idx]);
      j += ",\"left_child\":" + std::move(lit->second);
      j += ",\"right_child\":" + std::move(rit->second);
      memo.erase(lit);
      memo.erase(rit);
      j += "}";
    }
    memo[idx] = std::move(j);
  }
  *out += memo[index];
}

}  // namespace

LGBM_EXPORT int LGBM_BoosterDumpModel(void* handle, int start_iteration,
                                      int num_iteration,
                                      int feature_importance_type,
                                      int64_t buffer_len,
                                      int64_t* out_len, char* out_str) {
  if (!handle || !out_len) return Fail("null argument");
  auto* b = static_cast<CBooster*>(handle);
  int t0, t1;
  b->UsedRange(start_iteration, num_iteration, &t0, &t1);
  std::string j = "{\"name\":\"tree\",\"version\":\"v3\"";
  j += ",\"num_class\":" + std::to_string(b->num_class);
  j += ",\"num_tree_per_iteration\":"
       + std::to_string(b->num_tree_per_iteration);
  j += ",\"label_index\":" + std::to_string(b->label_index);
  j += ",\"max_feature_idx\":" + std::to_string(b->max_feature_idx);
  if (!b->objective_str.empty()) {
    j += ",\"objective\":";
    JsonStr(&j, b->objective_str);
  }
  j += std::string(",\"average_output\":")
       + (b->average_output ? "true" : "false");
  j += ",\"feature_names\":[";
  for (size_t i = 0; i < b->feature_names.size(); ++i) {
    if (i) j += ",";
    JsonStr(&j, b->feature_names[i]);
  }
  j += "],\"feature_infos\":{";
  {
    bool first = true;
    for (size_t i = 0; i < b->feature_infos.size()
                       && i < b->feature_names.size(); ++i) {
      const std::string& info = b->feature_infos[i];
      if (info == "none") continue;
      if (!first) j += ",";
      first = false;
      JsonStr(&j, b->feature_names[i]);
      j += ":{\"min_value\":";
      if (!info.empty() && info.front() == '[') {
        auto colon = info.find(':');
        JsonNum(&j, std::atof(info.substr(1, colon - 1).c_str()));
        j += ",\"max_value\":";
        JsonNum(&j, std::atof(
            info.substr(colon + 1, info.size() - colon - 2).c_str()));
        j += ",\"values\":[]}";
      } else {
        // categorical: colon-separated category values
        std::vector<long> vals;
        std::istringstream vs(info);
        std::string tokv;
        while (std::getline(vs, tokv, ':'))
          vals.push_back(std::atol(tokv.c_str()));
        long mn = vals.empty() ? 0 : *std::min_element(vals.begin(),
                                                       vals.end());
        long mx = vals.empty() ? 0 : *std::max_element(vals.begin(),
                                                       vals.end());
        j += std::to_string(mn) + ",\"max_value\":"
             + std::to_string(mx) + ",\"values\":[";
        for (size_t vI = 0; vI < vals.size(); ++vI) {
          if (vI) j += ",";
          j += std::to_string(vals[vI]);
        }
        j += "]}";
      }
    }
  }
  j += "},\"monotone_constraints\":[";
  for (size_t i = 0; i < b->monotone_constraints.size(); ++i) {
    if (i) j += ",";
    j += std::to_string(b->monotone_constraints[i]);
  }
  j += "],\"tree_info\":[";
  for (int i = t0; i < t1; ++i) {
    if (i > t0) j += ",";
    const CTree& t = b->trees[i];
    j += "{\"tree_index\":" + std::to_string(i - t0);
    j += ",\"num_leaves\":" + std::to_string(t.num_leaves);
    j += ",\"num_cat\":"
         + std::to_string((long long)(t.cat_boundaries.empty()
                                      ? 0 : t.cat_boundaries.size() - 1));
    j += ",\"shrinkage\":";
    JsonNum(&j, t.shrinkage);
    j += ",\"tree_structure\":";
    if (t.num_leaves == 1) {
      j += "{\"leaf_value\":";
      JsonNum(&j, t.leaf_value.empty() ? 0.0 : t.leaf_value[0]);
      if (t.is_linear) AppendLinearLeaf(t, 0, &j);
      j += "}";
    } else {
      NodeToJson(t, 0, &j);
    }
    j += "}";
  }
  j += "],\"feature_importances\":{";
  {
    int nfeat = b->max_feature_idx + 1;
    std::vector<double> imp(nfeat, 0.0);
    // the Python runtime and the reference count from tree 0 through
    // the last used iteration regardless of start_iteration
    for (int i = 0; i < t1; ++i) {
      const CTree& t = b->trees[i];
      for (int k = 0; k < t.num_leaves - 1; ++k) {
        if (t.split_feature[k] < nfeat) {
          imp[t.split_feature[k]] +=
              feature_importance_type == 1
                  ? std::max(TreeField(t.split_gain, k), 0.0)
                  : 1.0;
        }
      }
    }
    bool first = true;
    for (int f = 0; f < nfeat && f < (int)b->feature_names.size(); ++f) {
      if (imp[f] <= 0) continue;
      if (!first) j += ",";
      first = false;
      JsonStr(&j, b->feature_names[f]);
      j += ":";
      if (feature_importance_type == 1) JsonNum(&j, imp[f]);
      else j += std::to_string((long long)imp[f]);
    }
  }
  j += "}}";
  *out_len = static_cast<int64_t>(j.size()) + 1;
  if (out_str && buffer_len >= *out_len)
    std::memcpy(out_str, j.c_str(), *out_len);
  return 0;
}
