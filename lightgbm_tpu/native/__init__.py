"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its data-loading runtime in C++ (src/io/parser.cpp,
src/io/dataset_loader.cpp); this package holds the TPU build's native
equivalents. Sources compile on first use with the system g++ into a
cached shared object next to the source (no pybind11 dependency — plain
C ABI + ctypes), and every entry point has a NumPy fallback so a missing
toolchain degrades gracefully.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def compile_and_load(src_name: str, so_name: str) -> ctypes.CDLL:
    """Compile a C++ source in this directory into a cached shared
    object (rebuilt when the source is newer) and dlopen it. Shared by
    every native component; raises on a missing/broken toolchain (each
    caller decides how to degrade). The .tmp rename keeps a concurrent
    builder in another process from dlopening a half-written file."""
    src = os.path.join(_HERE, src_name)
    so = os.path.join(_HERE, so_name)
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        tmp = so + ".%d.tmp" % os.getpid()
        subprocess.check_call(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, src],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        os.replace(tmp, so)
    return ctypes.CDLL(so)


def _build_and_load() -> Optional[ctypes.CDLL]:
    """Load the parser library via compile_and_load, binding signatures.
    Returns None when no working toolchain is available."""
    global _LIB, _LIB_FAILED
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        try:
            lib = compile_and_load("parser.cpp", "_parser.so")
            lib.ParseDense.restype = ctypes.c_int
            lib.ParseDense.argtypes = [
                ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long)]
            lib.ParseLibSVM.restype = ctypes.c_int
            lib.ParseLibSVM.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long)]
            lib.FreeBuffer.restype = None
            lib.FreeBuffer.argtypes = [ctypes.c_void_p]
            lib.GreedyFindBin.restype = ctypes.c_int
            lib.GreedyFindBin.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_long, ctypes.c_int, ctypes.c_double,
                ctypes.c_int, ctypes.POINTER(ctypes.c_double)]
            _LIB = lib
        except Exception:
            _LIB_FAILED = True
            from ..utils import log
            log.warning("native parser unavailable (g++ build failed); "
                        "falling back to numpy text parsing")
        return _LIB


def parse_dense(path: str, delim: str, skip_rows: int
                ) -> Optional[np.ndarray]:
    """Parse a CSV/TSV file into a row-major float64 array, or None if
    the native library is unavailable (caller falls back to numpy)."""
    lib = _build_and_load()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_double)()
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    rc = lib.ParseDense(path.encode(), delim.encode(), skip_rows,
                        ctypes.byref(out), ctypes.byref(rows),
                        ctypes.byref(cols))
    if rc != 0:
        if rc == 1:
            raise OSError("cannot read %s" % path)
        return None
    try:
        n = rows.value * cols.value
        arr = np.ctypeslib.as_array(out, shape=(n,)).copy()
        return arr.reshape(rows.value, cols.value)
    finally:
        lib.FreeBuffer(out)


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    max_bin: int, total_cnt: int,
                    min_data_in_bin: int) -> Optional[np.ndarray]:
    """Native GreedyFindBin (reference: src/io/bin.cpp:78) — returns the
    bin upper bounds, or None when the native library is unavailable
    (caller falls back to the Python implementation)."""
    lib = _build_and_load()
    if lib is None:
        return None
    dv = np.ascontiguousarray(distinct_values, dtype=np.float64)
    cn = np.ascontiguousarray(counts, dtype=np.float64)
    out = np.empty(max(max_bin, 1) + 1, dtype=np.float64)
    n = lib.GreedyFindBin(
        dv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        cn.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_long(len(dv)), ctypes.c_int(int(max_bin)),
        ctypes.c_double(float(total_cnt)),
        ctypes.c_int(int(min_data_in_bin)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out[:n].copy()


def parse_libsvm(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse a LibSVM file → (dense X, labels), or None if unavailable."""
    lib = _build_and_load()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_double)()
    labels = ctypes.POINTER(ctypes.c_double)()
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    rc = lib.ParseLibSVM(path.encode(), ctypes.byref(out),
                         ctypes.byref(labels), ctypes.byref(rows),
                         ctypes.byref(cols))
    if rc != 0:
        if rc == 1:
            raise OSError("cannot read %s" % path)
        return None
    try:
        n = rows.value * cols.value
        X = np.ctypeslib.as_array(out, shape=(n,)).copy() \
            .reshape(rows.value, cols.value)
        y = np.ctypeslib.as_array(labels, shape=(rows.value,)).copy()
        return X, y
    finally:
        lib.FreeBuffer(out)
        lib.FreeBuffer(labels)
