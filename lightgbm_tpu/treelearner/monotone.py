"""Intermediate monotone-constraint tracking (host side).

Equivalent of the reference's ``IntermediateLeafConstraints``
(src/treelearner/monotone_constraints.hpp:508-855): per-leaf (min, max)
output bounds that, unlike ``basic`` mode, are tightened with the actual
sibling outputs instead of the mid-point, and are *propagated* to every
other leaf that is value-contiguous with the new children (found by
walking up from the split node and down the opposite branches). Each
touched leaf's best-split candidate is then recomputed — on the device,
from its stored histogram (reference:
SerialTreeLearner::RecomputeBestSplitForLeaf,
serial_tree_learner.cpp:800).

The tree-walk itself is pure O(num_leaves) pointer chasing over the host
``Tree``, so it stays in Python; only the rescans run on device.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.tree import Tree, kCategoricalMask

_INF = float("inf")


class IntermediateMonotoneTracker:
    """Host mirror of per-leaf output bounds + the contiguity walk."""

    def __init__(self, num_leaves: int, monotone_inner: np.ndarray):
        self.L = num_leaves
        self.mono = np.asarray(monotone_inner, dtype=np.int8)
        self.reset()

    def reset(self) -> None:
        self.entries: List[Tuple[float, float]] = \
            [(-_INF, _INF) for _ in range(self.L)]
        self.in_mono_subtree = [False] * self.L
        # node_parent_[node] — parent internal node of each internal node
        self.node_parent = [-1] * max(self.L - 1, 1)

    # ------------------------------------------------------------------
    def before_split(self, tree: Tree, leaf: int, mono_type: int) -> None:
        """reference: IntermediateLeafConstraints::BeforeSplit
        (monotone_constraints.hpp:530) — must run BEFORE the split is
        applied to the host tree (it records the pre-split leaf parent
        as the new node's parent)."""
        new_leaf = tree.num_leaves
        new_node = tree.num_leaves - 1
        if mono_type != 0 or self.in_mono_subtree[leaf]:
            self.in_mono_subtree[leaf] = True
            self.in_mono_subtree[new_leaf] = True
        self.node_parent[new_node] = int(tree.leaf_parent[leaf])

    def child_bounds(self, leaf: int, mono_type: int,
                     left_output: float, right_output: float
                     ) -> Tuple[float, float, float, float]:
        """Bounds the two children inherit + the entry updates
        (reference: UpdateConstraintsWithOutputs,
        monotone_constraints.hpp:543 — sibling outputs, not mid-points).
        Returns (lmin, lmax, rmin, rmax)."""
        pmin, pmax = self.entries[leaf]
        lmin, lmax = pmin, pmax
        rmin, rmax = pmin, pmax
        if mono_type < 0:
            lmin = max(lmin, right_output)   # left ≥ right for decreasing
            rmax = min(rmax, left_output)
        elif mono_type > 0:
            lmax = min(lmax, right_output)
            rmin = max(rmin, left_output)
        return lmin, lmax, rmin, rmax

    def apply_split(self, tree: Tree, leaf: int, new_leaf: int,
                    bounds: Tuple[float, float, float, float]) -> None:
        self.entries[leaf] = (bounds[0], bounds[1])
        self.entries[new_leaf] = (bounds[2], bounds[3])

    # ------------------------------------------------------------------
    def _update_leaf_bound(self, leaf: int, update_max: bool,
                           lo: float, hi: float, out: List[int]) -> None:
        """UpdateMin/MaxAndReturnBoolIfChanged
        (monotone_constraints.hpp:74-88); the advanced tracker overrides
        this with mark-dirty semantics."""
        emin, emax = self.entries[leaf]
        if update_max:
            if lo < emax:
                self.entries[leaf] = (emin, lo)
                out.append(leaf)
        else:
            if hi > emin:
                self.entries[leaf] = (hi, emax)
                out.append(leaf)

    # ------------------------------------------------------------------
    def leaves_to_update(self, tree: Tree, new_leaf: int,
                         split_feature_inner: int, split_threshold: int,
                         left_output: float, right_output: float,
                         is_numerical: bool,
                         leaf_has_candidate) -> List[int]:
        """The GoUp/GoDown walk (reference: GoUpToFindLeavesToUpdate /
        GoDownToFindLeavesToUpdate, monotone_constraints.hpp:620-805).
        ``leaf_has_candidate(leaf) -> bool`` mirrors the reference's
        ``best_split_per_leaf[leaf].gain == kMinScore`` skip. Updates
        ``self.entries`` in place; returns the leaves needing a device
        rescan."""
        out: List[int] = []
        if not self.in_mono_subtree[new_leaf]:
            return out
        feats_up: List[int] = []
        thr_up: List[int] = []
        was_right: List[bool] = []

        node = int(tree.leaf_parent[new_leaf])
        child_code = node  # start: the new split node (walk begins above)
        parent = self.node_parent[node] if node >= 0 else -1
        while parent != -1:
            inner = int(tree.split_feature_inner[parent])
            mono_type = int(self.mono[inner]) \
                if inner < len(self.mono) else 0
            is_right = int(tree.right_child[parent]) == child_code
            p_numerical = not (int(tree.decision_type[parent])
                               & kCategoricalMask)
            # OppositeChildShouldBeUpdated (monotone_constraints.hpp:589).
            # NOTE: the reference's comment claims categorical ancestors
            # should still be descended, but its code returns false for
            # them (the `else` branch); behavior parity follows the code.
            should = p_numerical and not any(
                f == inner and wr == is_right
                for f, wr in zip(feats_up, was_right))
            if should:
                if mono_type != 0:
                    left_c = int(tree.left_child[parent])
                    right_c = int(tree.right_child[parent])
                    curr_is_left = left_c == child_code
                    opposite = right_c if curr_is_left else left_c
                    update_max = (curr_is_left if mono_type < 0
                                  else not curr_is_left)
                    self._go_down(tree, opposite, feats_up, thr_up,
                                  was_right, update_max,
                                  split_feature_inner, split_threshold,
                                  left_output, right_output, True, True,
                                  is_numerical, leaf_has_candidate, out)
                was_right.append(is_right)
                thr_up.append(int(tree.threshold_in_bin[parent]))
                feats_up.append(inner)
            child_code = parent
            parent = self.node_parent[parent]
        return out

    def _go_down(self, tree: Tree, node: int, feats_up, thr_up, was_right,
                 update_max: bool, split_feature: int,
                 split_threshold: int, left_output: float,
                 right_output: float, use_left: bool, use_right: bool,
                 split_is_numerical: bool, leaf_has_candidate,
                 out: List[int]) -> None:
        if node < 0:
            leaf = ~node
            if not leaf_has_candidate(leaf):
                return
            if use_left and use_right:
                lo, hi = sorted((left_output, right_output))
            elif use_right:
                lo = hi = right_output
            else:
                lo = hi = left_output
            self._update_leaf_bound(leaf, update_max, lo, hi, out)
            return
        # ShouldKeepGoingLeftRight (monotone_constraints.hpp:806)
        inner = int(tree.split_feature_inner[node])
        thr = int(tree.threshold_in_bin[node])
        n_numerical = not (int(tree.decision_type[node])
                           & kCategoricalMask)
        keep_left = keep_right = True
        if n_numerical:
            for f, t, wr in zip(feats_up, thr_up, was_right):
                if f == inner:
                    if thr >= t and not wr:
                        keep_right = False
                    if thr <= t and wr:
                        keep_left = False
        use_left_for_right = True
        use_right_for_left = True
        if n_numerical and inner == split_feature and split_is_numerical:
            if thr >= split_threshold:
                use_left_for_right = False
            if thr <= split_threshold:
                use_right_for_left = False
        if keep_left:
            self._go_down(tree, int(tree.left_child[node]), feats_up,
                          thr_up, was_right, update_max, split_feature,
                          split_threshold, left_output, right_output,
                          use_left, use_right and use_right_for_left,
                          split_is_numerical, leaf_has_candidate, out)
        if keep_right:
            self._go_down(tree, int(tree.right_child[node]), feats_up,
                          thr_up, was_right, update_max, split_feature,
                          split_threshold, left_output, right_output,
                          use_left and use_left_for_right, use_right,
                          split_is_numerical, leaf_has_candidate, out)


class AdvancedMonotoneTracker(IntermediateMonotoneTracker):
    """monotone_constraints_method=advanced ("monotone precise mode").

    Equivalent of the reference's ``AdvancedLeafConstraints``
    (src/treelearner/monotone_constraints.hpp:856-1184): each leaf
    carries *per-feature, per-threshold* output constraints, so a
    candidate split is clamped only by the leaves actually contiguous
    with each child, not by a leaf-wide bound. The reference stores
    these as sorted (thresholds[], constraints[]) piece lists merged by
    ``UpdateConstraints`` (:870-968); here each (leaf, feature) holds a
    DENSE f32[B] array over the feature's bin axis — a range update is a
    vectorized ``np.maximum`` on a slice, piece bookkeeping disappears,
    and the arrays ship to the device split scan as-is
    (``find_best_split(bound_arrays=...)`` computes the running-extrema
    left/right clamps the reference keeps in
    ``CumulativeFeatureConstraint``).

    Laziness matches the reference: propagation
    (``UpdateMin/MaxAndReturnBoolIfChanged``) flat-updates the arrays and
    marks the touched side dirty for every feature; the dirty side is
    rebuilt from the tree on next use (``RecomputeConstraintsIfNeeded``,
    :375-430) by the up-then-down walk over constraining leaves
    (``GoUpToFindConstrainingLeaves`` / ``GoDown...``, :1027-1184).
    Reference quirk kept for parity: when BOTH sides are dirty only the
    min side is recomputed, and both flags clear (:385-393).
    """

    def __init__(self, num_leaves: int, monotone_inner: np.ndarray,
                 num_bin: np.ndarray, B: int):
        self.B = int(B)
        self.num_bin = np.asarray(num_bin, dtype=np.int64)
        super().__init__(num_leaves, monotone_inner)

    def reset(self) -> None:
        super().reset()
        Fp = len(self.mono)
        # dense per-(leaf, feature, bin) constraints; pads stay ±inf so
        # device-side reverse cumulative extrema are neutral there
        self.min_c = np.full((self.L, Fp, self.B), -_INF, dtype=np.float32)
        self.max_c = np.full((self.L, Fp, self.B), _INF, dtype=np.float32)
        self.min_dirty = np.zeros((self.L, Fp), dtype=bool)
        self.max_dirty = np.zeros((self.L, Fp), dtype=bool)
        # valid-bin mask per feature — flat updates must not disturb the
        # ±inf pads
        Fp_ = len(self.mono)
        cols = np.arange(self.B)[None, :]
        self._valid = cols < self.num_bin[:Fp_, None]        # [Fp, B]

    # -- entry ops (AdvancedConstraintEntry, monotone_constraints.hpp:375)
    def _flat_update_min(self, leaf: int, v: float) -> None:
        row = self.min_c[leaf]
        np.maximum(row, np.float32(v), out=row, where=self._valid)

    def _flat_update_max(self, leaf: int, v: float) -> None:
        row = self.max_c[leaf]
        np.minimum(row, np.float32(v), out=row, where=self._valid)

    def apply_split_outputs(self, leaf: int, new_leaf: int,
                            mono_type: int, left_output: float,
                            right_output: float,
                            is_numerical: bool) -> None:
        """UpdateConstraintsWithOutputs (monotone_constraints.hpp:543):
        clone the entry to the new leaf, then flat-tighten both with the
        actual sibling outputs."""
        self.min_c[new_leaf] = self.min_c[leaf]
        self.max_c[new_leaf] = self.max_c[leaf]
        self.min_dirty[new_leaf] = self.min_dirty[leaf]
        self.max_dirty[new_leaf] = self.max_dirty[leaf]
        if not is_numerical:
            return
        if mono_type < 0:
            self._flat_update_min(leaf, right_output)
            self._flat_update_max(new_leaf, left_output)
        elif mono_type > 0:
            self._flat_update_max(leaf, right_output)
            self._flat_update_min(new_leaf, left_output)

    def _update_leaf_bound(self, leaf: int, update_max: bool,
                           lo: float, hi: float, out: List[int]) -> None:
        """Advanced semantics (UpdateMin/MaxAndReturnBoolIfChanged,
        monotone_constraints.hpp:440-456): flat-update + mark the side
        dirty on every feature, and ALWAYS report the leaf as needing a
        rescan — even an unchanged flat bound may have been derived from
        a stale walk."""
        if update_max:
            self._flat_update_max(leaf, lo)
            self.max_dirty[leaf, :] = True
        else:
            self._flat_update_min(leaf, hi)
            self.min_dirty[leaf, :] = True
        out.append(leaf)

    # -- lazy recompute (RecomputeConstraintsIfNeeded, :375-430) -------
    def _recompute_if_needed(self, tree: Tree, leaf: int, f: int) -> None:
        if not (self.min_dirty[leaf, f] or self.max_dirty[leaf, f]):
            return
        min_update = bool(self.min_dirty[leaf, f])
        nb = int(self.num_bin[f]) if f < len(self.num_bin) else self.B
        if min_update:
            self.min_c[leaf, f, :nb] = -_INF
        else:
            self.max_c[leaf, f, :nb] = _INF
        self._go_up_constraining(tree, f, ~leaf, [], [], [],
                                 min_update, 0, nb, nb)
        self.min_dirty[leaf, f] = False
        self.max_dirty[leaf, f] = False

    def leaf_bound_arrays(self, tree: Tree, leaf: int
                          ) -> Tuple[np.ndarray, np.ndarray]:
        """The [Fp, B] (min_c, max_c) pair for a leaf's split scan, with
        every numerical feature's dirty side rebuilt first (the
        reference recomputes per feature right before FindBestThreshold,
        serial_tree_learner.cpp:758-762)."""
        for f in range(len(self.mono)):
            self._recompute_if_needed(tree, leaf, f)
        return self.min_c[leaf], self.max_c[leaf]

    # -- the up-then-down constraining-leaf walk -----------------------
    def _range_update(self, f: int, min_update: bool, extremum: float,
                      it_start: int, it_end: int, node_leaf: int) -> None:
        """UpdateConstraints (monotone_constraints.hpp:870-968) on the
        dense row: the piece-list insertion/merge collapses to a slice
        extremum."""
        if it_start >= it_end:
            return
        if min_update:
            row = self.min_c[self._target_leaf, f, it_start:it_end]
            np.maximum(row, np.float32(extremum), out=row)
        else:
            row = self.max_c[self._target_leaf, f, it_start:it_end]
            np.minimum(row, np.float32(extremum), out=row)

    def _go_up_constraining(self, tree: Tree, f_c: int, node_idx: int,
                            feats_up: List[int], thr_up: List[int],
                            was_right: List[bool], min_update: bool,
                            it_start: int, it_end: int,
                            last_threshold: int) -> None:
        """GoUpToFindConstrainingLeaves (monotone_constraints.hpp:1083)."""
        if node_idx < 0:
            self._target_leaf = ~node_idx
            parent = int(tree.leaf_parent[~node_idx])
        else:
            parent = self.node_parent[node_idx]
        if parent == -1:
            return
        inner = int(tree.split_feature_inner[parent])
        mono_type = int(self.mono[inner]) if inner < len(self.mono) else 0
        is_right = int(tree.right_child[parent]) == node_idx
        is_numerical = not (int(tree.decision_type[parent])
                            & kCategoricalMask)
        threshold = int(tree.threshold_in_bin[parent])
        if f_c == inner and is_numerical:
            # note the reference's asymmetry: right child widens only to
            # `threshold`, not threshold+1 (monotone_constraints.hpp:1100)
            if is_right:
                it_start = max(threshold, it_start)
            else:
                it_end = min(threshold + 1, it_end)
        should = self._opposite_should_update(is_numerical, feats_up,
                                              inner, was_right, is_right)
        if should:
            if mono_type != 0:
                left_c = int(tree.left_child[parent])
                right_c = int(tree.right_child[parent])
                curr_is_left = left_c == node_idx
                update_min_in_curr = (curr_is_left if mono_type < 0
                                      else not curr_is_left)
                if update_min_in_curr == min_update:
                    opposite = right_c if curr_is_left else left_c
                    self._go_down_constraining(
                        tree, f_c, inner, opposite, min_update,
                        it_start, it_end, feats_up, thr_up, was_right,
                        last_threshold)
            was_right.append(is_right)
            thr_up.append(threshold)
            feats_up.append(inner)
        if parent != 0:
            self._go_up_constraining(tree, f_c, parent, feats_up, thr_up,
                                     was_right, min_update, it_start,
                                     it_end, last_threshold)

    @staticmethod
    def _opposite_should_update(is_numerical: bool, feats_up, inner,
                                was_right, is_right) -> bool:
        """OppositeChildShouldBeUpdated (monotone_constraints.hpp:589)."""
        if not is_numerical:
            return False
        return not any(f == inner and wr == is_right
                       for f, wr in zip(feats_up, was_right))

    def _go_down_constraining(self, tree: Tree, f_c: int,
                              root_mono_f: int, node: int,
                              min_update: bool, it_start: int,
                              it_end: int, feats_up, thr_up, was_right,
                              last_threshold: int) -> None:
        """GoDownToFindConstrainingLeaves (monotone_constraints.hpp:1000)."""
        if node < 0:
            extremum = float(tree.leaf_value[~node])
            self._range_update(f_c, min_update, extremum, it_start,
                               it_end, node)
            return
        inner = int(tree.split_feature_inner[node])
        threshold = int(tree.threshold_in_bin[node])
        n_numerical = not (int(tree.decision_type[node])
                           & kCategoricalMask)
        # ShouldKeepGoingLeftRight (monotone_constraints.hpp:806)
        keep_left = keep_right = True
        if n_numerical:
            for f, t, wr in zip(feats_up, thr_up, was_right):
                if f == inner:
                    if threshold >= t and not wr:
                        keep_right = False
                    if threshold <= t and wr:
                        keep_left = False
        split_is_inner = inner == f_c
        split_is_mono_root = root_mono_f == f_c
        # LeftRightContainsRelevantInformation (:975-998)
        contains_left = contains_right = True
        if not (split_is_inner and not split_is_mono_root):
            m = int(self.mono[inner]) if inner < len(self.mono) else 0
            if m != 0:
                if (m == -1 and min_update) or (m == 1 and not min_update):
                    contains_right = False
                else:
                    contains_left = False
        if keep_left and (contains_left or not keep_right):
            new_end = min(threshold + 1, it_end) if (split_is_inner
                                                     and n_numerical) \
                else it_end
            self._go_down_constraining(
                tree, f_c, root_mono_f, int(tree.left_child[node]),
                min_update, it_start, new_end, feats_up, thr_up,
                was_right, last_threshold)
        if keep_right and (contains_right or not keep_left):
            new_start = max(threshold + 1, it_start) if (split_is_inner
                                                         and n_numerical) \
                else it_start
            self._go_down_constraining(
                tree, f_c, root_mono_f, int(tree.right_child[node]),
                min_update, new_start, it_end, feats_up, thr_up,
                was_right, last_threshold)
