"""Intermediate monotone-constraint tracking (host side).

Equivalent of the reference's ``IntermediateLeafConstraints``
(src/treelearner/monotone_constraints.hpp:508-855): per-leaf (min, max)
output bounds that, unlike ``basic`` mode, are tightened with the actual
sibling outputs instead of the mid-point, and are *propagated* to every
other leaf that is value-contiguous with the new children (found by
walking up from the split node and down the opposite branches). Each
touched leaf's best-split candidate is then recomputed — on the device,
from its stored histogram (reference:
SerialTreeLearner::RecomputeBestSplitForLeaf,
serial_tree_learner.cpp:800).

The tree-walk itself is pure O(num_leaves) pointer chasing over the host
``Tree``, so it stays in Python; only the rescans run on device.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.tree import Tree, kCategoricalMask

_INF = float("inf")


class IntermediateMonotoneTracker:
    """Host mirror of per-leaf output bounds + the contiguity walk."""

    def __init__(self, num_leaves: int, monotone_inner: np.ndarray):
        self.L = num_leaves
        self.mono = np.asarray(monotone_inner, dtype=np.int8)
        self.reset()

    def reset(self) -> None:
        self.entries: List[Tuple[float, float]] = \
            [(-_INF, _INF) for _ in range(self.L)]
        self.in_mono_subtree = [False] * self.L
        # node_parent_[node] — parent internal node of each internal node
        self.node_parent = [-1] * max(self.L - 1, 1)

    # ------------------------------------------------------------------
    def before_split(self, tree: Tree, leaf: int, mono_type: int) -> None:
        """reference: IntermediateLeafConstraints::BeforeSplit
        (monotone_constraints.hpp:530) — must run BEFORE the split is
        applied to the host tree (it records the pre-split leaf parent
        as the new node's parent)."""
        new_leaf = tree.num_leaves
        new_node = tree.num_leaves - 1
        if mono_type != 0 or self.in_mono_subtree[leaf]:
            self.in_mono_subtree[leaf] = True
            self.in_mono_subtree[new_leaf] = True
        self.node_parent[new_node] = int(tree.leaf_parent[leaf])

    def child_bounds(self, leaf: int, mono_type: int,
                     left_output: float, right_output: float
                     ) -> Tuple[float, float, float, float]:
        """Bounds the two children inherit + the entry updates
        (reference: UpdateConstraintsWithOutputs,
        monotone_constraints.hpp:543 — sibling outputs, not mid-points).
        Returns (lmin, lmax, rmin, rmax)."""
        pmin, pmax = self.entries[leaf]
        lmin, lmax = pmin, pmax
        rmin, rmax = pmin, pmax
        if mono_type < 0:
            lmin = max(lmin, right_output)   # left ≥ right for decreasing
            rmax = min(rmax, left_output)
        elif mono_type > 0:
            lmax = min(lmax, right_output)
            rmin = max(rmin, left_output)
        return lmin, lmax, rmin, rmax

    def apply_split(self, tree: Tree, leaf: int, new_leaf: int,
                    bounds: Tuple[float, float, float, float]) -> None:
        self.entries[leaf] = (bounds[0], bounds[1])
        self.entries[new_leaf] = (bounds[2], bounds[3])

    # ------------------------------------------------------------------
    def leaves_to_update(self, tree: Tree, new_leaf: int,
                         split_feature_inner: int, split_threshold: int,
                         left_output: float, right_output: float,
                         is_numerical: bool,
                         leaf_has_candidate) -> List[int]:
        """The GoUp/GoDown walk (reference: GoUpToFindLeavesToUpdate /
        GoDownToFindLeavesToUpdate, monotone_constraints.hpp:620-805).
        ``leaf_has_candidate(leaf) -> bool`` mirrors the reference's
        ``best_split_per_leaf[leaf].gain == kMinScore`` skip. Updates
        ``self.entries`` in place; returns the leaves needing a device
        rescan."""
        out: List[int] = []
        if not self.in_mono_subtree[new_leaf]:
            return out
        feats_up: List[int] = []
        thr_up: List[int] = []
        was_right: List[bool] = []

        node = int(tree.leaf_parent[new_leaf])
        child_code = node  # start: the new split node (walk begins above)
        parent = self.node_parent[node] if node >= 0 else -1
        while parent != -1:
            inner = int(tree.split_feature_inner[parent])
            mono_type = int(self.mono[inner]) \
                if inner < len(self.mono) else 0
            is_right = int(tree.right_child[parent]) == child_code
            p_numerical = not (int(tree.decision_type[parent])
                               & kCategoricalMask)
            # OppositeChildShouldBeUpdated (monotone_constraints.hpp:589).
            # NOTE: the reference's comment claims categorical ancestors
            # should still be descended, but its code returns false for
            # them (the `else` branch); behavior parity follows the code.
            should = p_numerical and not any(
                f == inner and wr == is_right
                for f, wr in zip(feats_up, was_right))
            if should:
                if mono_type != 0:
                    left_c = int(tree.left_child[parent])
                    right_c = int(tree.right_child[parent])
                    curr_is_left = left_c == child_code
                    opposite = right_c if curr_is_left else left_c
                    update_max = (curr_is_left if mono_type < 0
                                  else not curr_is_left)
                    self._go_down(tree, opposite, feats_up, thr_up,
                                  was_right, update_max,
                                  split_feature_inner, split_threshold,
                                  left_output, right_output, True, True,
                                  is_numerical, leaf_has_candidate, out)
                was_right.append(is_right)
                thr_up.append(int(tree.threshold_in_bin[parent]))
                feats_up.append(inner)
            child_code = parent
            parent = self.node_parent[parent]
        return out

    def _go_down(self, tree: Tree, node: int, feats_up, thr_up, was_right,
                 update_max: bool, split_feature: int,
                 split_threshold: int, left_output: float,
                 right_output: float, use_left: bool, use_right: bool,
                 split_is_numerical: bool, leaf_has_candidate,
                 out: List[int]) -> None:
        if node < 0:
            leaf = ~node
            if not leaf_has_candidate(leaf):
                return
            if use_left and use_right:
                lo, hi = sorted((left_output, right_output))
            elif use_right:
                lo = hi = right_output
            else:
                lo = hi = left_output
            emin, emax = self.entries[leaf]
            # UpdateMin/MaxAndReturnBoolIfChanged
            # (monotone_constraints.hpp:74-88)
            if update_max:
                if lo < emax:
                    self.entries[leaf] = (emin, lo)
                    out.append(leaf)
            else:
                if hi > emin:
                    self.entries[leaf] = (hi, emax)
                    out.append(leaf)
            return
        # ShouldKeepGoingLeftRight (monotone_constraints.hpp:806)
        inner = int(tree.split_feature_inner[node])
        thr = int(tree.threshold_in_bin[node])
        n_numerical = not (int(tree.decision_type[node])
                           & kCategoricalMask)
        keep_left = keep_right = True
        if n_numerical:
            for f, t, wr in zip(feats_up, thr_up, was_right):
                if f == inner:
                    if thr >= t and not wr:
                        keep_right = False
                    if thr <= t and wr:
                        keep_left = False
        use_left_for_right = True
        use_right_for_left = True
        if n_numerical and inner == split_feature and split_is_numerical:
            if thr >= split_threshold:
                use_left_for_right = False
            if thr <= split_threshold:
                use_right_for_left = False
        if keep_left:
            self._go_down(tree, int(tree.left_child[node]), feats_up,
                          thr_up, was_right, update_max, split_feature,
                          split_threshold, left_output, right_output,
                          use_left, use_right and use_right_for_left,
                          split_is_numerical, leaf_has_candidate, out)
        if keep_right:
            self._go_down(tree, int(tree.right_child[node]), feats_up,
                          thr_up, was_right, update_max, split_feature,
                          split_threshold, left_output, right_output,
                          use_left and use_left_for_right, use_right,
                          split_is_numerical, leaf_has_candidate, out)
